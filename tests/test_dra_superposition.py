"""DRA per-instance-type requirement superposition + allocator depth specs.

Reference: allocator.go:90-134 (ResourceClaimAllocationMetadata /
ContributedRequirements / pruning of intersection-emptying instance types)
and allocator_test.go's constraint-interaction, rollback, and exhaustion
families."""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_tpu.controllers.provisioning.scheduling import Scheduler
from karpenter_tpu.kube import Device, DeviceClass, ObjectMeta, ResourceClaim, ResourceSlice, Store
from karpenter_tpu.scheduling.dynamicresources import Allocator
from karpenter_tpu.scheduling.dynamicresources.allocator import (
    AllocationTracker,
    ClaimAllocationMetadata,
    requirements_from_picks,
)
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.resources import parse_resource_list

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def zoned_gpu(name, zones, model="a100"):
    """A template device only available in the given zones: selecting it pins
    the launched node's zone (the superposition contribution)."""
    return Device(
        name=name,
        attributes={"gpu.example.com/model": model},
        capacity=parse_resource_list({"memory": "40Gi"}),
        requirements=[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": list(zones)}],
    )


def gpu_it(name, devices, zones=("test-zone-a", "test-zone-b"), price=10.0):
    return InstanceType(
        name=name,
        requirements=Requirements.from_labels({
            wk.INSTANCE_TYPE_LABEL_KEY: name,
            wk.ARCH_LABEL_KEY: "amd64",
            wk.OS_LABEL_KEY: "linux",
        }),
        offerings=[
            Offering(
                requirements=Requirements.from_labels({
                    wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_ON_DEMAND,
                    wk.ZONE_LABEL_KEY: z,
                }),
                price=price,
            )
            for z in zones
        ],
        capacity=parse_resource_list({"cpu": "8", "memory": "32Gi", "pods": "110"}),
        dynamic_resources=devices,
    )


def gpu_claim(name, count=1, model=None, ns="default", constraints=None):
    sel = [{"attribute": "model", "operator": "In", "values": [model]}] if model else []
    req = {"name": "gpus", "deviceClassName": "gpu-class", "count": count}
    if sel:
        req["selectors"] = sel
    return ResourceClaim(metadata=ObjectMeta(name=name, namespace=ns), requests=[req], constraints=constraints or [])


def claim_pod(name, *claim_names, **kw):
    pod = make_pod(name=name, **kw)
    pod.spec.resource_claims = [{"name": f"c{i}", "resourceClaimName": c} for i, c in enumerate(claim_names)]
    return pod


def build_store():
    store, clock = Store(), FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    store.create(DeviceClass(metadata=ObjectMeta(name="gpu-class"), selectors=[{"attribute": "model", "operator": "Exists"}]))
    return store, clock, cluster


def scheduler_for(store, cluster, clock, types):
    np = make_nodepool(requirements=LINUX_AMD64)
    store.create(np)
    return Scheduler(store, cluster, [np], {"default-pool": types}, cluster.nodes(), [], clock, dra_enabled=True)


class TestRequirementsFromPicks:
    def test_device_requirements_intersect(self):
        from karpenter_tpu.scheduling.dynamicresources.allocator import _DeviceRef

        d1 = zoned_gpu("g1", ["test-zone-a", "test-zone-b"])
        d2 = zoned_gpu("g2", ["test-zone-b", "test-zone-c"])
        picks = [
            ("gpus", _DeviceRef(device=d1, driver="t", pool="p", device_id=("template", "it", "p", "g1")), None),
            ("gpus", _DeviceRef(device=d2, driver="t", pool="p", device_id=("template", "it", "p", "g2")), None),
        ]
        reqs = requirements_from_picks(picks)
        zr = reqs.get(wk.ZONE_LABEL_KEY)
        assert set(zr.values) == {"test-zone-b"}, "both devices land on ONE node: zones intersect"

    def test_unconstrained_devices_contribute_nothing(self):
        from karpenter_tpu.scheduling.dynamicresources.allocator import _DeviceRef

        d = Device(name="g", attributes={"gpu.example.com/model": "a100"}, capacity={})
        picks = [("gpus", _DeviceRef(device=d, driver="t", pool="p", device_id=("template", "it", "p", "g")), None)]
        assert len(requirements_from_picks(picks).values()) == 0


class TestSuperposition:
    def _alloc(self, store, clock):
        return Allocator(store, clock)

    def test_contributions_recorded_per_instance_type(self):
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        per_it = {}
        for it in (gpu_it("it-a", [zoned_gpu("g", ["test-zone-a"])]),
                   gpu_it("it-b", [zoned_gpu("g", ["test-zone-a", "test-zone-b"])])):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-a", "it-b"}
        meta = metas[rc.key()]
        assert meta.used_template_devices and meta.node_claim_id == "nc-1"
        assert set(meta.contributed) == {"it-a", "it-b"}
        # pessimistic intersection: zone-a only (allocator.go's zone example)
        assert set(meta.total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a"}

    def test_intersection_emptying_type_is_pruned(self):
        # allocator.go:118-124: it-a contributes zone IN a; it-b would
        # contribute zone IN b -> empty intersection -> it-b pruned
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        per_it = {}
        for it in (gpu_it("it-a", [zoned_gpu("g", ["test-zone-a"])]),
                   gpu_it("it-b", [zoned_gpu("g", ["test-zone-b"])])):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-a"}, "evaluation order wins; the emptier prunes"
        assert set(metas[rc.key()].contributed) == {"it-a"}

    def test_pruning_is_order_dependent_like_reference(self):
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        per_it = {}
        for it in (gpu_it("it-b", [zoned_gpu("g", ["test-zone-b"])]),
                   gpu_it("it-a", [zoned_gpu("g", ["test-zone-a"])])):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, _ = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-b"}, "first-evaluated type anchors the intersection"

    def test_multiple_claims_must_all_stay_satisfiable(self):
        # a type is pruned when ANY claim's intersection would empty
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc1, rc2 = gpu_claim("c1", model="a100"), gpu_claim("c2", model="h100")
        store.create(rc1)
        store.create(rc2)
        it_a = gpu_it("it-a", [zoned_gpu("g1", ["test-zone-a"], model="a100"), zoned_gpu("g2", ["test-zone-a"], model="h100")])
        it_b = gpu_it("it-b", [zoned_gpu("g1", ["test-zone-b"], model="a100"), zoned_gpu("g2", ["test-zone-b"], model="h100")])
        per_it = {}
        for it in (it_a, it_b):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc1, rc2], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        # it-b sits wholly in zone-b: rc1's (and rc2's) intersection with
        # it-a's zone-a contribution empties, and no alternative combination
        # exists — the type is pruned
        assert set(kept) == {"it-a"}
        assert set(metas[rc2.key()].total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a"}

    def test_mutually_conflicting_combination_explored_around(self):
        # THE spec one-shot device filtering cannot pass (VERDICT r4 #5): a
        # claim wants 2 devices; it-b offers g1(zone-a), g2(zone-b),
        # g3(zone-a). Every device is INDIVIDUALLY compatible with the
        # running intersection [a, b] from it-a, so a per-device filter
        # removes nothing — and a requirements-blind DFS picks g1+g2, whose
        # contribution a∩b collapses, pruning the type although g1+g3 is a
        # valid combination. The requirements-aware DFS skips g2 on the
        # g1 path and lands g1+g3, keeping the type alive.
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        it_a = gpu_it("it-a", [zoned_gpu("g1", ["test-zone-a", "test-zone-b"]), zoned_gpu("g2", ["test-zone-a", "test-zone-b"])])
        it_b = gpu_it("it-b", [zoned_gpu("g1", ["test-zone-a"]), zoned_gpu("g2", ["test-zone-b"]), zoned_gpu("g3", ["test-zone-a"])])
        per_it = {}
        for it in (it_a, it_b):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None, err
            per_it[it.name] = (tracker, result)
        # it-b's valid combination is g1+g3 (both zone-a); g2 must be skipped
        picked = sorted(ref.device.name for _n, ref, _c in per_it["it-b"][1].picks[rc.key()])
        assert picked == ["g1", "g3"]
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-a", "it-b"}
        assert set(metas[rc.key()].total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a"}

    def test_retry_under_running_bounds_finds_alternative(self):
        # cross-type repair: it-a pins zone-a; it-b's first DFS legitimately
        # lands g1+g2 in zone-b (self-consistent), which collapses against
        # the running zone-a — the retry re-runs the DFS WITH the running
        # intersection as a bound and finds the zone-a pair g3+g4
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        it_a = gpu_it("it-a", [zoned_gpu("g1", ["test-zone-a"]), zoned_gpu("g2", ["test-zone-a"])])
        it_b = gpu_it(
            "it-b",
            [
                zoned_gpu("g1", ["test-zone-b"]),
                zoned_gpu("g2", ["test-zone-b"]),
                zoned_gpu("g3", ["test-zone-a"]),
                zoned_gpu("g4", ["test-zone-a"]),
            ],
        )
        per_it = {}
        for it in (it_a, it_b):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None, err
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-a", "it-b"}
        picked = sorted(ref.device.name for _n, ref, _c in kept["it-b"][1].picks[rc.key()])
        assert picked == ["g3", "g4"]
        assert set(metas[rc.key()].total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a"}

    def test_cross_claim_zone_conflict_fails_allocation_outright(self):
        # two claims whose only devices pin DIFFERENT zones can never launch
        # on one node: the requirements-aware DFS fails the allocation itself
        # (allocator_test.go "should fail when two in-memory allocated claims
        # have incompatible zones"), rather than deferring to superposition
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc1, rc2 = gpu_claim("c1", model="a100"), gpu_claim("c2", model="h100")
        store.create(rc1)
        store.create(rc2)
        it = gpu_it("it-x", [zoned_gpu("g1", ["test-zone-a"], model="a100"), zoned_gpu("g2", ["test-zone-b"], model="h100")])
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc1, rc2], tracker)
        assert result is None and err is not None
        assert "c2" in err

    def test_collapse_retries_alternative_device_combination(self):
        # the DFS picks devices blind to superposition; when its pick would
        # collapse a claim's intersection, the allocator retries with
        # conflicting devices filtered so an alternative same-type device
        # keeps the instance type alive
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        it_a = gpu_it("it-a", [zoned_gpu("g", ["test-zone-b"])])
        # it-flex ships one zone-a device and one zone-b device; a zone-a
        # pick would collapse vs it-a's zone-b contribution
        it_flex = gpu_it("it-flex", [zoned_gpu("ga", ["test-zone-a"]), zoned_gpu("gb", ["test-zone-b"])])
        per_it = {}
        for it in (it_a, it_flex):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        assert set(kept) == {"it-a", "it-flex"}, "the zone-b alternative must keep it-flex alive"
        assert set(metas[rc.key()].total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-b"}

    def test_release_instance_types_relaxes_total(self):
        # allocator.go: totalRequirements updates when types are released
        store, clock, cluster = build_store()
        alloc = self._alloc(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        per_it = {}
        for it in (gpu_it("it-a", [zoned_gpu("g", ["test-zone-a"])]),
                   gpu_it("it-ab", [zoned_gpu("g", ["test-zone-a", "test-zone-b"])])):
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None
            per_it[it.name] = (tracker, result)
        kept, metas = alloc.superpose_template_allocation("nc-1", per_it)
        alloc.commit_template_metadata(metas)
        meta = alloc.resource_claim_allocation_metadata()[rc.key()]
        assert set(meta.total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a"}
        alloc.release_instance_types(rc.key(), ["it-a"])
        assert set(meta.total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-a", "test-zone-b"}
        assert "it-a" not in meta.contributed

    def test_scheduler_prunes_superposed_types_end_to_end(self):
        # through the real Scheduler: both GPU types fit the claim, but their
        # zone contributions conflict -> the claim's NodeClaim keeps only the
        # first and the claim metadata records the pinned zone
        store, clock, cluster = build_store()
        rc = gpu_claim("c1")
        store.create(rc)
        types = [
            gpu_it("gpu-a", [zoned_gpu("g", ["test-zone-a"])], price=10.0),
            gpu_it("gpu-b", [zoned_gpu("g", ["test-zone-b"])], price=20.0),
        ]
        s = scheduler_for(store, cluster, clock, types)
        results = s.solve([claim_pod("p1", "c1", cpu="1")])
        assert results.all_pods_scheduled()
        its = {it.name for it in results.new_node_claims[0].instance_type_options}
        assert len(its) == 1, f"conflicting contributions must prune to one type, got {its}"
        metas = s.allocator.resource_claim_allocation_metadata()
        meta = metas[rc.key()]
        zone_vals = set(meta.total.get(wk.ZONE_LABEL_KEY).values)
        assert len(zone_vals) == 1

    def test_compatible_contributions_keep_both_types(self):
        store, clock, cluster = build_store()
        rc = gpu_claim("c1")
        store.create(rc)
        types = [
            gpu_it("gpu-a", [zoned_gpu("g", ["test-zone-a", "test-zone-b"])]),
            gpu_it("gpu-b", [zoned_gpu("g", ["test-zone-b", "test-zone-c"])]),
        ]
        s = scheduler_for(store, cluster, clock, types)
        results = s.solve([claim_pod("p1", "c1", cpu="1")])
        assert results.all_pods_scheduled()
        its = {it.name for it in results.new_node_claims[0].instance_type_options}
        assert its == {"gpu-a", "gpu-b"}
        meta = s.allocator.resource_claim_allocation_metadata()[rc.key()]
        assert set(meta.total.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-b"}

    def test_no_metadata_for_in_cluster_allocations(self):
        # claims allocated from a node's published slices are not template
        # allocations: no superposition metadata (allocator.go:80-82)
        store, clock, cluster = build_store()
        store.create(ResourceSlice(
            metadata=ObjectMeta(name="sl"), node_name="n1", driver="gpu", pool_name="pool",
            devices=[zoned_gpu("g0", ["test-zone-a"])],
        ))
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        alloc.commit_for_node("n1", result)
        assert rc.key() not in alloc.resource_claim_allocation_metadata()


class TestAllocatorDepth:
    def test_match_attribute_constraint_spans_requests(self):
        # constraint.go: all devices for the constrained requests share the
        # attribute value — a mixed-model candidate set must pick same-model
        store, clock, cluster = build_store()
        devices = [
            zoned_gpu("a0", ["test-zone-a"], model="a100"),
            zoned_gpu("h0", ["test-zone-a"], model="h100"),
            zoned_gpu("h1", ["test-zone-a"], model="h100"),
        ]
        it = gpu_it("it", devices)
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2, constraints=[{"matchAttribute": "gpu.example.com/model"}])
        store.create(rc)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate("nc", alloc.template_devices(it), [rc], tracker)
        assert err is None
        picked = {ref.device.name for _, ref, _ in result.picks[rc.key()]}
        assert picked == {"h0", "h1"}, "matchAttribute forces the same-model pair"

    def test_dfs_rollback_releases_taken_devices(self):
        # allocationtracker.go rollback: a failing second request must release
        # the first request's tentatively-taken device
        store, clock, cluster = build_store()
        it = gpu_it("it", [zoned_gpu("g0", ["test-zone-a"], model="a100")])
        alloc = Allocator(store, clock)
        good = gpu_claim("good", model="a100")
        impossible = gpu_claim("impossible", model="h100")
        store.create(good)
        store.create(impossible)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate("nc", alloc.template_devices(it), [good, impossible], tracker)
        assert err is not None
        # the tracker must be clean: the same device allocates for a retry
        tracker2 = AllocationTracker(budgets=alloc.counter_budgets)
        result2, err2 = alloc.allocate("nc", alloc.template_devices(it), [good], tracker2)
        assert err2 is None and len(result2.picks[good.key()]) == 1

    def test_two_claims_cannot_share_exclusive_device(self):
        store, clock, cluster = build_store()
        it = gpu_it("it", [zoned_gpu("g0", ["test-zone-a"])])
        alloc = Allocator(store, clock)
        c1, c2 = gpu_claim("c1"), gpu_claim("c2")
        store.create(c1)
        store.create(c2)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        _, err = alloc.allocate("nc", alloc.template_devices(it), [c1, c2], tracker)
        assert err is not None and "c2" in err

    def test_partitionable_exhaustion_rolls_back_cleanly(self):
        # partitionable_devices.go: two 30-unit partitions exceed the 40-unit
        # shared counter; after failure the budget must be fully restored
        from karpenter_tpu.utils.quantity import Quantity

        store, clock, cluster = build_store()
        mig = lambda n: Device(
            name=n,
            attributes={"gpu.example.com/model": "mig"},
            capacity={},
            consumes_counters=[{"counterSet": "gpu0", "counters": {"mem": "30"}}],
        )
        it = gpu_it("it", [mig("p0"), mig("p1")])
        it.dynamic_resources_counters = [{"name": "gpu0", "counters": {"mem": "40"}}]
        alloc = Allocator(store, clock)
        c1, c2 = gpu_claim("c1"), gpu_claim("c2")
        store.create(c1)
        store.create(c2)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        _, err = alloc.allocate("nc", alloc.template_devices(it), [c1, c2], tracker)
        assert err is not None
        tracker2 = AllocationTracker(budgets=alloc.counter_budgets)
        result, err2 = alloc.allocate("nc", alloc.template_devices(it), [c1], tracker2)
        assert err2 is None
        # allocate() is pure; the draw-down lands at commit: exactly one
        # 30-unit draw against the fresh budget
        alloc.commit("nc", result, tracker2)
        pool_key = ("template", "it", "pool")
        rem = tracker2.remaining_counters[pool_key]["gpu0"]["mem"]
        assert rem == Quantity.parse("10")

    def test_allocation_timeout_aborts_dfs(self):
        # allocator.go:41-43: the DFS gives up at the 5s budget on the
        # injected clock
        store, clock, cluster = build_store()

        class SteppingClock(FakeClock):
            def now(self):
                t = super().now()
                self.step(3.0)  # every deadline check costs 3 virtual seconds
                return t

        stepping = SteppingClock()
        it = gpu_it("it", [zoned_gpu(f"g{i}", ["test-zone-a"]) for i in range(4)])
        alloc = Allocator(store, stepping)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        _, err = alloc.allocate("nc", alloc.template_devices(it), [rc], tracker)
        assert err is not None, "virtual-time deadline must abort the DFS"
