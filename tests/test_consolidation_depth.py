"""Consolidation depth specs ported from the reference's consolidation_test.go
(5,307 LoC): budgets across pools, delete-vs-replace decisions, price guards,
spot-to-spot edges, do-not-disrupt families (boolean, duration-based,
invalid), PDBs, ownerless pods, and savings ordering."""

import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from test_disruption import LINUX_AMD64, OD_ONLY, make_env, provision, run_disruption
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options


def one_node_per_pod_env(n, np_kwargs=None, cpu="500m", **opt_kwargs):
    """A fleet of n single-pod nodes via hostname anti-affinity."""
    env = make_env(np_kwargs=np_kwargs, **opt_kwargs)
    sel = {"matchLabels": {"app": "x"}}
    pods = [
        make_pod(cpu=cpu, name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
        for i in range(n)
    ]
    provision(env, pods)
    assert env.store.count("Node") == n
    return env


def empty_fleet_env(n, np_kwargs=None, **opt_kwargs):
    """n empty consolidatable nodes."""
    env = one_node_per_pod_env(n, np_kwargs=np_kwargs, **opt_kwargs)
    for i in range(n):
        env.store.delete("Pod", f"s{i}")
    return env


def zero_budgets(env, *pool_names):
    """Patch the named pools (default: all) down to a zero disruption budget."""
    names = pool_names or [np.metadata.name for np in env.store.list("NodePool")]

    def zero(p):
        p.spec.disruption.budgets = [Budget(nodes="0")]

    for name in names:
        env.store.patch("NodePool", name, zero)


class TestBudgetsDepth:
    def test_only_three_empty_nodes_disrupted(self):
        # consolidation_test.go:366 — budget nodes=3 caps one round's deletes
        env = empty_fleet_env(5)
        np = env.store.list("NodePool")[0]

        def set_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="3")]

        env.store.patch("NodePool", np.metadata.name, set_budget)
        # one disruption round only (validator consumes budget per candidate)
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        env.clock.step(5)
        for _ in range(6):  # let terminations drain without new rounds
            env.termination.reconcile()
            env.tick(provision_force=False)
        assert env.store.count("Node") == 2

    def test_all_empty_nodes_disrupted_with_full_budget(self):
        # consolidation_test.go:388
        env = empty_fleet_env(4)
        np = env.store.list("NodePool")[0]

        def set_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="100%")]

        env.store.patch("NodePool", np.metadata.name, set_budget)
        run_disruption(env)
        assert env.store.count("Node") == 0

    def test_zero_budget_blocks_all(self):
        # consolidation_test.go:411
        env = empty_fleet_env(3)
        np = env.store.list("NodePool")[0]

        def set_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="0")]

        env.store.patch("NodePool", np.metadata.name, set_budget)
        run_disruption(env)
        assert env.store.count("Node") == 3

    def test_per_pool_budgets_enforced_independently(self):
        # consolidation_test.go:522 — two pools, each budget-capped at 1/round
        env = Environment(options=Options())
        for name in ("pool-a", "pool-b"):
            np = make_nodepool(name=name, requirements=LINUX_AMD64)
            np.spec.disruption.consolidate_after = "30s"
            np.spec.disruption.budgets = [Budget(nodes="1")]
            env.store.create(np)
        sel_a, sel_b = {"matchLabels": {"app": "a"}}, {"matchLabels": {"app": "b"}}
        pods = [
            make_pod(cpu="500m", name=f"a{i}", labels={"app": "a"}, node_selector={wk.NODEPOOL_LABEL_KEY: "pool-a"}, anti_affinity=[hostname_anti_affinity(sel_a)])
            for i in range(2)
        ] + [
            make_pod(cpu="500m", name=f"b{i}", labels={"app": "b"}, node_selector={wk.NODEPOOL_LABEL_KEY: "pool-b"}, anti_affinity=[hostname_anti_affinity(sel_b)])
            for i in range(2)
        ]
        provision(env, pods)
        assert env.store.count("Node") == 4
        for p in pods:
            env.store.delete("Pod", p.metadata.name)
        # one round: at most one node per pool disrupts
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        for _ in range(6):
            env.termination.reconcile()
            env.tick(provision_force=False)
        assert env.store.count("Node") == 2


class TestDeleteDecisions:
    def test_can_delete_nodes(self):
        # consolidation_test.go:2421 — two underutilized nodes merge
        env = one_node_per_pod_env(3, np_kwargs={"requirements": OD_ONLY})
        # remove anti-affinity pressure: replace with plain pods
        for i in range(3):
            env.store.delete("Pod", f"s{i}")
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"f{i}"))
        provision(env, [])
        _full_budget(env)
        before = env.store.count("Node")
        run_disruption(env)
        assert env.store.count("Node") < before
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_delete_considers_pdb(self):
        # consolidation_test.go:2587 — a blocking PDB pins every node
        env = one_node_per_pod_env(2)
        env.store.create(_pdb("block", {"matchLabels": {"app": "x"}}, max_unavailable=0))
        run_disruption(env)
        assert env.store.count("Node") == 2

    def test_delete_considers_node_do_not_disrupt(self):
        # consolidation_test.go:2644
        env = empty_fleet_env(2)
        target = env.store.list("Node")[0].metadata.name

        def annotate(n):
            n.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"

        env.store.patch("Node", target, annotate)
        run_disruption(env)
        assert env.store.count("Node") == 1
        assert env.store.try_get("Node", target) is not None

    def test_delete_considers_pod_do_not_disrupt(self):
        # consolidation_test.go:2686
        env = one_node_per_pod_env(2)
        pod = env.store.get("Pod", "s0")

        def annotate(p):
            p.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"

        env.store.patch("Pod", "s0", annotate, namespace=pod.metadata.namespace)
        run_disruption(env)
        # s0's node survives; the other can still be considered
        assert env.store.get("Pod", "s0").spec.node_name
        node_of_s0 = env.store.get("Pod", "s0").spec.node_name
        assert env.store.try_get("Node", node_of_s0) is not None

    def test_duration_do_not_disrupt_active_blocks(self):
        # consolidation_test.go:2824 — "1h" annotation still active
        env = one_node_per_pod_env(2)
        pod = env.store.get("Pod", "s0")

        def annotate(p):
            p.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "1h"

        env.store.patch("Pod", "s0", annotate, namespace=pod.metadata.namespace)
        run_disruption(env, rounds=4)  # ~1 min of clock, well under 1h
        node_of_s0 = env.store.get("Pod", "s0").spec.node_name
        assert node_of_s0 and env.store.try_get("Node", node_of_s0) is not None

    def test_duration_do_not_disrupt_expires(self):
        # consolidation_test.go:2867 — protection lapses after the duration
        from karpenter_tpu.utils.pods import has_do_not_disrupt

        env = one_node_per_pod_env(1)
        pod = env.store.get("Pod", "s0")

        def annotate(p):
            p.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "1m"

        env.store.patch("Pod", "s0", annotate, namespace=pod.metadata.namespace)
        p = env.store.get("Pod", "s0")
        assert has_do_not_disrupt(p, env.clock.now())
        env.clock.step(120)
        assert not has_do_not_disrupt(p, env.clock.now())

    def test_invalid_do_not_disrupt_not_blocking(self):
        # consolidation_test.go:2916
        from karpenter_tpu.utils.pods import has_do_not_disrupt

        p = make_pod()
        p.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "not-a-duration"
        assert not has_do_not_disrupt(p, 0.0)
        p.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "-5m"
        assert not has_do_not_disrupt(p, 0.0)

    def test_deletes_evict_ownerless_pods(self):
        # consolidation_test.go:2956 — pods without ownerRefs still reschedule
        env = one_node_per_pod_env(3, np_kwargs={"requirements": OD_ONLY})
        for i in range(3):
            env.store.delete("Pod", f"s{i}")
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"own-{i}"))  # no ownerRef
        provision(env, [])
        _full_budget(env)
        before = env.store.count("Node")
        run_disruption(env)
        assert env.store.count("Node") < before
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_wont_delete_if_pod_would_go_pending(self):
        # consolidation_test.go:3442 — pods exactly fill remaining capacity
        env = make_env(np_kwargs={"requirements": OD_ONLY + [{"key": "karpenter.kwok.sh/instance-size", "operator": "In", "values": ["4x"]}]})
        # each 4x node has ~3.9 cpu allocatable; two nodes of 3 cpu pods
        provision(env, [make_pod(cpu="3", name="p0"), make_pod(cpu="3", name="p1")])
        assert env.store.count("Node") == 2
        run_disruption(env)
        # no single node can host both: nothing deletes
        assert env.store.count("Node") == 2
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_can_delete_while_invalid_nodepool_exists(self):
        # consolidation_test.go:3482 — a pool with no instance types alongside
        env = empty_fleet_env(2)
        bad = make_nodepool(name="bad-pool", requirements=[{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["nonexistent"]}])
        env.store.create(bad)
        run_disruption(env)
        assert env.store.count("Node") == 0


def _full_budget(env):
    for np in env.store.list("NodePool"):
        def set_budget(p):
            from karpenter_tpu.apis.nodepool import Budget

            p.spec.disruption.budgets = [Budget(nodes="100%")]

        env.store.patch("NodePool", np.metadata.name, set_budget)


def _pdb(name, selector, max_unavailable):
    from karpenter_tpu.kube.objects import ObjectMeta, PodDisruptionBudget

    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name),
        selector=selector,
        max_unavailable=max_unavailable,
    )


class TestReplaceDecisions:
    def test_oversized_on_demand_replaced_with_cheaper(self):
        # consolidation_test.go:2301 inverse — replacement happens only when
        # strictly cheaper; a right-sized node is NOT replaced
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", memory="1Gi", name="small")])
        # the provisioner already picked the cheapest fitting type: no replace
        before = {n.metadata.name for n in env.store.list("Node")}
        run_disruption(env, rounds=6)
        after = {n.metadata.name for n in env.store.list("Node")}
        assert before == after

    def test_replacement_maintains_zonal_spread(self):
        # consolidation_test.go:4525 — spread pods keep their zone layout
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        sel = {"matchLabels": {"app": "z"}}
        pods = [make_pod(cpu="500m", name=f"z{i}", labels={"app": "z"}, tsc=[zone_spread(1, sel)]) for i in range(3)]
        provision(env, pods)
        run_disruption(env, rounds=6)
        zones = set()
        for p in env.store.list("Pod"):
            assert p.spec.node_name, "spread pod went pending during consolidation"
            node = env.store.try_get("Node", p.spec.node_name)
            zones.add(node.metadata.labels.get(wk.ZONE_LABEL_KEY))
        assert len(zones) == 3, f"zonal spread collapsed to {zones}"


class TestSpotToSpot:
    def _spot_fleet(self, n_types_gate=True):
        env = make_env()
        env.options.feature_gates.spot_to_spot_consolidation = n_types_gate
        return env

    def test_spot_to_spot_disabled_gate_blocks(self):
        # consolidation_test.go:1136 — default gate off: spot nodes are not
        # replaced by cheaper spot
        env = make_env()
        assert env.options.feature_gates.spot_to_spot_consolidation is False
        provision(env, [make_pod(cpu="1", name="w")])
        node = env.store.list("Node")[0]
        assert node.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == wk.CAPACITY_TYPE_SPOT
        before = {n.metadata.name for n in env.store.list("Node")}
        run_disruption(env, rounds=6)
        assert {n.metadata.name for n in env.store.list("Node")} == before

    def test_spot_to_spot_min_flexibility(self):
        # consolidation_test.go:1061 — single-node spot replacement demands
        # >= 15 cheaper instance types; the method returns no command below it
        from karpenter_tpu.controllers.disruption.methods import SingleNodeConsolidation

        env = make_env()
        env.options.feature_gates.spot_to_spot_consolidation = True
        provision(env, [make_pod(cpu="1", name="w")])
        env.clock.step(40)
        env.tick(provision_force=True)
        env.nodeclaim_disruption.reconcile()
        candidates = env.disruption.get_candidates()
        if not candidates:
            pytest.skip("no candidates formed")
        method = SingleNodeConsolidation(env.disruption.ctx)
        env.disruption.ctx.round_candidates = candidates
        env.disruption.ctx.node_pool_totals = None
        cmd = method.compute_consolidation(candidates[:1])
        # provisioner already picked cheapest: replacement impossible; and
        # the <15-flexibility rule forbids marginal spot churn regardless
        assert not cmd.replacements


class TestSavingsOrdering:
    def test_lowest_disruption_cost_first(self):
        # consolidation_test.go:4429 — fewer/lighter pods disrupt first
        env = one_node_per_pod_env(2, np_kwargs={"requirements": OD_ONLY})
        # s0's node hosts an extra pod: higher disruption cost than s1's
        node0 = env.store.get("Pod", "s0").spec.node_name
        env.store.create(make_pod(cpu="100m", name="extra", node_name=node0))
        env.clock.step(40)
        env.tick(provision_force=True)
        env.nodeclaim_disruption.reconcile()
        candidates = sorted(env.disruption.get_candidates(), key=lambda c: c.disruption_cost)
        assert len(candidates) == 2
        assert len(candidates[0].reschedulable_pods) == 1  # the lighter node first
        assert len(candidates[1].reschedulable_pods) == 2


class TestBudgetsDepth5:
    """consolidation_test.go budget families not yet pinned: :433 (non-empty
    multi-node deletes), :522/:652 (cross-pool), :714-:934 (budget-blocked is
    NOT consolidated)."""

    def test_budget_caps_nonempty_multinode_deletes(self):
        # :433 "should only allow 3 nodes to be deleted in multi node
        # consolidation delete" — underutilized (non-empty) fleet, budget 3.
        # PREFERRED anti-affinity forces the 5-node setup (honored tier-0 at
        # provisioning) while staying relaxable in the consolidation
        # simulation, so the pods can re-home (pod affinity is immutable in
        # k8s — the reference manually binds instead)
        from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm

        env = make_env()
        sel = {"matchLabels": {"app": "x"}}
        pods = []
        for i in range(5):
            pod = make_pod(cpu="100m", name=f"s{i}", labels={"app": "x"})
            pod.spec.affinity = Affinity(
                pod_anti_affinity_preferred=[
                    WeightedPodAffinityTerm(
                        weight=1,
                        term=PodAffinityTerm(label_selector=sel, topology_key=wk.HOSTNAME_LABEL_KEY),
                    )
                ]
            )
            pods.append(pod)
        provision(env, pods)
        assert env.store.count("Node") == 5
        np = env.store.list("NodePool")[0]

        def set_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="3")]

        env.store.patch("NodePool", np.metadata.name, set_budget)
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        for _ in range(10):  # drain without advancing into another poll window
            env.termination.reconcile()
            env.tick(provision_force=False)
        # the budget caps the round at 3 deletions — and they must HAPPEN
        assert env.store.count("Node") == 2
        assert all(p.spec.node_name for p in env.store.list("Pod")), "pods re-homed"

    def test_cross_pool_budgets_independent(self):
        # :522 "should allow 2 nodes from each nodePool to be deleted" — each
        # pool's budget caps ITS nodes independently
        env = make_env()
        np_b = make_nodepool(name="pool-b", requirements=LINUX_AMD64)
        np_b.spec.disruption.consolidate_after = "30s"
        env.store.create(np_b)
        sel = {"matchLabels": {"app": "x"}}
        pods = []
        for i in range(3):
            pods.append(make_pod(cpu="100m", name=f"a{i}", labels={"app": "x"},
                                 node_selector={wk.NODEPOOL_LABEL_KEY: "default-pool"},
                                 anti_affinity=[hostname_anti_affinity(sel)]))
        for i in range(3):
            pods.append(make_pod(cpu="100m", name=f"b{i}", labels={"app": "x"},
                                 node_selector={wk.NODEPOOL_LABEL_KEY: "pool-b"},
                                 anti_affinity=[hostname_anti_affinity(sel)]))
        provision(env, pods)
        assert env.store.count("Node") == 6
        for name in ("default-pool", "pool-b"):
            def set_budget(p):
                p.spec.disruption.budgets = [Budget(nodes="2")]

            env.store.patch("NodePool", name, set_budget)
        for i in range(3):
            env.store.delete("Pod", f"a{i}")
            env.store.delete("Pod", f"b{i}")
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        for _ in range(8):  # drain without advancing into another poll window
            env.termination.reconcile()
            env.tick(provision_force=False)
        # one round: exactly 2 per pool deleted, exactly 1 left in each
        remaining_by_pool = {}
        for n in env.store.list("Node"):
            pool = n.metadata.labels.get(wk.NODEPOOL_LABEL_KEY)
            remaining_by_pool[pool] = remaining_by_pool.get(pool, 0) + 1
        assert remaining_by_pool == {"default-pool": 1, "pool-b": 1}

    def test_budget_blocked_round_is_not_consolidated(self):
        # :714/:738 "should not mark empty node consolidated if the
        # candidates can't be disrupted due to budgets" — the cluster must
        # NOT be marked consolidated, so cron budget windows opening later
        # are noticed without any object edit
        env = empty_fleet_env(3)
        np = env.store.list("NodePool")[0]

        def zero(p):
            p.spec.disruption.budgets = [Budget(nodes="0")]

        env.store.patch("NodePool", np.metadata.name, zero)
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        assert not env.cluster.consolidated(), (
            "budget-blocked candidates must keep the disruption poll alive"
        )
        assert env.store.count("Node") == 3

    def test_unblocked_empty_round_marks_consolidated(self):
        # the inverse: with nothing to do at all, the round MUST mark
        # consolidated (controller.go:181-183 pacing)
        env = make_env()
        provision(env, [make_pod(cpu="100m", name="p0")])
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        assert env.cluster.consolidated()


class TestConsolidationDestinations:
    def test_unmanaged_capacity_absorbs_candidate_pods(self):
        # :2539 "can delete nodes, when non-Karpenter capacity can fit pods"
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        env = one_node_per_pod_env(1, cpu="100m")
        env.store.create(
            Node(
                metadata=ObjectMeta(
                    name="legacy",
                    labels={
                        wk.HOSTNAME_LABEL_KEY: "legacy",
                        wk.ZONE_LABEL_KEY: "test-zone-a",
                        wk.ARCH_LABEL_KEY: "amd64",
                        wk.OS_LABEL_KEY: "linux",
                    },
                ),
                spec=NodeSpec(provider_id="legacy://1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                ),
            )
        )
        env.settle(rounds=3)
        run_disruption(env)
        # the managed single-pod node consolidates away; its pod lands on the
        # unmanaged node (which itself is never a candidate)
        assert env.store.try_get("Node", "legacy") is not None
        managed = [n for n in env.store.list("Node") if n.metadata.name != "legacy"]
        assert managed == [], [n.metadata.name for n in managed]
        pod = env.store.get("Pod", "s0")
        assert pod.spec.node_name == "legacy"

    def test_permanently_pending_pod_does_not_block_deletes(self):
        # :3390 "can delete nodes with a permanently pending pod" — an
        # unsatisfiable pending pod must not wedge consolidation of empties
        env = empty_fleet_env(2)
        env.store.create(make_pod(cpu="4000", name="impossible"))  # fits nothing
        run_disruption(env)
        assert env.store.count("Node") == 0
        assert not env.store.get("Pod", "impossible").spec.node_name


class TestValidationWindowChurn:
    """consolidation_test.go :3785-:3895 — commands invalidated by state that
    appears DURING the 15 s validation window: do-not-disrupt pods and
    blocking PDBs landing on a candidate."""

    def _candidate_cmd(self, env):
        from karpenter_tpu.controllers.disruption.methods import MultiNodeConsolidation
        from karpenter_tpu.controllers.disruption.types import REASON_UNDERUTILIZED, Command

        ctrl = env.disruption
        method = next(m for m in ctrl.methods if isinstance(m, MultiNodeConsolidation))
        eligible = [c for c in ctrl.get_candidates() if method.should_disrupt(c)]
        assert eligible, "fixture must produce a consolidation candidate"
        return ctrl, method, Command(reason=REASON_UNDERUTILIZED, candidates=eligible[:1])

    def test_do_not_disrupt_pod_scheduling_mid_window_invalidates(self):
        # :3857 "should not delete node if pods schedule with
        # karpenter.sh/do-not-disrupt set to true during the TTL wait"
        import pytest as _pytest

        from test_disruption import OD_ONLY
        from karpenter_tpu.controllers.disruption.validation import ValidationError, Validator

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", name=f"p{i}") for i in range(2)])
        run_disruption(env, rounds=4)
        ctrl, method, cmd = self._candidate_cmd(env)
        # a do-not-disrupt pod binds onto the candidate mid-window
        blocker = make_pod(
            cpu="100m", name="blocker",
            annotations={wk.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"},
            node_name=cmd.candidates[0].name(),
        )
        env.store.create(blocker)
        env.settle(rounds=2)
        with _pytest.raises(ValidationError):
            Validator(ctrl.ctx, method, mode="strict", metrics=env.registry).validate(cmd, delay_seconds=0)

    def test_blocking_pdb_appearing_mid_window_invalidates(self):
        # :3895 "should not delete node if pods schedule with a blocking PDB
        # during the TTL wait"
        import pytest as _pytest

        from test_disruption import OD_ONLY
        from karpenter_tpu.controllers.disruption.validation import ValidationError, Validator
        from karpenter_tpu.kube import ObjectMeta
        from karpenter_tpu.kube.objects import PodDisruptionBudget

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", name=f"p{i}", labels={"app": "guarded"}) for i in range(2)])
        run_disruption(env, rounds=4)
        ctrl, method, cmd = self._candidate_cmd(env)
        env.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb"),
                selector={"matchLabels": {"app": "guarded"}},
                max_unavailable=0,
            )
        )
        env.settle(rounds=2)
        with _pytest.raises(ValidationError):
            Validator(ctrl.ctx, ctrl.methods[3], mode="strict", metrics=env.registry).validate(cmd, delay_seconds=0)


class TestBudgetBlockedVariants:
    """consolidation_test.go :802-:934 — the budget-blocked-is-not-consolidated
    contract holds for NON-EMPTY (multi/single-node) consolidation too."""

    def test_multinode_budget_blocked_not_consolidated(self):
        # :802 — underutilized fleet, zero budget: no deletes AND the
        # cluster must not be marked consolidated
        env = one_node_per_pod_env(3, cpu="100m")
        zero_budgets(env)
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        assert env.store.count("Node") == 3
        assert not env.cluster.consolidated()

    def test_multinode_budget_blocked_many_pools(self):
        # :833 — ALL pools' candidates blocked across two pools
        env = make_env()
        np_b = make_nodepool(name="pool-b", requirements=LINUX_AMD64)
        np_b.spec.disruption.consolidate_after = "30s"
        env.store.create(np_b)
        sel = {"matchLabels": {"app": "x"}}
        pods = [
            make_pod(cpu="100m", name=f"a{i}", labels={"app": "x"},
                     node_selector={wk.NODEPOOL_LABEL_KEY: pool},
                     anti_affinity=[hostname_anti_affinity(sel)])
            for i, pool in enumerate(["default-pool", "pool-b"])
        ]
        provision(env, pods)
        zero_budgets(env, "default-pool", "pool-b")
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        assert env.store.count("Node") == 2
        assert not env.cluster.consolidated()


class TestSpotToSpotTruncation:
    """consolidation_test.go :1177/:1247 — the 15-cheapest truncation rules
    for single-node spot-to-spot: per-offering prices let the candidate's own
    TYPE rank among the replacement options."""

    def _spot_type(self, name, price_by_zone):
        from karpenter_tpu.cloudprovider.types import InstanceType, Offering
        from karpenter_tpu.scheduling.requirements import Requirement, Requirements
        from karpenter_tpu.utils.resources import parse_resource_list

        return InstanceType(
            name=name,
            requirements=Requirements.from_labels({
                wk.INSTANCE_TYPE_LABEL_KEY: name, wk.ARCH_LABEL_KEY: "amd64", wk.OS_LABEL_KEY: "linux",
            }),
            offerings=[
                Offering(
                    requirements=Requirements(
                        Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_SPOT]),
                        Requirement(wk.ZONE_LABEL_KEY, "In", [zone]),
                    ),
                    price=price,
                )
                for zone, price in price_by_zone.items()
            ],
            capacity=parse_resource_list({"cpu": "4", "memory": "8Gi", "pods": "110"}),
        )

    def _env_with_spot_node(self, cand_cheap_price):
        """A spot node on type 'cand' priced 100 in its zone; 17 cheaper spot
        types exist, and cand's OTHER-zone offering prices at
        cand_cheap_price — controlling where cand ranks among replacements."""
        from test_consolidation_depth3 import manual_node
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options

        types = [self._spot_type(f"st-{i:02d}", {"test-zone-a": 1.0 + i * 0.1}) for i in range(17)]
        types.append(self._spot_type("cand", {"test-zone-b": 100.0, "test-zone-a": cand_cheap_price}))
        env = Environment(options=Options(), instance_types=types)
        env.options.feature_gates.spot_to_spot_consolidation = True
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.disruption.consolidate_after = "30s"
        env.store.create(np)
        manual_node(env, "n1", "cand", "4", ct=wk.CAPACITY_TYPE_SPOT, zone="test-zone-b")
        env.store.create(make_pod(cpu="100m", name="w", node_name="n1"))
        env.settle(rounds=3)
        env.clock.step(40)
        env.nodeclaim_disruption.reconcile()  # consolidatable after the
        # window; deliberately NO disruption tick: the method drives below
        return env

    def _single_node_cmd(self, env):
        from karpenter_tpu.controllers.disruption.methods import SingleNodeConsolidation

        ctrl = env.disruption
        method = SingleNodeConsolidation(ctrl.ctx)
        eligible = [c for c in ctrl.get_candidates() if method.should_disrupt(c)]
        assert len(eligible) == 1, "fixture must yield exactly the spot node"
        ctrl.ctx.round_candidates = eligible
        ctrl.ctx.node_pool_totals = None
        return method.compute_consolidation(eligible[:1])

    def test_candidate_among_15_cheapest_blocks_churn(self):
        # :1177 "cannot replace spot with spot if it is part of the 15
        # cheapest instance types" — cand's other-zone offering is the
        # cheapest overall, so replacing would be pointless churn
        env = self._env_with_spot_node(cand_cheap_price=0.5)
        cmd = self._single_node_cmd(env)
        assert not cmd.candidates and not cmd.replacements, "blocked, not a delete"

    def test_truncates_to_15_cheapest_excluding_candidate(self):
        # :1247 "spot to spot consolidation should order the instance types
        # by price before enforcing minimum flexibility" — cand ranks 18th,
        # so the command proceeds with exactly the 15 cheapest options
        env = self._env_with_spot_node(cand_cheap_price=2.9)
        cmd = self._single_node_cmd(env)
        assert cmd.replacements
        names = [it.name for it in cmd.replacements[0].instance_type_options]
        assert len(names) == 15
        assert "cand" not in names
        assert names == sorted(names), "options stay price-ordered (st-00..st-14)"
