"""ClusterCost tracking + balanced scoring specs (reference:
pkg/state/cost/suite_test.go, disruption/balanced.go coverage)."""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.controllers.disruption.balanced import (
    NodePoolTotals,
    ScoreResult,
    compute_node_pool_totals,
    evaluate_balanced_move,
    score_move,
)
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env(**np_kwargs):
    env = Environment(options=Options())
    np_kwargs.setdefault("requirements", LINUX_AMD64)
    env.store.create(make_nodepool(**np_kwargs))
    return env


class TestClusterCost:
    def test_tracks_provisioned_claims(self):
        env = make_env()
        for i in range(3):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle(rounds=6)
        assert env.store.count("NodeClaim") >= 1
        total = env.cluster_cost.get_cluster_cost()
        assert total > 0
        assert abs(total - env.cluster_cost.get_nodepool_cost("default-pool")) < 1e-12

    def test_cost_matches_offering_price(self):
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        nc = env.store.list("NodeClaim")[0]
        labels = nc.metadata.labels
        np_ = env.store.get("NodePool", "default-pool")
        it = next(
            it
            for it in env.cloud_provider.get_instance_types(np_)
            if it.name == labels[wk.INSTANCE_TYPE_LABEL_KEY]
        )
        price = it.offering_price(labels[wk.ZONE_LABEL_KEY], labels[wk.CAPACITY_TYPE_LABEL_KEY])
        assert abs(env.cluster_cost.get_cluster_cost() - price) < 1e-9

    def test_deleted_claim_decrements(self):
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        assert env.cluster_cost.get_cluster_cost() > 0
        env.store.delete("Pod", "p")
        for _ in range(12):
            env.clock.step(30)
            env.tick(provision_force=True)
        assert env.store.count("NodeClaim") == 0
        assert env.cluster_cost.get_cluster_cost() == 0

    def test_claim_without_labels_ignored_until_labeled(self):
        from karpenter_tpu.apis.nodeclaim import NodeClaim
        from karpenter_tpu.kube import ObjectMeta

        env = make_env()
        nc = NodeClaim(metadata=ObjectMeta(name="bare"))
        env.store.create(nc)
        assert env.cluster_cost.get_cluster_cost() == 0

        def label(obj):
            obj.metadata.labels.update(
                {
                    wk.NODEPOOL_LABEL_KEY: "default-pool",
                    wk.INSTANCE_TYPE_LABEL_KEY: "c-4x-amd64-linux",
                    wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_ON_DEMAND,
                    wk.ZONE_LABEL_KEY: "test-zone-a",
                }
            )

        env.store.patch("NodeClaim", "bare", label)
        # MODIFIED event retries the add now that labels are present
        assert "bare" in env.cluster_cost._claims
        np_ = env.store.get("NodePool", "default-pool")
        it = next(
            it for it in env.cloud_provider.get_instance_types(np_) if it.name == "c-4x-amd64-linux"
        )
        price = it.offering_price("test-zone-a", wk.CAPACITY_TYPE_ON_DEMAND)
        assert abs(env.cluster_cost.get_nodepool_cost("default-pool") - price) < 1e-9

    def test_delete_node_pool_clears(self):
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        env.cluster_cost.delete_node_pool("default-pool")
        assert env.cluster_cost.get_cluster_cost() == 0

    def test_pricing_controller_refreshes_prices(self):
        """Catalog price changes reach the totals via the periodic pricing
        refresh (informer/pricing.go)."""
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        before = env.cluster_cost.get_cluster_cost()
        assert before > 0
        for it in env.cloud_provider.instance_types:
            for o in it.offerings:
                o.price *= 3
        env.pricing.reconcile(force=True)
        assert abs(env.cluster_cost.get_cluster_cost() - 3 * before) < 1e-9

    def test_update_offerings_reprices(self):
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        np_ = env.store.get("NodePool", "default-pool")
        its = env.cloud_provider.get_instance_types(np_)
        for it in its:
            for o in it.offerings:
                o.price = o.price * 2
        before = env.cluster_cost.get_cluster_cost()
        env.cluster_cost.update_offerings(np_, its)
        assert abs(env.cluster_cost.get_cluster_cost() - 2 * before) < 1e-9


class TestBalancedScoring:
    def test_score_move_threshold(self):
        totals = NodePoolTotals(total_cost=10.0, total_disruption_cost=10.0)
        # savings 10% of pool cost, disrupting 10% of pool: score 1.0 >= 0.5
        assert score_move(1.0, 1.0, totals).approved()
        # savings 1% while disrupting 10%: score 0.1 < 0.5
        assert not score_move(0.1, 1.0, totals).approved()

    def test_zero_totals_not_approved(self):
        assert not score_move(1.0, 1.0, NodePoolTotals()).approved()

    def test_zero_disruption_is_infinite_score(self):
        r = ScoreResult(savings_fraction=0.5, disruption_fraction=0.0)
        assert r.score() == float("inf") and r.approved()

    def test_evaluate_only_gates_balanced_pools(self):
        """A command touching no Balanced pool is approved by the method-level
        gate before evaluate_balanced_move is even called; here we check
        evaluate skips non-Balanced pools."""
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        ctrl = env.disruption
        candidates = ctrl.get_candidates()
        assert candidates
        totals = compute_node_pool_totals(candidates, env.cluster.nodes(), env.cluster_cost)
        assert totals["default-pool"].total_cost > 0
        assert totals["default-pool"].total_disruption_cost >= 1.0

        from karpenter_tpu.controllers.disruption.types import Command

        cmd = Command(reason="Underutilized", candidates=candidates)
        # default policy is not Balanced -> every pool skipped -> approved
        assert evaluate_balanced_move(cmd, 0.0, totals)

    def test_balanced_pool_blocks_tiny_savings(self):
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p"))
        env.settle(rounds=6)
        def set_balanced(np_):
            np_.spec.disruption.consolidation_policy = "Balanced"

        env.store.patch("NodePool", "default-pool", set_balanced)
        candidates = env.disruption.get_candidates()
        assert candidates
        totals = compute_node_pool_totals(candidates, env.cluster.nodes(), env.cluster_cost)

        from karpenter_tpu.controllers.disruption.types import Command

        cmd = Command(reason="Underutilized", candidates=candidates)
        source = sum(c.price for c in candidates)
        # replacement nearly as expensive -> tiny savings -> blocked
        assert not evaluate_balanced_move(cmd, source * 0.999, totals)
        # free replacement -> savings = 100% of pool cost -> approved
        assert evaluate_balanced_move(cmd, 0.0, totals)
