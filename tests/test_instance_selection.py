"""Instance-selection depth specs ported from the reference's
instance_selection_test.go (1,489 LoC): cheapest-instance picking under every
combination of pod/pool arch, os, zone, and capacity-type constraints, plus
resource-driven selection and minValues operator coverage."""

import pytest

from helpers import make_nodepool, make_pod
from test_scheduler import LINUX_AMD64, build_env, make_scheduler
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.types import order_by_price


def solve(pods, node_pools=None, types=None, **kw):
    env = build_env(node_pools=node_pools, types=types)
    s = make_scheduler(*env, **kw)
    return s.solve(pods)


def cheapest_price(its, reqs):
    best = float("inf")
    for it in its:
        for o in it.offerings:
            if o.available and reqs.intersects(o.requirements) is None:
                best = min(best, o.price)
    return best


def launch_price(nc):
    """Cheapest launchable price for the finalized claim."""
    return cheapest_price(nc.instance_type_options, nc.requirements)


def assert_cheapest(results, types, within=1.0001):
    """The claim's launch price equals the cheapest offering any compatible
    type offers under the claim's own requirements."""
    assert results.all_pods_scheduled()
    assert len(results.new_node_claims) == 1
    nc = results.new_node_claims[0]
    best_possible = cheapest_price(nc.instance_type_options, nc.requirements)
    assert launch_price(nc) <= best_possible * within
    # the instance-type options are price-ordered cheapest-first in the API claim
    api = nc.to_api_node_claim()
    it_req = next(r for r in api.spec.requirements if r["key"] == wk.INSTANCE_TYPE_LABEL_KEY and r["operator"] == "In")
    assert it_req["values"], "claim must carry launchable instance types"
    return nc


class TestCheapestInstance:
    def test_cheapest_unconstrained(self):
        # instance_selection_test.go:82
        types = catalog.construct_instance_types()
        results = solve([make_pod(cpu="500m")], types=types)
        assert_cheapest(results, types)

    @pytest.mark.parametrize("arch", ["amd64", "arm64"])
    def test_cheapest_pod_arch(self, arch):
        # :89/:103 — pod nodeSelector on arch
        types = catalog.construct_instance_types()
        np = make_nodepool(requirements=[{"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]}])
        results = solve([make_pod(cpu="500m", node_selector={wk.ARCH_LABEL_KEY: arch})], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        assert nc.requirements.get(wk.ARCH_LABEL_KEY).values_list() == [arch]
        assert all(it.requirements.get(wk.ARCH_LABEL_KEY).has(arch) for it in nc.instance_type_options)

    @pytest.mark.parametrize("arch", ["amd64", "arm64"])
    def test_cheapest_pool_arch(self, arch):
        # :116/:131 — pool requirement on arch
        types = catalog.construct_instance_types()
        np = make_nodepool(requirements=[{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": [arch]}])
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        assert all(it.requirements.get(wk.ARCH_LABEL_KEY).has(arch) for it in nc.instance_type_options)

    def test_cheapest_pod_zone(self):
        # :230
        types = catalog.construct_instance_types()
        results = solve([make_pod(cpu="500m", node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})], types=types)
        nc = assert_cheapest(results, types)
        assert nc.requirements.get(wk.ZONE_LABEL_KEY).values_list() == ["test-zone-b"]

    def test_cheapest_pool_zone(self):
        # :215
        types = catalog.construct_instance_types()
        np = make_nodepool(requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}])
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        assert all(o.zone() == "test-zone-b" for it in nc.instance_type_options for o in it.offerings if nc.requirements.intersects(o.requirements) is None)

    def test_cheapest_pod_capacity_type_spot(self):
        # :258
        types = catalog.construct_instance_types()
        results = solve([make_pod(cpu="500m", node_selector={wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_SPOT})], types=types)
        nc = assert_cheapest(results, types)
        assert nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY).values_list() == [wk.CAPACITY_TYPE_SPOT]

    def test_cheapest_pool_capacity_type_ondemand_zone(self):
        # :271 — pool pins on-demand + zone-a
        types = catalog.construct_instance_types()
        np = make_nodepool(
            requirements=LINUX_AMD64
            + [
                {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]},
                {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]},
            ]
        )
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        cts = {o.capacity_type() for it in nc.instance_type_options for o in it.offerings if nc.requirements.intersects(o.requirements) is None}
        assert cts == {wk.CAPACITY_TYPE_ON_DEMAND}

    def test_cheapest_mixed_pod_and_pool_constraints(self):
        # :310 — pool spot, pod zone-b
        types = catalog.construct_instance_types()
        np = make_nodepool(
            requirements=LINUX_AMD64 + [{"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_SPOT]}]
        )
        results = solve([make_pod(cpu="500m", node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        offs = compatible_offerings(nc)
        assert offs and all(o.capacity_type() == wk.CAPACITY_TYPE_SPOT and o.zone() == "test-zone-b" for o in offs)

    def test_no_match_pod_arch(self):
        # :428 — nonexistent arch
        results = solve([make_pod(node_selector={wk.ARCH_LABEL_KEY: "s390x"})])
        assert len(results.pod_errors) == 1

    def test_no_match_pool_arch_pod_zone_conflict(self):
        # :477 — pool arm64, but no arm64 offering in the pod's zone
        types = [
            catalog.make_instance_type("c", 4, arch="arm64", zones=["test-zone-a"]),
            catalog.make_instance_type("c", 4, arch="amd64", zones=["test-zone-b"]),
        ]
        np = make_nodepool(requirements=[{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["arm64"]}])
        results = solve([make_pod(node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})], node_pools=[np], types=types)
        assert len(results.pod_errors) == 1

    def test_resources_drive_selection(self):
        # :509 — a big pod skips small instance types
        types = catalog.construct_instance_types()
        results = solve([make_pod(cpu="11", memory="20Gi")])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        from karpenter_tpu.utils import resources as res

        total = res.requests_for_pods(nc.pods)
        assert all(res.fits(total, it.allocatable()) for it in nc.instance_type_options)

    def test_cheaper_on_demand_beats_pricier_spot_requirement_mix(self):
        # :563 — when the claim may use both spot and OD, ordering considers
        # the cheapest launchable offering per type
        types = catalog.construct_instance_types()
        results = solve([make_pod(cpu="500m")], types=types)
        nc = results.new_node_claims[0]
        ordered = order_by_price(nc.instance_type_options, nc.requirements)
        prices = [cheapest_price([it], nc.requirements) for it in ordered]
        assert prices == sorted(prices)


class TestMinValuesOperators:
    def _pool_with_min_values(self, key, operator, values, min_values):
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.template.requirements = list(np.spec.template.requirements) + [
            {"key": key, "operator": operator, "values": values, "minValues": min_values}
        ]
        return np

    def test_min_values_in_operator(self):
        # :621 — instance-type In with minValues=2: the claim keeps >= 2 types
        types = catalog.construct_instance_types()
        names = sorted({it.name for it in types if "amd64-linux" in it.name})[:4]
        np = self._pool_with_min_values(wk.INSTANCE_TYPE_LABEL_KEY, "In", names, 2)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert results.all_pods_scheduled()
        api = results.new_node_claims[0].to_api_node_claim()
        it_req = next(r for r in api.spec.requirements if r["key"] == wk.INSTANCE_TYPE_LABEL_KEY and r["operator"] == "In")
        assert len(it_req["values"]) >= 2

    def test_min_values_gt_operator(self):
        # :693 — Gt on instance-cpu with minValues
        types = catalog.construct_instance_types()
        np = self._pool_with_min_values("karpenter.kwok.sh/instance-cpu", "Gt", ["2"], 2)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert len({it.name for it in nc.instance_type_options}) >= 2
        assert all(int(it.requirements.get("karpenter.kwok.sh/instance-cpu").any()) > 2 for it in nc.instance_type_options)

    def test_min_values_gt_unsatisfiable_fails(self):
        # :784 — Gt excludes everything
        types = [catalog.make_instance_type("c", 4)]
        np = self._pool_with_min_values("karpenter.kwok.sh/instance-cpu", "Gt", ["64"], 1)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert len(results.pod_errors) == 1

    def test_min_values_lt_operator(self):
        # :870
        types = catalog.construct_instance_types()
        np = self._pool_with_min_values("karpenter.kwok.sh/instance-cpu", "Lt", ["16"], 2)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert all(int(it.requirements.get("karpenter.kwok.sh/instance-cpu").any()) < 16 for it in nc.instance_type_options)

    def test_min_values_lt_unsatisfiable_fails(self):
        # :961
        types = [catalog.make_instance_type("c", 4)]
        np = self._pool_with_min_values("karpenter.kwok.sh/instance-cpu", "Lt", ["2"], 1)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert len(results.pod_errors) == 1

    def test_min_values_max_of_in_and_notin(self):
        # :1029 — same key with In (minValues 2) and NotIn: the max governs
        types = catalog.construct_instance_types()
        names = sorted({it.name for it in types if "amd64-linux" in it.name})
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.template.requirements = list(np.spec.template.requirements) + [
            {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": names[:6], "minValues": 2},
            {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "NotIn", "values": names[:1], "minValues": 3},
        ]
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert results.all_pods_scheduled()
        api = results.new_node_claims[0].to_api_node_claim()
        it_req = next(r for r in api.spec.requirements if r["key"] == wk.INSTANCE_TYPE_LABEL_KEY and r["operator"] == "In")
        assert len(it_req["values"]) >= 3
        assert names[0] not in it_req["values"]

    def test_min_values_unmet_count_fails(self):
        # :1234 — minValues above the surviving type count
        types = [catalog.make_instance_type("c", 4), catalog.make_instance_type("m", 4)]
        np = self._pool_with_min_values(wk.INSTANCE_TYPE_LABEL_KEY, "In", [t.name for t in types], 3)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert len(results.pod_errors) == 1

    def test_min_values_multiple_keys(self):
        # :1410 — minValues on two requirement keys simultaneously
        types = catalog.construct_instance_types()
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.template.requirements = list(np.spec.template.requirements) + [
            {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "Exists", "minValues": 2},
            {"key": "karpenter.kwok.sh/instance-family", "operator": "Exists", "minValues": 2},
        ]
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        fams = {it.requirements.get("karpenter.kwok.sh/instance-family").any() for it in nc.instance_type_options}
        assert len(fams) >= 2
        assert len({it.name for it in nc.instance_type_options}) >= 2

    def test_min_values_best_effort_policy_relaxes(self):
        # MinValuesPolicy=BestEffort (options.go) — unsatisfiable minValues
        # relax instead of failing
        types = [catalog.make_instance_type("c", 4), catalog.make_instance_type("m", 4)]
        np = self._pool_with_min_values(wk.INSTANCE_TYPE_LABEL_KEY, "In", [t.name for t in types], 3)
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types, min_values_policy="BestEffort")
        assert results.all_pods_scheduled()


class TestOfferingAvailability:
    def test_unavailable_offerings_skipped(self):
        # fake provider ICE'd offerings are not launchable
        it = catalog.make_instance_type("c", 4, zones=["test-zone-a", "test-zone-b"])
        for o in it.offerings:
            if o.zone() == "test-zone-a":
                o.available = False
        results = solve([make_pod(cpu="500m")], types=[it])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        zones = {o.zone() for t in nc.instance_type_options for o in t.offerings if o.available and nc.requirements.intersects(o.requirements) is None}
        assert zones == {"test-zone-b"}

    def test_all_offerings_unavailable_fails(self):
        it = catalog.make_instance_type("c", 4)
        for o in it.offerings:
            o.available = False
        results = solve([make_pod(cpu="500m")], types=[it])
        assert len(results.pod_errors) == 1




def compatible_offerings(nc):
    """AVAILABLE offerings launchable under the claim's final requirements."""
    return [
        o
        for it in nc.instance_type_options
        for o in it.offerings
        if o.available and nc.requirements.intersects(o.requirements) is None
    ]

class TestCheapestFourWayCombos:
    """instance_selection_test.go :291-:396 — the remaining pod/pool
    constraint combinations over arch/os/zone/capacity-type."""

    def test_cheapest_pod_ct_spot_pod_zone(self):
        # :291 "(pod ct = spot, pod zone = test-zone-1)"
        types = catalog.construct_instance_types()
        pod = make_pod(
            cpu="500m",
            node_selector={wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_SPOT, wk.ZONE_LABEL_KEY: "test-zone-a"},
        )
        results = solve([pod], types=types)
        nc = assert_cheapest(results, types)
        offs = compatible_offerings(nc)
        assert offs and all(o.capacity_type() == wk.CAPACITY_TYPE_SPOT and o.zone() == "test-zone-a" for o in offs)

    def test_cheapest_pool_four_way_pin(self):
        # :330 "(prov ct = ondemand/test-zone-1/arm64/linux)" — the pool pins
        # every dimension; the claim's launchable set respects all four
        types = catalog.construct_instance_types()
        np = make_nodepool(
            requirements=[
                {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
                {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["arm64"]},
                {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]},
                {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]},
            ]
        )
        results = solve([make_pod(cpu="500m")], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        assert set(nc.requirements.get(wk.ARCH_LABEL_KEY).values) == {"arm64"}
        offs = compatible_offerings(nc)
        assert offs and all(o.capacity_type() == wk.CAPACITY_TYPE_ON_DEMAND and o.zone() == "test-zone-a" for o in offs)

    def test_cheapest_pool_and_pod_split_dimensions(self):
        # :362 "(prov = spot/test-zone-2, pod = amd64/linux)"
        types = catalog.construct_instance_types()
        np = make_nodepool(
            requirements=[
                {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
                {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64", "arm64"]},
                {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_SPOT]},
                {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]},
            ]
        )
        pod = make_pod(cpu="500m", node_selector={wk.ARCH_LABEL_KEY: "amd64", wk.OS_LABEL_KEY: "linux"})
        results = solve([pod], node_pools=[np], types=types)
        nc = assert_cheapest(results, types)
        assert set(nc.requirements.get(wk.ARCH_LABEL_KEY).values) == {"amd64"}

    def test_cheapest_pod_four_way_pin(self):
        # :396 "(pod ct = spot/test-zone-2/amd64/linux)"
        types = catalog.construct_instance_types()
        pod = make_pod(
            cpu="500m",
            node_selector={
                wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_SPOT,
                wk.ZONE_LABEL_KEY: "test-zone-b",
                wk.ARCH_LABEL_KEY: "amd64",
                wk.OS_LABEL_KEY: "linux",
            },
        )
        results = solve([pod], types=types)
        nc = assert_cheapest(results, types)
        offs = compatible_offerings(nc)
        assert offs and all(o.capacity_type() == wk.CAPACITY_TYPE_SPOT and o.zone() == "test-zone-b" for o in offs)

    def test_no_match_pod_arch_and_zone(self):
        # :448 "(pod arch = arm zone=test-zone-2)" — arm types exist but not
        # in the requested zone
        types = [
            catalog.make_instance_type("c", 4, arch="arm64", zones=["test-zone-a"]),
            catalog.make_instance_type("m", 4, arch="amd64", zones=["test-zone-b"]),
        ]
        np = make_nodepool(requirements=[{"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]}])
        pod = make_pod(node_selector={wk.ARCH_LABEL_KEY: "arm64", wk.ZONE_LABEL_KEY: "test-zone-b"})
        results = solve([pod], node_pools=[np], types=types)
        assert len(results.pod_errors) == 1

    def test_enough_resources_picks_bigger_type(self):
        # :509 "should schedule on an instance with enough resources" — the
        # request outgrows small types; the claim's fit set excludes them
        types = [
            catalog.make_instance_type("c", 2),
            catalog.make_instance_type("c", 16),
        ]
        results = solve([make_pod(cpu="8")], types=types)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert all(it.capacity["cpu"].milli >= 8000 for it in nc.instance_type_options)
