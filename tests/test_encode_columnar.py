"""Columnar cold-encode specs: the signature pass without per-pod bytecode.

The encode's one O(P) pass is now columnar: `pod_signature` takes inlined
fast paths for the dominant shapes, signatures are stamped ON the Pod object
across solves (`_SigStamp`, invalidated by resourceVersion), stamped tuples
are interned so grouping probes hash object ids, and `_columnar_group` does
the whole grouping pass in C loops (attrgetter maps + np.unique). These
specs pin the safety net:

- BYTE-IDENTICAL signatures: the fast paths must return exactly what the
  structure-literal reference (`_pod_signature_reference`) returns, across a
  zoo of pod shapes;
- stamp lifecycle: cache hit on unchanged rv, recompute on bump, and NO
  survival across copy/deepcopy (the host relaxation loop deep-copies then
  mutates specs in place — a stamp that survived would serve stale
  signatures);
- `_columnar_group` parity with the sequential loop (same sig ids in the
  same first-appearance order), and its gates (PVC pods, unstamped pods);
- encode + solve parity: KARPENTER_ENCODE_COLUMNAR=0 (the exact-reference
  legacy arm) produces identical encodes and bit-identical placements.
"""

import copy

import numpy as np

from helpers import hostname_anti_affinity, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import Container
from karpenter_tpu.solver.encode import (
    EncodeCache,
    _columnar_group,
    _pod_signature_reference,
    encode,
    pod_signature,
    pod_signature_cached,
)
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.utils.quantity import Quantity
from test_solver import make_snapshot
from test_solvetrace import canon


def _zoo():
    """One pod per encoder-visible spec shape, fast paths and fall-throughs."""
    sel = {"matchLabels": {"app": "z"}}
    pods = [
        make_pod(cpu="500m"),  # the plain deployment-replica majority
        make_pod(cpu="1", memory="2Gi", labels={"app": "z", "tier": "web"}),
        make_pod(cpu="1", node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"}),
        make_pod(cpu="250m", labels={"app": "z"}, tsc=[zone_spread(selector=sel)]),  # affinity-free spread
        make_pod(cpu="1", labels={"app": "z"}, anti_affinity=[hostname_anti_affinity(sel)]),
        make_pod(cpu="1", required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}]]),
        make_pod(cpu="1", tolerations=[{"key": "dedicated", "operator": "Equal", "value": "gpu", "effect": "NoSchedule"}]),
        make_pod(cpu="1", volumes=[{"name": "d", "persistentVolumeClaim": {"claimName": "c1"}}]),
        make_pod(cpu="1", volumes=[{"name": "e", "ephemeral": {}}]),
    ]
    ported = make_pod(cpu="1")
    ported.spec.containers[0].ports = [{"containerPort": 80, "hostPort": 8080}]
    pods.append(ported)
    init = make_pod(cpu="1")
    init.spec.init_containers = [Container(resources={"requests": {"cpu": Quantity(200)}}, restart_policy="Always")]
    pods.append(init)
    ovh = make_pod(cpu="1")
    ovh.spec.overhead = {"cpu": Quantity(100)}
    pods.append(ovh)
    dra = make_pod(cpu="1")
    dra.spec.resource_claims = [{"name": "gpu", "resourceClaimName": "rc-1"}]
    pods.append(dra)
    multi = make_pod(cpu="1")
    multi.spec.containers.append(Container(resources={"requests": {"memory": Quantity(512), "cpu": Quantity(100)}}))
    pods.append(multi)
    return pods


class TestSignatureByteParity:
    def test_fast_paths_match_reference(self):
        for i, p in enumerate(_zoo()):
            assert pod_signature(p) == _pod_signature_reference(p), f"zoo[{i}]"

    def test_requirement_class_is_element_zero(self):
        p = make_pod(cpu="1", node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"})
        sig = pod_signature(p)
        assert sig[0] == ((tuple(sorted(p.spec.node_selector.items())), None))


class TestStampLifecycle:
    def test_hit_and_invalidate_on_rv_bump(self):
        p = make_pod(cpu="500m")
        s1 = pod_signature_cached(p)
        assert p._sig_stamp is not None and p._sig_stamp.sig is s1
        stamp = p._sig_stamp
        assert pod_signature_cached(p) is s1  # pure hit, same object
        assert p._sig_stamp is stamp  # untouched
        # spec change + rv bump (what the Store does on update)
        p.spec.node_selector = {wk.ZONE_LABEL_KEY: "test-zone-b"}
        p.metadata.resource_version += 1
        s2 = pod_signature_cached(p)
        assert s2 != s1 and p._sig_stamp is not stamp

    def test_stamp_never_survives_deepcopy(self):
        """preferences.py deep-copies a pod and mutates the COPY's spec in
        place with no rv bump — a surviving stamp would serve the original's
        signature for the relaxed pod. (A SHALLOW copy shares the spec object
        itself, so in-place mutation is equally invisible through original
        and copy — exactly the old (uid, rv)-keyed cache's semantics.)"""
        p = make_pod(cpu="500m")
        pod_signature_cached(p)
        assert copy.deepcopy(p)._sig_stamp is None
        dup = copy.deepcopy(p)
        dup.spec.node_selector = {wk.ZONE_LABEL_KEY: "test-zone-a"}
        assert pod_signature_cached(dup) != pod_signature_cached(p)

    def test_interning_collapses_replicas(self):
        a, b = make_pod(cpu="500m", ns="x"), make_pod(cpu="500m", ns="x")
        assert pod_signature_cached(a) is pod_signature_cached(b)

    def test_deepcopied_pods_group_without_crashing(self):
        """A deep-copied previously-stamped pod carries `_sig_stamp = None`
        (the attribute EXISTS and is None — not absent): the grouping pass
        must take the first-contact path for the whole list, not crash on
        the None stamp (regression: the rv read ran outside the guard)."""
        pods = [make_pod(cpu="500m") for _ in range(4)]
        for p in pods:
            pod_signature_cached(p)
        copies = [copy.deepcopy(p) for p in pods]
        assert all(c._sig_stamp is None for c in copies)
        grouped, _arts = _columnar_group(pods[:2] + copies)
        assert grouped is not None
        sig_of_pod, _, _ = grouped
        assert sig_of_pod.tolist() == [0] * 6  # replicas, one signature


class TestColumnarGroup:
    def test_matches_sequential_grouping(self):
        pods = []
        for i in range(40):
            if i % 3 == 0:
                pods.append(make_pod(cpu="500m"))
            elif i % 3 == 1:
                pods.append(make_pod(cpu="1", memory="2Gi", labels={"app": "z"}))
            else:
                pods.append(make_pod(cpu="250m", labels={"app": "z"}, tsc=[zone_spread(selector={"matchLabels": {"app": "z"}})]))
        for p in pods:
            pod_signature_cached(p)
        grouped, _arts = _columnar_group(pods)
        assert grouped is not None
        sig_of_pod, rep_idx, rep_keys = grouped
        # sequential reference: first-appearance sid allocation
        ids: dict = {}
        ref = []
        for p in pods:
            k = pod_signature_cached(p)
            ref.append(ids.setdefault(k, len(ids)))
        assert sig_of_pod.tolist() == ref
        assert [pod_signature_cached(pods[i]) for i in rep_idx.tolist()] == rep_keys

    def test_stamps_on_first_contact(self):
        pods = [make_pod(cpu="500m") for _ in range(5)]
        assert all(getattr(p, "_sig_stamp", None) is None for p in pods)
        grouped, _arts = _columnar_group(pods)  # first contact stamps the whole set
        assert grouped is not None
        assert all(p._sig_stamp is not None for p in pods)

    def test_pvc_pods_gate_to_sequential_loop(self):
        pods = [make_pod(cpu="500m"), make_pod(cpu="1", volumes=[{"name": "d", "persistentVolumeClaim": {"claimName": "c"}}])]
        for p in pods:
            pod_signature_cached(p)
        assert _columnar_group(pods)[0] is None  # volume components extend keys

    def test_ephemeral_volume_pods_gate_to_sequential_loop(self):
        """Generic-ephemeral volumes are claim-backed too (volumes.py
        has_pvc_volumes matches persistentVolumeClaim OR ephemeral): the
        columnar gate must route them through the sequential path exactly
        like PVC pods, or their signatures silently lose the resolved
        volume component (regression: the gate tested only \"pvc\")."""
        eph = make_pod(cpu="1", volumes=[{"name": "scratch", "ephemeral": {"volumeClaimTemplate": {"spec": {}}}}])
        sig = pod_signature_cached(eph)
        assert eph._sig_stamp.pvc, "stamp must flag ephemeral volumes as claim-backed"
        assert _columnar_group([make_pod(cpu="500m"), eph])[0] is None

    def test_group_memo_hit_and_rv_invalidation(self):
        import karpenter_tpu.solver.encode as E

        pods = [make_pod(cpu="500m") for _ in range(8)] + [make_pod(cpu="2")]
        g1, arts1 = _columnar_group(pods)
        g2, arts2 = _columnar_group(pods)  # unchanged ids+rvs: memo hit
        assert g2 is g1 and arts2 is arts1
        # rv bump on one pod invalidates the memo (content re-grouped)
        pods[3].metadata.resource_version += 1
        g3, arts3 = _columnar_group(pods)
        assert g3 is not g1
        assert g3[0].tolist() == g1[0].tolist()  # same content, same grouping
        # different pod list misses too
        g4, _ = _columnar_group(pods[:5])
        assert g4 is not g3

    def test_group_memo_arrays_are_frozen(self):
        import numpy as np
        import pytest as _pytest

        pods = [make_pod(cpu="500m") for _ in range(4)]
        grouped, _arts = _columnar_group(pods)
        sig_of_pod, rep_idx, _ = grouped
        with _pytest.raises(ValueError):
            sig_of_pod[0] = 1
        with _pytest.raises(ValueError):
            rep_idx[0] = 1


class TestEncodeParity:
    def _snap(self):
        pods = []
        for i in range(30):
            if i % 4 == 0:
                pods.append(make_pod(cpu="500m", memory="512Mi", name=f"a{i}"))
            elif i % 4 == 1:
                pods.append(make_pod(cpu="1", memory="2Gi", name=f"b{i}"))
            elif i % 4 == 2:
                pods.append(make_pod(cpu="250m", name=f"c{i}", labels={"app": "w"}, tsc=[zone_spread(selector={"matchLabels": {"app": "w"}})]))
            else:
                pods.append(make_pod(cpu="2", name=f"d{i}", node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"}))
        return make_snapshot(pods)

    def test_encode_fields_identical_vs_legacy(self, monkeypatch):
        # the SAME snapshot through both arms: pod uids are random per
        # construction and tiebreak the encode's lexsort, so two separately
        # built snapshots would differ in pod order for free
        snap = self._snap()
        e_col = encode(snap, cache=EncodeCache())
        monkeypatch.setenv("KARPENTER_ENCODE_COLUMNAR", "0")
        e_ref = encode(snap, cache=EncodeCache())
        assert e_col.n_sigs == e_ref.n_sigs
        assert np.array_equal(e_col.sig_of_pod, e_ref.sig_of_pod)
        assert np.array_equal(e_col.sig_req, e_ref.sig_req)
        assert np.array_equal(e_col.sig_mask, e_ref.sig_mask)
        assert np.array_equal(e_col.sig_dom_allowed, e_ref.sig_dom_allowed)
        assert [p.metadata.name for p in e_col.pods] == [p.metadata.name for p in e_ref.pods]

    def test_placements_bit_identical_vs_legacy(self, monkeypatch):
        snap = self._snap()
        r_col = TPUSolver(force=True).solve(snap)
        monkeypatch.setenv("KARPENTER_ENCODE_COLUMNAR", "0")
        r_ref = TPUSolver(force=True).solve(snap)
        assert canon(r_col) == canon(r_ref)

    def test_uncached_encode_never_stamps(self):
        """encode(snap) without a cache must not stamp: in-place pod mutation
        between uncached encodes stays visible, exactly as before."""
        snap = self._snap()
        encode(snap)
        assert all(getattr(p, "_sig_stamp", None) is None for p in snap.pods)
