"""TPU annealed consolidation: quality vs the binary-search baseline."""

import numpy as np
import pytest

from helpers import make_nodepool, make_pod, hostname_anti_affinity
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import FeatureGates, Options

OD_ONLY = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
    {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]},
]


def build_fleet(n_nodes=6, solver_backend="ffd"):
    """A fleet of underutilized 1-pod nodes via anti-affinity, then relaxed."""
    env = Environment(options=Options(solver_backend=solver_backend))
    np_ = make_nodepool(requirements=OD_ONLY)
    np_.spec.disruption.consolidate_after = "30s"
    np_.spec.disruption.budgets = [Budget(nodes="100%")]
    env.store.create(np_)
    sel = {"matchLabels": {"app": "x"}}
    pods = [
        make_pod(cpu="500m", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
        for i in range(n_nodes)
    ]
    for p in pods:
        env.store.create(p)
    env.settle()
    assert env.store.count("Node") == n_nodes
    for p in pods:
        env.store.delete("Pod", p.metadata.name)
    for i in range(n_nodes):
        env.store.create(make_pod(cpu="500m", name=f"f{i}"))
    env.settle(rounds=4)
    return env


class TestAnnealModel:
    def test_objective_prefers_feasible_savings(self):
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.models.consolidation_model import ConsolidationTensors, _objective, anneal

        # 3 nodes each $1 with slack to absorb one other's pods
        t = ConsolidationTensors(
            node_price=jnp.array([1.0, 1.0, 1.0]),
            node_cost=jnp.array([0.1, 0.1, 0.1]),
            node_slack=jnp.array([[4.0], [4.0], [4.0]]),
            node_used=jnp.array([[1.0], [1.0], [1.0]]),
            node_npods=jnp.array([1.0, 1.0, 1.0]),
            pod_compat=jnp.ones((3, 3)).at[jnp.diag_indices(3)].set(0),
            row_alloc=jnp.array([[8.0]]),
            row_price=jnp.array([0.5]),
        )
        s_none, f = _objective(t, jnp.array([False, False, False]))
        assert float(s_none) == 0.0
        s_two, f2 = _objective(t, jnp.array([True, True, False]))
        assert bool(f2) and float(s_two) > 0  # delete 2, pods fit node 3
        best_x, best_s = anneal(t, jax.random.PRNGKey(0), n_chains=8, n_steps=128)
        assert float(np.max(np.asarray(best_s))) >= float(s_two)

    def test_propose_subsets_on_real_candidates(self):
        env = build_fleet(4)
        # flip Consolidatable without running the disruption loop (which would
        # consolidate the fleet out from under the proposal test)
        env.clock.step(40)
        env.nodeclaim_disruption.reconcile()
        cands = env.disruption.get_candidates()
        assert len(cands) == 4
        from karpenter_tpu.solver.consolidation import propose_subsets

        its = env.cloud_provider.get_instance_types()
        proposals = propose_subsets(cands, its)
        assert proposals, "annealer should find profitable subsets"
        # proposals are ordered best-first and non-trivial
        assert all(len(s) >= 1 for s in proposals)


class TestTPUConsolidationE2E:
    def test_fleet_shrinks_with_tpu_backend(self):
        env = build_fleet(5, solver_backend="tpu")
        n0 = env.store.count("Node")
        for _ in range(20):
            env.clock.step(15)
            env.tick(provision_force=True)
        n1 = env.store.count("Node")
        assert n1 < n0
        assert all(p.spec.node_name for p in env.store.list("Pod"))


class TestAnnealQuality:
    def test_anneal_savings_at_least_95pct_of_binary_search(self):
        """VERDICT r2 #8: on an underutilized fleet the annealed subset search
        must recover >= 95% of the savings the reference's binary search
        (multinodeconsolidation.go:117-191) finds, both exact-validated."""
        from bench import _command_savings, bench_consolidation  # reuses the real path

        from karpenter_tpu.controllers.disruption.methods import MultiNodeConsolidation
        from karpenter_tpu.solver.consolidation import propose_subsets

        # build the same fleet shape as the bench, smaller
        import bench as bench_mod

        env_nodes = 24
        secs, extra = bench_mod.bench_consolidation(env_nodes)
        ratio = extra["anneal_vs_binary_search_savings"]
        assert extra["binary_search_savings_per_hour"] > 0
        assert ratio is not None and ratio >= 0.95, f"anneal recovered only {ratio} of binary-search savings ({extra})"
        assert extra["proposal_acceptance_rate"] > 0
