"""Cluster/StateNode mirror behavior (reference: pkg/controllers/state suite)."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.kube import Container, Node, ObjectMeta, Pod, PodSpec
from karpenter_tpu.kube.objects import NodeSpec, NodeStatus, OwnerReference
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.kube import Store
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.resources import parse_resource_list


def mknode(name, pid=None, cpu="4", nodepool="default-pool"):
    return Node(
        metadata=ObjectMeta(name=name, labels={wk.NODEPOOL_LABEL_KEY: nodepool, wk.HOSTNAME_LABEL_KEY: name}),
        spec=NodeSpec(provider_id=pid or f"kwok://{name}"),
        status=NodeStatus(
            capacity=parse_resource_list({"cpu": cpu, "memory": "8Gi", "pods": "110"}),
            allocatable=parse_resource_list({"cpu": cpu, "memory": "7Gi", "pods": "110"}),
        ),
    )


def mkpod(name, node_name="", cpu="1", ns="default", daemonset=False):
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            node_name=node_name,
            containers=[Container(resources={"requests": parse_resource_list({"cpu": cpu})})],
        ),
    )
    if daemonset:
        pod.metadata.owner_references = [OwnerReference(kind="DaemonSet", name="ds", uid="u1")]
    return pod


class TestCluster:
    def setup_method(self):
        self.store = Store()
        self.clock = FakeClock()
        self.cluster = Cluster(self.store, self.clock)
        start_informers(self.store, self.cluster)

    def test_node_lifecycle(self):
        self.store.create(mknode("n1"))
        nodes = self.cluster.nodes()
        assert len(nodes) == 1 and nodes[0].name() == "n1"
        self.store.delete("Node", "n1")
        assert self.cluster.nodes() == []

    def test_pod_binding_updates_usage(self):
        self.store.create(mknode("n1"))
        self.store.create(mkpod("p1", node_name="n1", cpu="2"))
        sn = self.cluster.node_for_name("n1")
        assert sn.total_pod_requests()["cpu"].value == 2
        assert sn.available()["cpu"].value == 2  # 4 - 2
        self.store.delete("Pod", "p1")
        sn = self.cluster.node_for_name("n1")
        assert sn.available()["cpu"].value == 4

    def test_daemonset_requests_tracked(self):
        self.store.create(mknode("n1"))
        self.store.create(mkpod("ds-pod", node_name="n1", cpu="1", daemonset=True))
        sn = self.cluster.node_for_name("n1")
        assert sn.total_daemon_requests()["cpu"].value == 1

    def test_claim_then_node_pairing(self):
        nc = NodeClaim(metadata=ObjectMeta(name="claim-1", labels={wk.NODEPOOL_LABEL_KEY: "default-pool"}))
        nc.status.provider_id = "kwok://n1"
        nc.status.capacity = parse_resource_list({"cpu": "4"})
        self.store.create(nc)
        assert len(self.cluster.nodes()) == 1
        assert self.cluster.nodes()[0].node is None
        # node arrives with same provider id -> same StateNode
        self.store.create(mknode("n1", pid="kwok://n1"))
        nodes = self.cluster.nodes()
        assert len(nodes) == 1
        assert nodes[0].node is not None and nodes[0].node_claim is not None

    def test_pods_bound_before_node_known_are_replayed(self):
        self.store.create(mkpod("p1", node_name="n1", cpu="2"))
        self.store.create(mknode("n1"))
        sn = self.cluster.node_for_name("n1")
        assert sn.total_pod_requests()["cpu"].value == 2

    def test_synced_gate(self):
        assert self.cluster.synced()
        nc = NodeClaim(metadata=ObjectMeta(name="c1"))
        nc.status.provider_id = "kwok://nx"
        self.store.create(nc)
        assert self.cluster.synced()  # informer saw it

    def test_marked_for_deletion_on_claim_deleting(self):
        nc = NodeClaim(metadata=ObjectMeta(name="c1", finalizers=["karpenter.sh/termination"]))
        nc.status.provider_id = "kwok://n1"
        self.store.create(nc)
        self.store.delete("NodeClaim", "c1")
        assert self.cluster.nodes()[0].marked_for_deletion

    def test_consolidated_timestamp(self):
        self.cluster.mark_consolidated()
        assert self.cluster.consolidated()
        self.store.create(mkpod("p1"))  # any change invalidates
        assert not self.cluster.consolidated()

    def test_nodepool_resources(self):
        self.store.create(mknode("n1", cpu="4"))
        self.store.create(mknode("n2", cpu="8"))
        total = self.cluster.nodepool_resources("default-pool")
        assert total["cpu"].value == 12
        assert self.cluster.nodepool_node_count("default-pool") == 2

    def test_nomination_window(self):
        self.store.create(mknode("n1"))
        self.cluster.nominate_node("n1")
        sn = self.cluster.node_for_name("n1")
        assert sn.nominated(self.clock.now())
        assert sn.validate_node_disruptable(self.clock.now()) is not None
        self.clock.step(30)
        sn = self.cluster.node_for_name("n1")
        assert not sn.nominated(self.clock.now())
