"""Static capacity behavior specs (reference: test/suites/regression static
specs + static/{provisioning,deprovisioning} controller tests)."""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_DRIFTED
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env(**kw):
    return Environment(options=Options(**kw))


def static_pool(replicas, name="static-pool", **kw):
    return make_nodepool(name=name, requirements=LINUX_AMD64, replicas=replicas, **kw)


class TestStaticProvisioning:
    def test_scales_to_replica_count(self):
        env = make_env()
        env.store.create(static_pool(3))
        env.settle()
        assert env.store.count("NodeClaim") == 3
        assert env.store.count("Node") == 3
        for nc in env.store.list("NodeClaim"):
            assert nc.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "static-pool"
            assert nc.is_registered()

    def test_replica_increase_scales_up(self):
        env = make_env()
        env.store.create(static_pool(1))
        env.settle()
        assert env.store.count("NodeClaim") == 1

        def bump(np):
            np.spec.replicas = 4

        env.store.patch("NodePool", "static-pool", bump)
        env.settle()
        assert env.store.count("NodeClaim") == 4

    def test_node_limit_caps_fleet(self):
        env = make_env()
        env.store.create(static_pool(5, limits={"nodes": "2"}))
        env.settle()
        assert env.store.count("NodeClaim") == 2

    def test_static_pool_ignores_pending_pods(self):
        # static pools never grow beyond replicas for demand; a huge pending
        # pod must not trigger extra static capacity
        env = make_env()
        env.store.create(static_pool(1))
        env.store.create(make_pod(cpu="1000"))
        env.settle()
        assert env.store.count("NodeClaim") == 1

    def test_deleted_claim_is_replaced(self):
        env = make_env()
        env.store.create(static_pool(2))
        env.settle()
        victim = env.store.list("NodeClaim")[0]
        env.store.delete("NodeClaim", victim.metadata.name)
        env.settle(rounds=15)
        live = [nc for nc in env.store.list("NodeClaim") if nc.metadata.deletion_timestamp is None]
        assert len(live) == 2


class TestStaticDeprovisioning:
    def test_replica_decrease_scales_down(self):
        env = make_env()
        env.store.create(static_pool(4))
        env.settle()
        assert env.store.count("NodeClaim") == 4

        def shrink(np):
            np.spec.replicas = 2

        env.store.patch("NodePool", "static-pool", shrink)
        env.settle(rounds=15)
        live = [nc for nc in env.store.list("NodeClaim") if nc.metadata.deletion_timestamp is None]
        assert len(live) == 2
        assert env.store.count("Node") == 2

    def test_empty_nodes_picked_before_loaded(self):
        env = make_env()
        env.store.create(static_pool(2))
        env.settle()
        nodes = env.store.list("Node")
        # pin a pod to the first node so it's "loaded"
        loaded = nodes[0].metadata.name
        pod = make_pod(cpu="100m", node_name=loaded)
        pod.status.phase = "Running"
        env.store.create(pod)
        env.settle(rounds=3)

        def shrink(np):
            np.spec.replicas = 1

        env.store.patch("NodePool", "static-pool", shrink)
        env.settle(rounds=15)
        remaining = [n.metadata.name for n in env.store.list("Node")]
        assert remaining == [loaded]

    def test_zero_replicas_drains_fleet(self):
        env = make_env()
        env.store.create(static_pool(2))
        env.settle()

        def zero(np):
            np.spec.replicas = 0

        env.store.patch("NodePool", "static-pool", zero)
        env.settle(rounds=20)
        assert env.store.count("Node") == 0
        live = [nc for nc in env.store.list("NodeClaim") if nc.metadata.deletion_timestamp is None]
        assert not live


class TestStaticDrift:
    def test_drifted_static_claims_replaced_one_for_one(self):
        env = make_env()
        env.store.create(static_pool(2))
        env.settle()
        before = {nc.metadata.name for nc in env.store.list("NodeClaim")}

        def relabel(np):
            np.spec.template.labels = {"fleet-gen": "v2"}  # changes static hash

        env.store.patch("NodePool", "static-pool", relabel)
        env.settle(rounds=40, step_seconds=15.0)
        live = [nc for nc in env.store.list("NodeClaim") if nc.metadata.deletion_timestamp is None]
        assert len(live) == 2
        assert not (before & {nc.metadata.name for nc in live})
        assert all(nc.metadata.labels.get("fleet-gen") == "v2" for nc in live)

    def test_static_nodes_never_consolidated(self):
        # two empty static nodes stay: emptiness/consolidation must skip them
        env = make_env()
        env.store.create(static_pool(2))
        env.settle(rounds=20, step_seconds=30.0)
        assert env.store.count("Node") == 2
