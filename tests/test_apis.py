"""NodePool budgets math, conditions, durations/cron — modeled on the
reference's pkg/apis/v1 suite coverage."""

import pytest

from karpenter_tpu.apis.conditions import ConditionSet
from karpenter_tpu.apis.nodepool import (
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
    Budget,
    NodePool,
)
from karpenter_tpu.utils.durations import Cron, parse_duration

NOW = 1_700_000_000.0  # 2023-11-14T22:13:20Z (Tuesday)


class TestDurations:
    def test_parse(self):
        assert parse_duration("10s") == 10
        assert parse_duration("1h30m") == 5400
        assert parse_duration("Never") == float("inf")
        with pytest.raises(ValueError):
            parse_duration("10x")

    def test_cron_basic(self):
        c = Cron("* * * * *")
        assert c.active_within(NOW, 60)

    def test_cron_macro(self):
        c = Cron("@daily")
        # within last 24h there is always a midnight
        assert c.active_within(NOW, 24 * 3600)
        assert not c.active_within(NOW, 60)  # 22:13 is not midnight


class TestBudget:
    def test_percentage_rounds_up(self):
        b = Budget(nodes="10%")
        assert b.allowed_disruptions(NOW, 10) == (1, None)
        assert b.allowed_disruptions(NOW, 5) == (1, None)  # ceil(0.5)
        assert b.allowed_disruptions(NOW, 0) == (0, None)

    def test_absolute(self):
        assert Budget(nodes="5").allowed_disruptions(NOW, 100) == (5, None)
        assert Budget(nodes="0").allowed_disruptions(NOW, 100) == (0, None)

    def test_inactive_schedule_unbounded(self):
        # schedule fires at midnight for 1h; NOW is 22:13 -> inactive
        b = Budget(nodes="0", schedule="0 0 * * *", duration="1h")
        allowed, err = b.allowed_disruptions(NOW, 100)
        assert err is None and allowed == 2**31 - 1

    def test_active_schedule(self):
        # every hour on the hour, 30m duration; 22:13 is within [22:00, 22:30]
        b = Budget(nodes="3", schedule="0 * * * *", duration="30m")
        assert b.allowed_disruptions(NOW, 100) == (3, None)

    def test_misconfigured_fails_closed(self):
        b = Budget(nodes="5", schedule="bad cron here really bad", duration="1h")
        allowed, err = b.allowed_disruptions(NOW, 100)
        assert allowed == 0 and err is not None

    def test_nodepool_most_restrictive(self):
        np = NodePool()
        np.spec.disruption.budgets = [
            Budget(nodes="10"),
            Budget(nodes="5", reasons=[REASON_EMPTY]),
            Budget(nodes="2", reasons=[REASON_DRIFTED]),
        ]
        assert np.allowed_disruptions(NOW, 100, REASON_UNDERUTILIZED) == 10
        assert np.allowed_disruptions(NOW, 100, REASON_EMPTY) == 5
        assert np.allowed_disruptions(NOW, 100, REASON_DRIFTED) == 2


class TestNodePool:
    def test_hash_ignores_requirements(self):
        a, b = NodePool(), NodePool()
        b.spec.template.requirements = [{"key": "zone", "operator": "In", "values": ["a"]}]
        assert a.hash() == b.hash()
        b.spec.template.labels = {"x": "1"}
        assert a.hash() != b.hash()

    def test_limits(self):
        from karpenter_tpu.utils.quantity import Quantity

        np = NodePool()
        np.spec.limits = {"cpu": Quantity.parse("10")}
        assert np.limits_exceeded_by({"cpu": Quantity.parse("8")}) is None
        assert np.limits_exceeded_by({"cpu": Quantity.parse("12")}) is not None
        assert np.limits_exceeded_by({"memory": Quantity.parse("1Ti")}) is None  # unlimited


class TestConditions:
    def test_set_transitions(self):
        cs = ConditionSet()
        assert cs.set_true("Launched", now=1.0)
        assert cs.is_true("Launched")
        assert not cs.set_true("Launched", now=2.0)  # no transition
        assert cs.get("Launched").last_transition_time == 1.0
        assert cs.set_false("Launched", "gone", now=3.0)
        assert cs.get("Launched").last_transition_time == 3.0
