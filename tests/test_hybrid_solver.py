"""Hybrid partitioned solve: tensor majority + exact host FFD residual.

One out-of-window pod must no longer demote the whole snapshot to the host
FFD. When every fallback reason is pod-local and the flagged residual is
constraint-independent of the rest, the solver packs the in-window majority
on the tensor path and runs the host scheduler only on the residual —
against the tensor result's node state, so residual pods schedule INTO the
freshly proposed claims (no double-provisioning) and the merged placement
stays feasible under the pure host oracle.
"""

import pytest

from helpers import hostname_anti_affinity, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube.objects import (
    Affinity,
    Container,
    PodAffinityTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.solver import FFDSolver
from karpenter_tpu.solver.encode import check_capability, encode, hybrid_partition
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results
from test_solver import make_snapshot


def preferred_affinity_pod(name="odd", cpu="500m", labels=None, ports=None):
    """A pod whose ONLY out-of-window constraint is preferred pod affinity —
    the canonical pod-local fallback reason."""
    p = make_pod(cpu=cpu, name=name, labels=labels)
    if ports:
        p.spec.containers = [Container(resources=p.spec.containers[0].resources, ports=ports)]
    p.spec.affinity = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=1,
                term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
    )
    return p


class TestPartition:
    def test_pod_local_reason_partitions(self):
        pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(6)] + [preferred_affinity_pod()]
        snap = make_snapshot(pods)
        enc = encode(snap)
        assert enc.fallback_reasons and not enc.fallback_has_global
        part = hybrid_partition(snap, enc)
        assert part is not None
        tensor_pods, residual_pods = part
        assert len(tensor_pods) == 6 and len(residual_pods) == 1
        assert residual_pods[0].metadata.name == "odd"

    def test_global_reason_blocks_partition(self):
        # asymmetric anti-affinity: the selector matches pods that do not
        # declare it — a snapshot-global symmetry failure
        sel = {"matchLabels": {"app": "other"}}
        pods = [make_pod(cpu="1", labels={"app": "me"}, anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)])] + [
            make_pod(cpu="1", labels={"app": "other"}) for _ in range(3)
        ]
        snap = make_snapshot(pods)
        enc = encode(snap)
        assert enc.fallback_has_global
        assert hybrid_partition(snap, enc) is None

    def test_all_pods_flagged_blocks_partition(self):
        snap = make_snapshot([preferred_affinity_pod(name=f"o{i}") for i in range(3)])
        enc = encode(snap)
        assert hybrid_partition(snap, enc) is None

    def test_shared_spread_group_partitions_with_seam_export(self):
        # the flagged pod (preferred pod affinity — pod-local) declares the
        # SAME zone spread as the tensor-side pods. PR 3: spread groups may
        # span the seam — the solver exports the tensor side's zone
        # occupancy into the residual Topology, so the split preserves the
        # joint skew accounting instead of forcing whole-snapshot FFD.
        sel = {"matchLabels": {"app": "w"}}
        spread = zone_spread(selector=sel)
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[spread]) for _ in range(4)]
        multi = make_pod(cpu="1", name="multi", labels={"app": "w"}, tsc=[spread])
        multi.spec.affinity = Affinity(
            pod_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=1,
                    term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
                )
            ]
        )
        pods.append(multi)
        snap = make_snapshot(pods)
        enc = encode(snap)
        assert any("preferred pod affinity" in r for r in enc.fallback_reasons)
        assert not enc.fallback_has_global
        part = hybrid_partition(snap, enc)
        assert part is not None
        _tensor, residual = part
        assert [p.metadata.name for p in residual] == ["multi"]
        # the solver runs hybrid and the COMBINED zone skew stays <= 1
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "hybrid"
        assert not results.pod_errors
        zone_counts: dict[str, int] = {}
        for nc in results.new_node_claims:
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            members = [p for p in nc.pods if p.metadata.labels.get("app") == "w"]
            if members:
                assert len(zr.values) == 1, "spread member claim must commit to one zone"
                z = next(iter(zr.values))
                zone_counts[z] = zone_counts.get(z, 0) + len(members)
        observed = [c for c in zone_counts.values() if c > 0]
        assert observed and max(observed) - min(observed) <= 1, zone_counts

    def test_shared_affinity_group_still_blocks_partition(self):
        # AFFINITY kinds keep the coupling gate: bootstrap/blocking semantics
        # cannot split. The flagged pod shares a required zone pod-affinity
        # group with the tensor side (symmetric selector), plus a pod-local
        # reason on the same pod ("pod affinity combined with other topology
        # constraints": a self-selecting hostname spread rides along).
        sel = {"matchLabels": {"grp": "co"}}
        aff_term = PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)
        pods = [make_pod(cpu="1", labels={"grp": "co"}, pod_affinity=[aff_term]) for _ in range(3)]
        flagged = make_pod(
            cpu="1",
            name="flagged",
            labels={"grp": "co", "f": "x"},
            pod_affinity=[aff_term],
            tsc=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.HOSTNAME_LABEL_KEY,
                    label_selector={"matchLabels": {"f": "x"}},
                )
            ],
        )
        pods.append(flagged)
        snap = make_snapshot(pods)
        enc = encode(snap)
        assert enc.fallback_reasons and not enc.fallback_has_global, enc.fallback_reasons
        assert hybrid_partition(snap, enc) is None
        solver = TPUSolver()
        solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert solver.last_solve_mode == "fallback"

    def test_capability_report_collects_all_reason_families(self):
        # one pod per family: the first-pod break used to hide all but one
        dra = make_pod(cpu="1", name="dra")
        dra.spec.resource_claims = [{"name": "gpu"}]
        pods = [
            make_pod(cpu="1", name="plain"),
            preferred_affinity_pod(name="pref"),
            make_pod(
                cpu="1",
                name="multi",
                labels={"app": "m"},
                tsc=[
                    zone_spread(selector={"matchLabels": {"app": "m"}}),
                    TopologySpreadConstraint(max_skew=1, topology_key="rack", label_selector={"matchLabels": {"app": "m"}}),
                ],
            ),
            dra,
        ]
        reasons = check_capability(make_snapshot(pods))
        joined = " ".join(reasons)
        assert "preferred pod affinity" in joined
        assert "multiple domain keys" in joined
        assert "dynamic resource claims" in joined


class TestHybridSolve:
    def test_merged_placement_is_complete_and_valid(self):
        pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(8)] + [preferred_affinity_pod()]
        snap = make_snapshot(pods)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "hybrid"
        assert solver.last_solve_mode == "hybrid"
        assert results.all_pods_scheduled()
        assert not validate_results(make_snapshot(pods), results)

    def test_residual_reuses_tensor_claim_capacity(self):
        # the tensor majority opens claims with headroom; the residual pod
        # must land on one of them (in-flight capacity), NOT a fresh claim
        pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(6)] + [preferred_affinity_pod()]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "hybrid"
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1
        names = {p.metadata.name for nc in results.new_node_claims for p in nc.pods}
        assert "odd" in names

    def test_parity_with_pure_ffd(self):
        # the hybrid result schedules the same pod set the pure host solver
        # does, and every placement is feasible under exact validation
        pods = (
            [make_pod(cpu="1", name=f"a{i}") for i in range(5)]
            + [make_pod(cpu="2", memory="4Gi", name=f"b{i}") for i in range(3)]
            + [make_pod(cpu="1", name=f"z{i}", node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"}) for i in range(2)]
            + [preferred_affinity_pod(name=f"odd{i}", cpu="1") for i in range(2)]
        )
        solver = TPUSolver()
        hybrid_results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "hybrid"
        ffd_results = FFDSolver().solve(make_snapshot(pods))
        assert set(hybrid_results.pod_errors) == set(ffd_results.pod_errors) == set()
        assert not validate_results(make_snapshot(pods), hybrid_results)

    def test_residual_sees_tensor_host_ports(self):
        # the tensor half holds hostPort 80 on its claim; a ported residual
        # pod must open its own node instead of conflicting
        ports = [{"containerPort": 80, "hostPort": 80}]
        tensor_ported = make_pod(cpu="100m", name="t-ported")
        tensor_ported.spec.containers = [
            Container(resources=tensor_ported.spec.containers[0].resources, ports=ports)
        ]
        pods = [tensor_ported, preferred_affinity_pod(name="r-ported", cpu="100m", ports=ports)]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "hybrid"
        assert results.all_pods_scheduled()
        by_claim = [{p.metadata.name for p in nc.pods} for nc in results.new_node_claims]
        assert not any({"t-ported", "r-ported"} <= names for names in by_claim)
        assert len(results.new_node_claims) == 2

    def test_residual_respects_tensor_consumption_on_existing_nodes(self):
        # tiny fleet: the tensor half fills the existing node; the residual
        # must overflow to a new claim, not overcommit the node
        from test_sharded import existing_node_snapshot

        types = [catalog.make_instance_type("c", 4, zones=["test-zone-a"])]
        pods = [make_pod(cpu="1500m", name=f"p{i}") for i in range(2)] + [
            preferred_affinity_pod(name="odd", cpu="1500m")
        ]
        snap = existing_node_snapshot(pods, types)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "hybrid"
        assert results.all_pods_scheduled()
        snap2 = existing_node_snapshot(pods, types)
        assert not validate_results(snap2, results)

    def test_hybrid_disabled_keeps_whole_snapshot_fallback(self):
        pods = [make_pod(cpu="500m") for _ in range(4)] + [preferred_affinity_pod()]
        solver = TPUSolver(hybrid=False)
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert solver.last_solve_mode == "fallback"
        assert results.all_pods_scheduled()

    def test_force_still_raises_on_out_of_window(self):
        pods = [make_pod(cpu="500m"), preferred_affinity_pod()]
        with pytest.raises(RuntimeError, match="unsupported"):
            TPUSolver(force=True).solve(make_snapshot(pods))

    def test_metrics_backend_and_reason_labels(self):
        from karpenter_tpu.metrics import (
            SOLVER_FALLBACK_TOTAL,
            SOLVER_HYBRID_RESIDUAL_TOTAL,
            SOLVER_SOLVE_TOTAL,
            make_registry,
        )

        registry = make_registry()
        pods = [make_pod(cpu="500m") for _ in range(4)] + [preferred_affinity_pod()]
        solver = TPUSolver(registry=registry)
        solver.solve(make_snapshot(pods))
        assert solver.last_backend == "hybrid"
        assert registry.counter(SOLVER_SOLVE_TOTAL).value(backend="hybrid") == 1
        # the tensor sub-solve must not double-count as a tpu-backend solve
        assert registry.counter(SOLVER_SOLVE_TOTAL).value(backend="tpu") == 0
        assert registry.counter(SOLVER_FALLBACK_TOTAL).total() == 0
        assert registry.counter(SOLVER_HYBRID_RESIDUAL_TOTAL).value(reason="pod-affinity") == 1
        # the reasons stay observable on the solver
        assert any("preferred pod affinity" in r for r in solver.last_fallback_reasons)

    def test_solve_mode_set_on_every_exit_path(self):
        # full
        solver = TPUSolver()
        solver.solve(make_snapshot([make_pod(cpu="1")]))
        assert solver.last_solve_mode == "full"
        assert solver.last_backend == "tpu"
        # fallback (global reason: empty snapshot)
        solver2 = TPUSolver()
        solver2.solve(make_snapshot([]))
        assert solver2.last_solve_mode == "fallback"
        assert solver2.last_backend == "ffd-fallback"
        # hybrid
        solver3 = TPUSolver()
        solver3.solve(make_snapshot([make_pod(cpu="1"), preferred_affinity_pod()]))
        assert solver3.last_solve_mode == "hybrid"


class TestReasonFamilyEnum:
    """Tier-1 regression: every reason string `check_capability` emits maps
    to a known fallback family (no unlabeled-cardinality metrics), and every
    family has a hybrid tier."""

    def test_every_family_has_a_tier(self):
        from karpenter_tpu.solver.fallback import FAMILY_TIERS, GLOBAL, POD_LOCAL, REASON_FAMILIES

        for _needle, family in REASON_FAMILIES:
            assert family in FAMILY_TIERS, f"family {family} has no hybrid tier"
            assert FAMILY_TIERS[family] in (GLOBAL, POD_LOCAL)
        assert FAMILY_TIERS["other"] == GLOBAL  # unknown reasons stay conservative

    def _reason_battery(self):
        """Snapshots covering the emitted reason space; yields reason lists."""
        from karpenter_tpu.scheduling.requirements import Requirement  # noqa: F401

        sel = {"matchLabels": {"app": "x"}}
        rack_spread = TopologySpreadConstraint(max_skew=1, topology_key="rack", label_selector=sel)
        dra = make_pod(cpu="1")
        dra.spec.resource_claims = [{"name": "gpu"}]
        honor_taints = zone_spread(selector=sel)
        honor_taints.node_taints_policy = "Honor"
        batteries = [
            # asymmetric memberships (anti / spread / affinity)
            [make_pod(labels={"app": "me"}, anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)]), make_pod(labels={"app": "x"})],
            [make_pod(labels={"app": "me"}, tsc=[zone_spread(selector=sel)]), make_pod(labels={"app": "x"})],
            [make_pod(labels={"app": "me"}, pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)]), make_pod(labels={"app": "x"})],
            # pod-local families
            [preferred_affinity_pod()],
            [make_pod(labels={"app": "x"}, pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY), PodAffinityTerm(label_selector=sel, topology_key="rack")])],
            [make_pod(labels={"app": "x"}, pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY, namespaces=["other"])])],
            [make_pod(labels={"app": "x"}, anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.HOSTNAME_LABEL_KEY, namespaces=["other"])])],
            [make_pod(labels={"app": "x"}, tsc=[zone_spread(selector=sel), rack_spread])],
            [make_pod(labels={"app": "x"}, tsc=[honor_taints])],
            [dra],
        ]
        for pods in batteries:
            yield check_capability(make_snapshot(pods))

    def test_every_emitted_reason_maps_to_a_family(self):
        from karpenter_tpu.solver.fallback import FAMILY_TIERS, reason_family

        seen = set()
        for reasons in self._reason_battery():
            assert reasons, "battery snapshot unexpectedly in-window"
            for r in reasons:
                fam = reason_family(r)
                assert fam != "other", f"unmapped reason: {r}"
                assert fam in FAMILY_TIERS
                seen.add(fam)
        assert len(seen) >= 8  # the battery spans a real breadth of families

    def test_min_values_and_strict_reserved_map(self):
        from karpenter_tpu.solver.fallback import reason_family

        assert reason_family("nodepool uses minValues") == "min-values"
        assert reason_family("strict reserved-offering mode with reserved offerings") == "strict-reserved-offering"
        assert reason_family("empty snapshot") == "empty"
        assert reason_family("validation: host port conflict on slot 3") == "validation"
        assert reason_family("relaxation required: soft constraints unsatisfiable tier-0") == "relaxation"


@pytest.mark.slow
class TestHybridBenchScale:
    """The ISSUE 1 acceptance scenario at bench scale: a 10k-pod snapshot
    with 5% out-of-window (preferred-affinity) pods must solve on the hybrid
    path with a complete, valid placement. Timing is asserted by the bench
    driver on TPU hardware (`hybrid_10000pods_seconds` <= 5s); this test
    pins the correctness half so the bench number can be trusted."""

    def test_10k_pod_hybrid_scenario(self):
        import time

        from bench import build_snapshot

        snap = build_snapshot(10000, 100, fallback_frac=0.05)
        solver = TPUSolver()
        t0 = time.perf_counter()
        results = solver.solve(snap)
        dt = time.perf_counter() - t0
        assert solver.last_backend == "hybrid", solver.last_fallback_reasons[:3]
        assert not results.pod_errors
        placed = sum(len(nc.pods) for nc in results.new_node_claims) + sum(
            len(en.pods) for en in results.existing_nodes
        )
        assert placed == 10000
        print(f"hybrid 10k-pod solve: {dt:.2f}s")


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
