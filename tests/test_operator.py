"""Operator runtime specs: leader election, health/metrics endpoints, run
loop (reference: operator.go:126-252)."""

import threading
import time
import urllib.request

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube import Store
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.leaderelection import LeaderElector
from karpenter_tpu.operator.options import Options
from karpenter_tpu.operator.server import OperatorServer
from karpenter_tpu.utils.clock import Clock, FakeClock

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


class TestLeaderElection:
    def test_single_instance_acquires(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, clock, "a")
        assert a.try_acquire_or_renew()
        assert a.is_leader()

    def test_standby_waits_then_takes_over(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, clock, "a")
        b = LeaderElector(store, clock, "b")
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()  # active/standby
        clock.step(5)
        assert a.try_acquire_or_renew()  # renew keeps the lease
        assert not b.try_acquire_or_renew()
        clock.step(16)  # a stops renewing; lease lapses
        assert b.try_acquire_or_renew()
        assert b.is_leader()
        # a discovers it lost on its next renewal attempt
        assert not a.try_acquire_or_renew()
        lease = store.get("Lease", "karpenter-leader-election", "kube-system")
        assert lease.holder_identity == "b"
        assert lease.lease_transitions == 1

    def test_stale_leader_stops_acting_after_renew_deadline(self):
        # a leader whose renewals stopped must consider itself demoted before
        # a standby could legitimately take the lapsed lease
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, clock, "a")
        assert a.try_acquire_or_renew() and a.is_leader()
        clock.step(11)  # > renew_deadline (10s), < takeover not needed
        assert not a.is_leader()
        assert a.try_acquire_or_renew() and a.is_leader()  # renewing restores

    def test_release_by_stale_loser_does_not_touch_lease(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, clock, "a")
        b = LeaderElector(store, clock, "b")
        assert a.try_acquire_or_renew()
        clock.step(16)
        assert b.try_acquire_or_renew()
        rv_before = store.get("Lease", "karpenter-leader-election", "kube-system").metadata.resource_version
        a.release()  # a never observed the loss; must not write
        lease = store.get("Lease", "karpenter-leader-election", "kube-system")
        assert lease.holder_identity == "b"
        assert lease.metadata.resource_version == rv_before

    def test_release_enables_fast_failover(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, clock, "a")
        b = LeaderElector(store, clock, "b")
        assert a.try_acquire_or_renew()
        a.release()
        clock.step(16)  # released lease reads as lapsed immediately
        assert b.try_acquire_or_renew()


class TestOperatorServer:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()

    def test_healthz_readyz_metrics(self):
        env = Environment(options=Options())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        server = OperatorServer(env, port=0)
        port = server.start()
        try:
            code, body = self._get(port, "/healthz")
            assert code == 200 and body == "ok"
            code, _ = self._get(port, "/readyz")
            assert code == 200  # empty cluster state is synced
            env.store.create(make_pod(cpu="1"))
            env.settle()
            code, body = self._get(port, "/metrics")
            assert code == 200
            assert "karpenter_nodeclaims_created_total" in body
        finally:
            server.stop()

    def test_profiling_gated(self):
        env = Environment(options=Options())
        server = OperatorServer(env, port=0, enable_profiling=False)
        port = server.start()
        try:
            import urllib.error

            try:
                code, _ = self._get(port, "/debug/profile")
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 404
        finally:
            server.stop()


class TestRunLoop:
    def test_run_loop_provisions_on_wall_clock(self):
        env = Environment(options=Options(batch_idle_duration=0.05, batch_max_duration=0.2), clock=Clock())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        env.store.create(make_pod(cpu="1"))
        stop = threading.Event()
        t = threading.Thread(target=env.run, kwargs={"stop_event": stop, "tick_seconds": 0.05})
        t.start()
        try:
            deadline = time.time() + 30
            while time.time() < deadline:
                pods = env.store.list("Pod")
                if pods and pods[0].spec.node_name:
                    break
                time.sleep(0.1)
        finally:
            stop.set()
            t.join(timeout=10)
        assert env.store.list("Pod")[0].spec.node_name != ""
        # run() released the lease on shutdown
        lease = env.store.get("Lease", "karpenter-leader-election", "kube-system")
        assert lease.holder_identity == ""

    def test_standby_does_not_reconcile(self):
        env = Environment(options=Options(batch_idle_duration=0.05, batch_max_duration=0.2), clock=Clock())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        # another instance holds the lease and keeps renewing
        holder = LeaderElector(env.store, env.clock, "other")
        assert holder.try_acquire_or_renew()
        env.store.create(make_pod(cpu="1"))
        stop = threading.Event()
        t = threading.Thread(target=env.run, kwargs={"stop_event": stop, "tick_seconds": 0.05})
        t.start()
        try:
            for _ in range(8):
                holder.try_acquire_or_renew()
                time.sleep(0.1)
        finally:
            stop.set()
            t.join(timeout=10)
        assert env.store.count("NodeClaim") == 0  # standby stayed passive


class TestOperationalOptions:
    """The reference's full operational flag surface (options.go:68-135)."""

    def test_defaults_match_reference(self):
        from karpenter_tpu.operator.options import Options

        o = Options()
        assert (o.metrics_port, o.health_probe_port) == (8080, 8081)
        assert (o.kube_client_qps, o.kube_client_burst) == (200, 300)
        assert o.disable_controller_warmup is True  # options.go default
        assert o.disable_leader_election is False
        assert o.leader_election_name == "karpenter-leader-election"
        assert (o.log_level, o.log_output_paths, o.log_error_output_paths) == ("info", "stdout", "stderr")
        assert o.cpu_requests == 1000 and o.memory_limit == -1
        assert o.ignore_dra_requests is True

    def test_from_args_reference_flag_names(self):
        from karpenter_tpu.operator.options import Options

        o = Options.from_args([
            "--metrics-port", "9090",
            "--health-probe-port=9091",
            "--kube-client-qps", "50",
            "--enable-profiling", "true",
            "--disable-leader-election=true",
            "--log-level", "debug",
            "--batch-max-duration", "30s",
            "--batch-idle-duration", "2",
            "--preference-policy", "Ignore",
            "--feature-gates", "NodeRepair=true,SpotToSpotConsolidation=true",
        ])
        assert o.metrics_port == 9090 and o.health_probe_port == 9091
        assert o.kube_client_qps == 50
        assert o.enable_profiling and o.disable_leader_election
        assert o.log_level == "debug"
        assert o.batch_max_duration == 30.0 and o.batch_idle_duration == 2.0
        assert o.preference_policy == "Ignore"
        assert o.feature_gates.node_repair and o.feature_gates.spot_to_spot_consolidation

    def test_env_fallbacks(self, monkeypatch):
        from karpenter_tpu.operator.options import Options

        monkeypatch.setenv("METRICS_PORT", "7000")
        monkeypatch.setenv("LOG_LEVEL", "error")
        monkeypatch.setenv("DISABLE_LEADER_ELECTION", "true")
        monkeypatch.setenv("KUBE_CLIENT_BURST", "500")
        o = Options.from_env()
        assert o.metrics_port == 7000
        assert o.log_level == "error"
        assert o.disable_leader_election is True
        assert o.kube_client_burst == 500

    def test_flags_win_over_env(self, monkeypatch):
        from karpenter_tpu.operator.options import Options

        monkeypatch.setenv("METRICS_PORT", "7000")
        o = Options.from_args(["--metrics-port", "7001"])
        assert o.metrics_port == 7001

    def test_validation_fails_closed(self):
        import pytest as _pytest

        from karpenter_tpu.operator.options import Options

        with _pytest.raises(ValueError, match="log-level"):
            Options.from_args(["--log-level", "verbose"])
        with _pytest.raises(ValueError, match="preference-policy"):
            Options.from_args(["--preference-policy", "Sometimes"])
        with _pytest.raises(ValueError, match="not a valid value"):
            Options.from_args(["--enable-profiling", "yes"])

    def test_unknown_flags_fail_closed(self):
        # the reference's flag.FlagSet errors on undeclared flags; typos must
        # not silently run the operator with default config
        import pytest as _pytest

        from karpenter_tpu.operator.options import Options

        with _pytest.raises(ValueError, match="unknown flags"):
            Options.from_args(["--metrics-prot", "9999"])

    def test_bare_bool_flags_like_go(self):
        # Go flag semantics: bare --flag means true, and a following flag is
        # NOT consumed as its value
        from karpenter_tpu.operator.options import Options

        o = Options.from_args(["--enable-profiling", "--feature-gates", "NodeRepair=true"])
        assert o.enable_profiling is True
        assert o.feature_gates.node_repair is True
        o2 = Options.from_args(["--disable-leader-election"])
        assert o2.disable_leader_election is True

    def test_go_parsebool_forms_on_flags(self):
        from karpenter_tpu.operator.options import Options

        o = Options.from_args(["--disable-leader-election=1", "--enable-profiling", "t"])
        assert o.disable_leader_election is True and o.enable_profiling is True

    def test_env_bool_go_parsebool_values(self, monkeypatch):
        import pytest as _pytest

        from karpenter_tpu.operator.options import Options

        monkeypatch.setenv("DISABLE_LEADER_ELECTION", "1")
        assert Options.from_env().disable_leader_election is True
        monkeypatch.setenv("DISABLE_LEADER_ELECTION", "definitely")
        with _pytest.raises(ValueError, match="DISABLE_LEADER_ELECTION"):
            Options.from_env()
        monkeypatch.setenv("DISABLE_LEADER_ELECTION", "f")
        assert Options.from_env().disable_leader_election is False

    def test_env_int_named_error(self, monkeypatch):
        import pytest as _pytest

        from karpenter_tpu.operator.options import Options

        monkeypatch.setenv("METRICS_PORT", "abc")
        with _pytest.raises(ValueError, match="METRICS_PORT"):
            Options.from_env()
