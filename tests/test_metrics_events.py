"""Metrics registry + events recorder (reference: pkg/metrics, pkg/events,
pkg/controllers/metrics/*)."""

import math

from helpers import make_nodepool, make_pod
from karpenter_tpu import metrics as m
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.events import Recorder
from karpenter_tpu.metrics import make_registry
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.clock import FakeClock

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


class TestRegistry:
    def test_counter_inc_and_labels(self):
        r = Registry()
        c = r.counter("test_total", "help", ("a", "b"))
        c.inc(a="x", b="y")
        c.inc(2, a="x", b="y")
        c.inc(a="z")
        assert c.value(a="x", b="y") == 3
        assert c.value(a="z", b="") == 1
        assert c.total() == 4

    def test_gauge_set_add_reset(self):
        r = Registry()
        g = r.gauge("test_gauge", "help", ("k",))
        g.set(5, k="a")
        g.add(2, k="a")
        assert g.value(k="a") == 7
        g.reset()
        assert g.value(k="a") == 0

    def test_histogram_observe(self):
        r = Registry()
        h = r.histogram("test_seconds", "help", (), buckets=(0.1, 1, 10))
        for v in (0.05, 0.5, 5, 50):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == 55.55
        assert h.percentile(0.5) in (0.1, 1)

    def test_histogram_percentile_empty_is_nan(self):
        r = Registry()
        h = r.histogram("empty_seconds", "", ())
        assert math.isnan(h.percentile(0.5))

    def test_expose_text_format(self):
        r = Registry()
        r.counter("karpenter_things_total", "things", ("kind",)).inc(kind="a")
        r.gauge("karpenter_level", "level", ()).set(3)
        r.histogram("karpenter_dur_seconds", "dur", (), buckets=(1, 2)).observe(1.5)
        text = r.expose()
        assert '# TYPE karpenter_things_total counter' in text
        assert 'karpenter_things_total{kind="a"} 1' in text
        assert "karpenter_level 3" in text
        assert 'karpenter_dur_seconds_bucket{le="2"} 1' in text
        assert 'karpenter_dur_seconds_count 1' in text

    def test_type_mismatch_raises(self):
        import pytest

        r = Registry()
        r.counter("x_total", "", ())
        with pytest.raises(TypeError):
            r.gauge("x_total", "", ())

    def test_unknown_label_raises(self):
        import pytest

        r = Registry()
        c = r.counter("y_total", "", ("a",))
        with pytest.raises(ValueError):
            c.inc(b="nope")


class TestRecorder:
    def test_dedupe_window(self):
        clock = FakeClock()
        rec = Recorder(clock)

        class Obj:
            kind = "NodeClaim"

            class metadata:
                name = "nc-1"

        assert rec.publish(Obj(), "Launched", "msg")
        assert not rec.publish(Obj(), "Launched", "msg")  # deduped
        clock.step(121)
        assert rec.publish(Obj(), "Launched", "msg")  # window elapsed
        assert len(rec.events) == 2

    def test_different_reasons_not_deduped(self):
        clock = FakeClock()
        rec = Recorder(clock)

        class Obj:
            kind = "Node"

            class metadata:
                name = "n-1"

        assert rec.publish(Obj(), "A", "m1")
        assert rec.publish(Obj(), "B", "m2")
        assert rec.reasons() == ["A", "B"]


class TestEndToEndMetrics:
    def make_env(self):
        env = Environment(options=Options())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        return env

    def test_provisioning_flow_instruments(self):
        env = self.make_env()
        for _ in range(3):
            env.store.create(make_pod())
        env.settle()
        reg = env.registry
        assert reg.counter(m.NODECLAIMS_CREATED_TOTAL).total() >= 1
        assert reg.counter(m.NODES_CREATED_TOTAL).total() >= 1
        assert reg.histogram(m.SCHEDULER_SCHEDULING_DURATION).count() >= 1
        assert reg.histogram(m.PODS_BOUND_DURATION).count() == 3
        assert reg.histogram(m.PODS_STARTUP_DURATION).count() == 3
        assert reg.gauge(m.CLUSTER_STATE_SYNCED).value() == 1.0
        assert reg.gauge(m.CLUSTER_STATE_NODE_COUNT).value() == env.store.count("Node")
        # per-node gauges labeled by node/pool
        node = env.store.list("Node")[0]
        pool = node.metadata.labels[wk.NODEPOOL_LABEL_KEY]
        zone = node.metadata.labels[wk.ZONE_LABEL_KEY]
        assert (
            reg.gauge(m.NODES_ALLOCATABLE).value(
                node_name=node.metadata.name, nodepool=pool, resource_type="cpu", zone=zone
            )
            > 0
        )

    def test_termination_counters(self):
        env = self.make_env()
        env.store.create(make_pod())
        env.settle()
        for p in env.store.list("Pod"):
            env.store.delete("Pod", p.metadata.name, namespace=p.metadata.namespace, grace=False)
        env.settle(rounds=30)
        assert env.store.count("Node") == 0
        assert env.registry.counter(m.NODES_TERMINATED_TOTAL).total() >= 1
        assert env.registry.counter(m.NODECLAIMS_TERMINATED_TOTAL).total() >= 1
        # disruption decisions recorded (emptiness consolidation)
        assert env.registry.counter(m.DISRUPTION_DECISIONS_TOTAL).total() >= 1

    def test_expose_contains_karpenter_namespace(self):
        env = self.make_env()
        env.store.create(make_pod())
        env.settle()
        text = env.registry.expose()
        assert "karpenter_nodeclaims_created_total" in text
        assert "karpenter_scheduler_scheduling_duration_seconds_bucket" in text
