"""solvetrace specs: the flight recorder must observe without influencing.

Covers the tentpole's acceptance surface: bit-identical placements with
tracing on vs off across every solve mode, ring-buffer bounding + the
dropped-trace counter, the JIT-recompile sentinel (a seeded shape-bucket
miss is counted, steady-state warm re-solves record zero), Perfetto/JSONL
export round-trips, the shared nearest-rank quantile helper, and the
/debug/solves + /metrics operator surfaces."""

import json
import urllib.request

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm
from karpenter_tpu.metrics import (
    SOLVER_RECOMPILE_TOTAL,
    SOLVER_SOLVE_QUANTILE_SECONDS,
    SOLVER_TRACE_DROPPED_TOTAL,
    make_registry,
)
from karpenter_tpu.obs import RollingQuantiles, SolveTrace, TraceRecorder, default_recorder, quantile
from karpenter_tpu.obs.export import parse_dump, to_jsonl, to_trace_events
from karpenter_tpu.solver import FFDSolver
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.testing.metrics_poller import _p95
from test_solver import make_snapshot


def _odd_pod(name="odd"):
    """Pod-local out-of-window pod (preferred pod affinity) -> hybrid."""
    p = make_pod(cpu="500m", name=name)
    p.spec.affinity = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=1,
                term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
    )
    return p


def _global_pod(name="asym"):
    """Asymmetric anti-affinity membership -> whole-snapshot fallback."""
    sel = {"matchLabels": {"app": "other"}}
    return make_pod(
        cpu="1",
        name=name,
        labels={"app": "me"},
        anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)],
    )


def canon(results):
    """Placement fingerprint: node/claim membership and options, order-free."""
    existing = sorted(
        (en.name(), tuple(sorted(p.metadata.name for p in en.pods))) for en in results.existing_nodes if en.pods
    )
    claims = sorted(
        (
            tuple(sorted(p.metadata.name for p in nc.pods)),
            tuple(sorted(it.name for it in nc.instance_type_options)),
        )
        for nc in results.new_node_claims
    )
    return (existing, claims, sorted(results.pod_errors))


class TestQuantileHelper:
    def test_nearest_rank_exact_values(self):
        assert quantile([1, 2, 3, 4], 0.50) == 2
        assert quantile([1, 2, 3, 4], 0.95) == 4
        assert quantile(list(range(1, 21)), 0.95) == 19
        assert quantile(list(range(1, 101)), 0.99) == 99
        assert quantile([7.0], 0.5) == 7.0
        assert quantile([], 0.95) == 0.0

    def test_small_n_underestimate_fixed(self):
        # the old poller rule round(0.95*(n-1)) returned the 12th sample at
        # n=13; nearest-rank must return the max
        values = list(range(1, 14))
        assert quantile(values, 0.95) == 13
        assert _p95(values) == 13  # the poller shares the helper

    def test_sorted_flag_and_rolling_window(self):
        assert quantile([3, 1, 2], 0.5) == 2
        win = RollingQuantiles(capacity=4)
        for v in [10, 20, 30, 40, 50, 60]:  # evicts 10, 20
            win.append(v)
        assert len(win) == 4
        assert win.quantile(0.5) == 40
        assert win.quantile(0.99) == 60


class TestParityOnOff:
    def test_bit_identical_placements_every_mode(self):
        """full -> delta -> hybrid -> hybrid-delta -> fallback, tracing on vs
        off on the same snapshots: recording must never change placements."""
        on = TPUSolver(recorder=TraceRecorder(enabled=True))
        off = TPUSolver(recorder=TraceRecorder(enabled=False))
        assert on._trace.enabled is False  # pre-solve placeholder

        snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(5)])
        steps = [
            ("full", lambda: None),
            ("delta", lambda: snap.pods.append(make_pod(cpu="500m", name="p5"))),
            ("hybrid", lambda: snap.pods.append(_odd_pod())),
            ("hybrid-delta", lambda: snap.pods.append(make_pod(cpu="500m", name="p6"))),
        ]
        for expected, mutate in steps:
            mutate()
            r_on, r_off = on.solve(snap), off.solve(snap)
            assert on.last_solve_mode == expected, (expected, on.last_solve_mode, on.last_fallback_reasons)
            assert off.last_solve_mode == expected
            assert canon(r_on) == canon(r_off), expected
            assert on._trace.enabled and not off._trace.enabled

        snap2 = make_snapshot(
            [_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name=f"o{i}") for i in range(2)]
        )
        r_on, r_off = on.solve(snap2), off.solve(snap2)
        assert on.last_solve_mode == "fallback" == off.last_solve_mode
        assert canon(r_on) == canon(r_off)

    def test_disabled_recorder_keeps_compat_surfaces(self):
        off = TPUSolver(recorder=TraceRecorder(enabled=False))
        off.solve(make_snapshot([make_pod(cpu="500m", name="a")]))
        assert off.last_solve_mode == "full"
        ph = off.last_phase_seconds
        assert set(ph) == {"encode", "pack", "residual"}
        assert ph["encode"] > 0 and ph["pack"] > 0  # phase totals survive off
        assert len(off.recorder.traces()) == 0  # but nothing is retained


class TestRingAndStats:
    def _commit(self, rec, mode="full", registry=None, n_pods=0):
        t = rec.begin(n_pods=n_pods)
        with t.span("encode", mode="full"):
            pass
        t.mode = mode
        t.backend = "tpu"
        rec.commit(t, registry=registry)
        return t

    def test_ring_bounds_and_dropped_counter(self):
        reg = make_registry()
        rec = TraceRecorder(capacity=4, enabled=True)
        for i in range(10):
            self._commit(rec, registry=reg, n_pods=i)
        assert len(rec.traces()) == 4
        assert [t.n_pods for t in rec.traces()] == [6, 7, 8, 9]  # oldest evicted
        assert rec.dropped == 6
        assert reg.counter(SOLVER_TRACE_DROPPED_TOTAL).value() == 6

    def test_rolling_quantiles_published(self):
        reg = make_registry()
        rec = TraceRecorder(capacity=8, enabled=True)
        for _ in range(5):
            self._commit(rec, registry=reg)
        g = reg.gauge(SOLVER_SOLVE_QUANTILE_SECONDS)
        for q in ("p50", "p90", "p99"):
            assert g.value(mode="full", phase="total", quantile=q) > 0
        stats = rec.stats()
        assert stats["full/total"]["n"] == 5
        assert stats["full/total"]["p50"] <= stats["full/total"]["p99"]

    def test_dump_limit_zero_means_none(self):
        rec = TraceRecorder(capacity=4, enabled=True)
        for _ in range(3):
            self._commit(rec)
        assert len(rec.dump()["solves"]) == 3
        assert len(rec.dump(limit=1)["solves"]) == 1
        assert rec.dump(limit=0)["solves"] == []
        assert rec.dump(limit=-1)["solves"] == []

    def test_raising_solve_commits_empty_attribution(self):
        # a solve that raises past every exit path must not inherit the
        # previous solve's backend/reasons into its trace
        import pytest

        rec = TraceRecorder(capacity=8, enabled=True)
        solver = TPUSolver(force=True, recorder=rec)
        snap = make_snapshot([make_pod(cpu="500m", name="ok")])
        solver.solve(snap)
        assert solver.last_backend == "tpu"
        with pytest.raises(RuntimeError, match="tensor path unsupported"):
            solver.solve(make_snapshot([_odd_pod()]))
        raised = rec.traces()[-1]
        assert raised.backend == "" and raised.mode == ""
        assert raised.fallback_reasons  # the encode's reasons, not the prior solve's

    def test_summary_since(self):
        rec = TraceRecorder(capacity=8, enabled=True)
        self._commit(rec, mode="full")
        mark = rec.seq
        t = self._commit(rec, mode="hybrid")
        t.recompiles = {}
        s = rec.summary_since(mark)
        assert s["n_solves"] == 1 and s["modes"] == {"hybrid": 1}
        assert "last_phases" in s


class TestRecompileSentinel:
    def test_seeded_shape_bucket_miss_counted_then_steady_state_zero(self):
        from karpenter_tpu.models.scheduler_model_grouped import _pack_compressed_impl

        reg = make_registry()
        solver = TPUSolver(registry=reg, recorder=TraceRecorder(enabled=True))
        snap = make_snapshot([make_pod(cpu="500m", name=f"s{i}") for i in range(5)])
        solver.solve(snap)
        before = reg.counter(SOLVER_RECOMPILE_TOTAL).total()
        # seeded miss: 43 same-signature pods crosses the (n_slots,
        # nnz-bucket) static-shape signature of the 5-pod pack. The jit cache
        # is process-shared, so another suite may have packed 43 pods already
        # — clear the kernel's cache to make the miss deterministic (the
        # persistent XLA cache keeps the re-trace cheap)
        _pack_compressed_impl.clear_cache()
        snap43 = make_snapshot([make_pod(cpu="500m", name=f"t{i}") for i in range(43)])
        solver.solve(snap43)
        assert solver.last_solve_mode == "full"
        seeded = dict(solver._trace.recompiles)
        assert sum(seeded.values()) >= 1, seeded
        assert "pack_full" in seeded
        assert reg.counter(SOLVER_RECOMPILE_TOTAL).total() > before
        # steady-state warm re-solve (identical resubmit): ZERO recompiles
        solver.solve(snap43)
        assert solver._trace.recompiles == {}
        # and the warm re-solve's trace is stamped into the quantile surface
        assert reg.counter(SOLVER_RECOMPILE_TOTAL).value(fn="pack_full") >= 1

    def test_sentinel_snapshot_is_safe_without_jax_modules(self):
        from karpenter_tpu.obs import RecompileSentinel

        s = RecompileSentinel(watchlist=(("ghost", "not.a.module", "fn"),))
        assert s.snapshot() == {}
        assert s.delta(None) == {}


class TestExport:
    def _traced_recorder(self):
        rec = TraceRecorder(capacity=8, enabled=True)
        t = rec.begin(n_pods=3)
        with t.span("encode", mode="full"):
            pass
        with t.span("pack", mode="full"):
            with t.span("decode"):
                pass
        t.mode, t.backend = "full", "tpu"
        t.recompiles = {"pack_full": 1}
        rec.commit(t)
        return rec

    def test_perfetto_round_trips_through_json(self):
        rec = self._traced_recorder()
        ev = json.loads(json.dumps(to_trace_events(rec.traces())))
        names = [e["name"] for e in ev["traceEvents"]]
        assert "solve#1" in names and "encode" in names and "pack" in names
        assert "decode" in names  # nested child spans flatten into events
        assert "recompile:pack_full" in names
        solve_ev = next(e for e in ev["traceEvents"] if e["name"] == "solve#1")
        assert solve_ev["ph"] == "X" and solve_ev["dur"] > 0

    def test_jsonl_round_trip_and_parse_dump(self):
        rec = self._traced_recorder()
        jsonl = to_jsonl(rec.traces())
        rows = [json.loads(line) for line in jsonl.splitlines()]
        assert rows and rows[0]["mode"] == "full"
        assert parse_dump(jsonl)[0]["recompiles"] == {"pack_full": 1}
        # a /debug/solves dump parses to the same traces
        assert parse_dump(json.dumps(rec.dump()))[0]["mode"] == "full"

    def test_cli_exports_perfetto_and_jsonl(self, tmp_path):
        from karpenter_tpu.obs.__main__ import main

        rec = self._traced_recorder()
        src = tmp_path / "solves.jsonl"
        src.write_text(to_jsonl(rec.traces()) + "\n")
        out = tmp_path / "solves.trace.json"
        assert main([str(src), "--out", str(out)]) == 0
        data = json.loads(out.read_text())
        assert data["traceEvents"]
        out2 = tmp_path / "norm.jsonl"
        assert main([str(src), "--format", "jsonl", "--out", str(out2)]) == 0
        assert json.loads(out2.read_text().splitlines()[0])["mode"] == "full"
        assert main([str(tmp_path / "missing.jsonl"), "--out", str(out)]) == 2

    def test_trace_to_dict_fields(self):
        rec = self._traced_recorder()
        d = rec.traces()[0].to_dict()
        for key in ("seq", "mode", "backend", "n_pods", "duration_s", "phases", "spans", "cache", "recompiles"):
            assert key in d
        # the span tree nests: pack carries the decode child
        pack = next(s for s in d["spans"] if s["name"] == "pack")
        assert pack["children"][0]["name"] == "decode"


class TestExplainAndAttribution:
    def test_hybrid_explain_names_families_and_residual(self):
        solver = TPUSolver(recorder=TraceRecorder(enabled=True))
        snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(4)] + [_odd_pod()])
        solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        tr = solver._trace
        assert tr.families == ["pod-affinity"]
        assert tr.attribution["residual_pods"] == 1
        assert tr.phase_totals["residual"] > 0
        # the residual's host FFD attached its per-phase split + memo stats
        assert "ffd.new_claim" in tr.phase_totals
        assert "ffd_memo" in tr.attribution
        text = tr.explain()
        assert "why hybrid" in text and "pod-affinity" in text

    def test_fallback_explain_and_ffd_span(self):
        solver = TPUSolver(recorder=TraceRecorder(enabled=True))
        snap = make_snapshot(
            [_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name=f"o{i}") for i in range(2)]
        )
        solver.solve(snap)
        assert solver.last_solve_mode == "fallback"
        tr = solver._trace
        assert tr.phase_totals.get("fallback", 0) > 0
        assert "ffd.existing" in tr.phase_totals
        assert "why fallback" in tr.explain()

    def test_delta_attribution(self):
        solver = TPUSolver(recorder=TraceRecorder(enabled=True))
        snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(5)])
        solver.solve(snap)
        assert solver._trace.attribution["encode_mode"] == "full"
        snap.pods.append(make_pod(cpu="500m", name="p5"))
        solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        a = solver._trace.attribution
        assert a["encode_mode"] == "delta" and a["row_cache"] is True
        assert a["delta_added"] == 1 and a["delta_removed"] == 0
        assert "why delta" in solver._trace.explain()

    def test_standalone_ffd_solver_records_a_trace(self):
        rec = default_recorder()
        mark = rec.seq
        FFDSolver().solve(make_snapshot([make_pod(cpu="1", name="solo")]))
        traces = [t for t in rec.traces() if t.seq > mark]
        if rec.enabled:  # KARPENTER_SOLVETRACE=0 legitimately disables this
            assert traces and traces[-1].mode == "ffd" and traces[-1].backend == "ffd"
            assert "ffd.new_claim" in traces[-1].phase_totals


class TestOperatorSurfaces:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()

    def test_debug_solves_and_metrics_serve_traces(self):
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.operator.server import OperatorServer

        env = Environment(options=Options())
        solver = TPUSolver(registry=env.registry)  # default recorder = env.trace_recorder
        solver.solve(make_snapshot([make_pod(cpu="500m", name="web")]))
        server = OperatorServer(env, port=0)
        port = server.start()
        try:
            code, body = self._get(port, "/debug/solves")
            assert code == 200
            dump = json.loads(body)
            assert dump["capacity"] > 0 and dump["solves"], dump.get("enabled")
            assert any(s["mode"] in ("full", "delta") for s in dump["solves"])
            code, body = self._get(port, "/debug/solves?n=1")
            assert code == 200 and len(json.loads(body)["solves"]) == 1
            code, body = self._get(port, "/metrics")
            assert code == 200
            assert SOLVER_SOLVE_QUANTILE_SECONDS in body
            assert SOLVER_TRACE_DROPPED_TOTAL in body
            assert SOLVER_RECOMPILE_TOTAL in body
        finally:
            server.stop()

    def test_trace_object_defaults(self):
        t = SolveTrace()
        assert t.mode == "" and t.phase_totals == {}
        assert t.explain()  # renders without a single recorded fact
