"""Mechanical enforcement of the PR-1 invariant: every TPUSolver.solve exit
path sets `last_solve_mode` AND `last_backend`, and the pair is always one of
the known combinations. One scenario per exit path:

  full         -> ("full", "tpu")
  delta        -> ("delta", "tpu")
  hybrid       -> ("hybrid", "hybrid")
  hybrid-delta -> ("hybrid-delta", "hybrid")
  fallback     -> ("fallback", "ffd-fallback")
"""

import pytest

from helpers import make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import (
    Affinity,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot

VALID_PAIRS = {
    ("full", "tpu"),
    ("delta", "tpu"),
    ("hybrid", "hybrid"),
    ("hybrid-delta", "hybrid"),
    ("fallback", "ffd-fallback"),
}


def _odd_pod(name="odd"):
    p = make_pod(cpu="500m", name=name)
    p.spec.affinity = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=1,
                term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
    )
    return p


def _global_pod(name="asym"):
    # asymmetric anti-affinity (selector matches non-declaring pods): global
    sel = {"matchLabels": {"app": "other"}}
    return make_pod(
        cpu="1",
        name=name,
        labels={"app": "me"},
        anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)],
    )


def _check(solver):
    assert (solver.last_solve_mode, solver.last_backend) in VALID_PAIRS, (
        solver.last_solve_mode,
        solver.last_backend,
    )


def _exit_path_walk():
    """Yields (expected_mode, results, solver) per scenario, checking the
    mode/backend pair after every solve."""
    # full
    solver = TPUSolver()
    snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(5)])
    yield "full", solver.solve(snap), solver

    # delta: append a known shape
    snap.pods.append(make_pod(cpu="500m", name="p5"))
    yield "delta", solver.solve(snap), solver

    # hybrid: one pod-local out-of-window pod
    snap.pods.append(_odd_pod())
    yield "hybrid", solver.solve(snap), solver

    # hybrid-delta: one more known-shape pod on the retained hybrid carry
    snap.pods.append(make_pod(cpu="500m", name="p6"))
    yield "hybrid-delta", solver.solve(snap), solver

    # fallback: a snapshot-global reason
    snap2 = make_snapshot([_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name=f"o{i}") for i in range(2)])
    yield "fallback", solver.solve(snap2), solver


class TestSolveModeInvariant:
    def test_every_exit_path_sets_mode_and_backend(self):
        seen = []
        for expected, results, solver in _exit_path_walk():
            _check(solver)
            assert solver.last_solve_mode == expected, (expected, solver.last_solve_mode, solver.last_fallback_reasons)
            if expected != "fallback":  # the fallback scenario's placement may legitimately error
                assert not results.pod_errors
            seen.append(expected)
        assert seen == ["full", "delta", "hybrid", "hybrid-delta", "fallback"]

    def test_empty_snapshot_sets_fallback(self):
        solver = TPUSolver()
        solver.solve(make_snapshot([]))
        assert (solver.last_solve_mode, solver.last_backend) == ("fallback", "ffd-fallback")

    def test_hybrid_disabled_sets_fallback(self):
        solver = TPUSolver(hybrid=False)
        solver.solve(make_snapshot([make_pod(cpu="500m"), _odd_pod()]))
        assert (solver.last_solve_mode, solver.last_backend) == ("fallback", "ffd-fallback")

    def test_mode_reset_between_solves(self):
        # a hybrid solve must not leak its mode into a later clean solve
        solver = TPUSolver()
        solver.solve(make_snapshot([make_pod(cpu="500m"), _odd_pod()]))
        assert solver.last_solve_mode == "hybrid"
        solver.solve(make_snapshot([make_pod(cpu="500m", name="fresh")]))
        _check(solver)
        assert solver.last_solve_mode == "full"
