"""Mechanical enforcement of the PR-1 invariant: every TPUSolver.solve exit
path sets `last_solve_mode` AND `last_backend`, and the pair is always one of
the known combinations. One scenario per exit path:

  full         -> ("full", "tpu")
  delta        -> ("delta", "tpu")
  hybrid       -> ("hybrid", "hybrid")
  hybrid-delta -> ("hybrid-delta", "hybrid")
  fallback     -> ("fallback", "ffd-fallback")
  sim          -> ("sim", "tpu")   # solve_prepared: consolidation masked sims
"""

import pytest

from helpers import make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import (
    Affinity,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot

VALID_PAIRS = {
    ("full", "tpu"),
    ("delta", "tpu"),
    ("hybrid", "hybrid"),
    ("hybrid-delta", "hybrid"),
    ("fallback", "ffd-fallback"),
    ("sim", "tpu"),
}


def _odd_pod(name="odd"):
    p = make_pod(cpu="500m", name=name)
    p.spec.affinity = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=1,
                term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
    )
    return p


def _global_pod(name="asym"):
    # asymmetric anti-affinity (selector matches non-declaring pods): global
    sel = {"matchLabels": {"app": "other"}}
    return make_pod(
        cpu="1",
        name=name,
        labels={"app": "me"},
        anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)],
    )


def _check(solver):
    assert (solver.last_solve_mode, solver.last_backend) in VALID_PAIRS, (
        solver.last_solve_mode,
        solver.last_backend,
    )


def _exit_path_walk():
    """Yields (expected_mode, results, solver) per scenario, checking the
    mode/backend pair after every solve."""
    # full
    solver = TPUSolver()
    snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(5)])
    yield "full", solver.solve(snap), solver

    # delta: append a known shape
    snap.pods.append(make_pod(cpu="500m", name="p5"))
    yield "delta", solver.solve(snap), solver

    # hybrid: one pod-local out-of-window pod
    snap.pods.append(_odd_pod())
    yield "hybrid", solver.solve(snap), solver

    # hybrid-delta: one more known-shape pod on the retained hybrid carry
    snap.pods.append(make_pod(cpu="500m", name="p6"))
    yield "hybrid-delta", solver.solve(snap), solver

    # fallback: a snapshot-global reason
    snap2 = make_snapshot([_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name=f"o{i}") for i in range(2)])
    yield "fallback", solver.solve(snap2), solver


class TestSolveModeInvariant:
    def test_every_exit_path_sets_mode_and_backend(self):
        seen = []
        for expected, results, solver in _exit_path_walk():
            _check(solver)
            assert solver.last_solve_mode == expected, (expected, solver.last_solve_mode, solver.last_fallback_reasons)
            if expected != "fallback":  # the fallback scenario's placement may legitimately error
                assert not results.pod_errors
            seen.append(expected)
        assert seen == ["full", "delta", "hybrid", "hybrid-delta", "fallback"]

    def test_empty_snapshot_sets_fallback(self):
        solver = TPUSolver()
        solver.solve(make_snapshot([]))
        assert (solver.last_solve_mode, solver.last_backend) == ("fallback", "ffd-fallback")

    def test_hybrid_disabled_sets_fallback(self):
        solver = TPUSolver(hybrid=False)
        solver.solve(make_snapshot([make_pod(cpu="500m"), _odd_pod()]))
        assert (solver.last_solve_mode, solver.last_backend) == ("fallback", "ffd-fallback")

    def test_mode_reset_between_solves(self):
        # a hybrid solve must not leak its mode into a later clean solve
        solver = TPUSolver()
        solver.solve(make_snapshot([make_pod(cpu="500m"), _odd_pod()]))
        assert solver.last_solve_mode == "hybrid"
        solver.solve(make_snapshot([make_pod(cpu="500m", name="fresh")]))
        _check(solver)
        assert solver.last_solve_mode == "full"


class TestReasonFamilyEnum:
    """Thin wrapper over solverlint's reason-family-tiers rule (ISSUE 4):
    the mechanical walker that used to live here — every family routes to a
    defined tier, GLOBAL families justify themselves, no stale entries —
    moved into the analyzer (karpenter_tpu/analysis/rules.py), where
    `python -m karpenter_tpu.analysis` enforces it repo-wide. This class
    keeps the wiring assertion plus the behavior pins no static rule can
    express."""

    def test_analyzer_rule_holds_on_fallback_module(self):
        from karpenter_tpu.analysis import run_analysis

        findings = run_analysis(rules=["reason-family-tiers"])
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_tier_demotions_stay_pinned(self):
        from karpenter_tpu.solver.fallback import FAMILY_TIERS, GLOBAL, POD_LOCAL, REASON_FAMILIES

        for _needle, family in REASON_FAMILIES:
            assert FAMILY_TIERS[family] in (GLOBAL, POD_LOCAL)
        # demotions PR 3 made are pinned here so a revert is loud
        assert FAMILY_TIERS["min-values"] == POD_LOCAL
        assert FAMILY_TIERS["asymmetric-spread-membership"] == POD_LOCAL
        assert FAMILY_TIERS["strict-reserved-offering"] == POD_LOCAL
        assert FAMILY_TIERS["other"] == GLOBAL

    def test_reason_family_total_on_arbitrary_strings(self):
        import random

        from karpenter_tpu.solver.fallback import FAMILY_TIERS, REASON_FAMILIES, reason_family

        enum = {fam for _n, fam in REASON_FAMILIES} | {"other"}
        rng = random.Random(0)
        probes = ["", "garbage", "pod xyz: exploded"] + [
            "".join(rng.choice("abcdef -:/") for _ in range(rng.randrange(1, 40))) for _ in range(200)
        ] + [needle for needle, _f in REASON_FAMILIES]
        for s in probes:
            fam = reason_family(s)
            assert fam in enum and fam in FAMILY_TIERS, (s, fam)

    def test_residual_metric_cardinality_bounded_by_enum(self):
        from karpenter_tpu.metrics import (
            SOLVER_DECODE_REPAIR_TOTAL,
            SOLVER_FALLBACK_TOTAL,
            SOLVER_HYBRID_RESIDUAL_TOTAL,
            make_registry,
        )
        from karpenter_tpu.solver.fallback import REASON_FAMILIES

        registry = make_registry()
        solver = TPUSolver(registry=registry)
        # one hybrid solve + one fallback solve + a clean solve
        solver.solve(make_snapshot([make_pod(cpu="500m"), _odd_pod()]))
        assert solver.last_solve_mode == "hybrid"
        solver.solve(make_snapshot([_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name="o")]))
        assert solver.last_solve_mode == "fallback"
        solver.solve(make_snapshot([make_pod(cpu="500m", name="clean")]))

        enum = {fam for _n, fam in REASON_FAMILIES} | {"other"}
        for metric in (SOLVER_HYBRID_RESIDUAL_TOTAL, SOLVER_FALLBACK_TOTAL, SOLVER_DECODE_REPAIR_TOTAL):
            for labels, _v in registry.counter(metric).collect():
                assert labels.get("reason") in enum, (metric, labels)
