"""Tier-1 gate for solverlint (ISSUE 4 + the ISSUE 11 concurrency rules +
the ISSUE 15 swallowed-exception rule + the ISSUE 19 determinism rules):
the repo is clean under all fifteen rules, each rule catches its seeded
fixture violation and honors the pragma suppression form, the --self-test
discovery gate is healthy, and the runtime shape contracts
(solver/contracts.py) catch seeded drifts."""

import os
from pathlib import Path

import numpy as np
import pytest

from karpenter_tpu.analysis import RULES, run_analysis
from karpenter_tpu.analysis.__main__ import main as lint_main
from karpenter_tpu.analysis.core import repo_root

FIXTURES = Path(__file__).parent / "solverlint_fixtures"


def _fixture_findings(rule: str, fixture: str):
    return run_analysis(rules=[rule], paths=[FIXTURES / fixture])


class TestRepoGate:
    def test_repo_is_clean(self):
        # the one full repo-wide scan in this suite (the CLI path is covered
        # by the cheap restricted/exit-code tests below)
        findings = run_analysis()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_self_test_flag(self):
        assert lint_main(["--self-test"]) == 0

    def test_cli_restricted_paths_respect_rule_globs(self):
        # a single non-fallback operand must NOT be held to the
        # reason-family-tiers rule (regression: paths mode used to run every
        # rule over every operand and exit 1 on clean files)
        assert lint_main([str(repo_root() / "karpenter_tpu" / "solver" / "encode.py")]) == 0
        assert run_analysis(paths=[repo_root() / "karpenter_tpu" / "solver" / "ffd.py"]) == []

    def test_cli_rejects_unreadable_operands_with_exit_two(self, tmp_path):
        # an operator error must be exit 2 ("broken gate"), never exit 1
        # ("findings") or a traceback
        assert lint_main([str(tmp_path / "nope.py")]) == 2
        assert lint_main([str(tmp_path)]) == 2

    def test_rule_registry_holds_all_rules(self):
        assert len(RULES) >= 15
        assert set(RULES) == {
            "shared-array-mutation",
            "host-sync-in-hot-path",
            "python-loop-over-pod-axis",
            "reason-family-tiers",
            "metric-label-cardinality",
            "guarded-field-access",
            "lock-order",
            "thread-escape",
            "bare-thread-primitive",
            "swallowed-exception",
            "unordered-iteration-escape",
            "wallclock-and-rng-in-solve-path",
            "float-reduction-order",
            "env-dependent-branch",
            "stale-pragma",
        }

    def test_shared_field_registry_extraction(self):
        from karpenter_tpu.analysis.config import load_config
        from karpenter_tpu.solver.encode import SHARED_ENCODE_FIELDS

        # the AST extraction the analyzer uses must agree with the live
        # constant the runtime freeze uses
        cfg = load_config(repo_root())
        assert cfg.resolve_shared_fields(repo_root()) == SHARED_ENCODE_FIELDS


class TestRuleFixtures:
    """One known violation per rule is detected, and each pragma'd twin is
    suppressed (the fixture files carry both)."""

    def test_shared_array_mutation(self):
        findings = _fixture_findings("shared-array-mutation", "shared_mutation.py")
        assert len(findings) == 5, findings
        fields = sorted(f.message.split("'")[1] for f in findings)
        assert fields == ["counts_dom_init", "group_registered", "row_alloc", "sig_dom_allowed", "sig_req"], findings

    def test_host_sync(self):
        findings = _fixture_findings("host-sync-in-hot-path", "host_sync.py")
        assert len(findings) == 5, findings
        msgs = " | ".join(f.message for f in findings)
        assert "float()" in msgs and ".item()" in msgs and "asarray" in msgs
        # the .shape exemption prunes only its own subtree, and lambda
        # bodies are scanned as part of the enclosing scope
        lines = {f.line for f in findings}
        src = (FIXTURES / "host_sync.py").read_text().splitlines()
        assert any("takes.shape[0]" in src[ln - 1] and "takes.sum()" in src[ln - 1] for ln in lines)
        assert any("lambda" in src[ln - 1] for ln in lines)

    def test_pod_axis_loop(self):
        findings = _fixture_findings("python-loop-over-pod-axis", "pod_loop.py")
        assert len(findings) == 3, findings
        assert all("enc.pods" in f.message for f in findings)
        # the seeded multi-group item-builder and decode-materialization
        # loops are flagged; the vectorized np.unique and columnar-gather
        # forms right below each must stay clean
        src = (FIXTURES / "pod_loop.py").read_text().splitlines()
        assert sum("enumerate(enc.pods)" in src[f.line - 1] for f in findings) == 2
        flagged_fns = set()
        for f in findings:
            for ln in range(f.line - 1, -1, -1):
                if src[ln].startswith("def "):
                    flagged_fns.add(src[ln].split("(")[0][4:])
                    break
        assert "bad_decode_loop" in flagged_fns and "ok_decode_columnar" not in flagged_fns

    def test_reason_family_tiers(self):
        findings = _fixture_findings("reason-family-tiers", "fallback_registry.py")
        msgs = sorted(f.message for f in findings)
        assert len(findings) == 3, findings
        assert any("fam-untiered" in m and "no tier" in m for m in msgs)
        assert any("fam-global-bare" in m and "justification" in m for m in msgs)
        assert any("fam-stale" in m and "stale" in m for m in msgs)

    def test_metric_label_cardinality(self):
        findings = _fixture_findings("metric-label-cardinality", "metric_labels.py")
        assert len(findings) == 7, findings
        by_msg = [f.message for f in findings]
        # the enumerable-value findings include the fleet tenant-label leak
        # (a raw tenant id instead of a tenant_label() producer output), the
        # podtrace stage-label leak (a runtime span name instead of the
        # static STAGES enum), the faultline breaker-state leak (a runtime
        # breaker attribute instead of the TENANT_STATES enum), and the
        # globalpack proposer leak (a runtime trace backend instead of the
        # static proposer enum)
        assert sum("not statically enumerable" in m for m in by_msg) == 6
        assert sum("splat" in m for m in by_msg) == 1
        src = (FIXTURES / "metric_labels.py").read_text().splitlines()
        assert any("tenant=session.tenant_id" in src[f.line - 1] for f in findings)
        assert any("stage=stage" in src[f.line - 1] for f in findings)
        assert any("state=breaker.state" in src[f.line - 1] for f in findings)
        assert any("proposer=trace.backend" in src[f.line - 1] for f in findings)

    def test_guarded_field_access(self):
        # a read AND a write outside the declared lock are both findings;
        # nested withs, the line pragma, and the caller-holds method pragma
        # are the sanctioned forms
        findings = _fixture_findings("guarded-field-access", "guarded_field.py")
        assert len(findings) == 2, findings
        msgs = " | ".join(f.message for f in findings)
        assert "'hits'" in msgs and "'misses'" in msgs and "_lock" in msgs

    def test_lock_order(self):
        findings = _fixture_findings("lock-order", "lock_order.py")
        assert len(findings) == 2, findings
        msgs = sorted(f.message for f in findings)
        assert any("blocking call solver.solve()" in m for m in msgs)
        # the cycle is reported ONCE (the nested forward and the COMBINED
        # `with self._b, self._a:` backward fold into one finding) and
        # names the full path plus the inventory doc
        cycles = [m for m in msgs if "lock-order cycle" in m]
        assert len(cycles) == 1
        assert "FixtureInverted._a -> FixtureInverted._b -> FixtureInverted._a" in cycles[0]
        assert "serving/__init__.py" in cycles[0]

    def test_thread_escape(self):
        findings = _fixture_findings("thread-escape", "thread_escape.py")
        assert len(findings) == 4, findings
        msgs = " | ".join(f.message for f in findings)
        assert "thread target self._run" in msgs
        assert "thread target self._other" in msgs  # renamed from-import resolved
        assert "watch callback self._on_pod" in msgs
        assert "lambda" in msgs

    def test_swallowed_exception(self):
        findings = _fixture_findings("swallowed-exception", "swallowed_exception.py")
        assert len(findings) == 4, findings
        msgs = " | ".join(f.message for f in findings)
        # the broad forms are flagged; re-raise, recording (event publish or
        # metric emission), narrowing, and the pragma are the sanctioned outs
        assert "except Exception" in msgs
        assert "<bare except>" in msgs
        assert "except BaseException" in msgs
        # tuple form: `except (Exception, OSError):` is just as broad
        assert "except Exception, OSError" in msgs

    def test_bare_thread_primitive(self):
        findings = _fixture_findings("bare-thread-primitive", "bare_primitive.py")
        assert len(findings) == 3, findings
        msgs = " | ".join(f.message for f in findings)
        assert "threading.Lock()" in msgs and "threading.Event()" in msgs
        # a from-import rename resolves through the import table
        assert "_SneakyLock() constructs threading.Lock" in msgs
        # threading.local is exempt by design
        assert "threading.local" not in msgs

    def test_unordered_iteration_escape(self):
        findings = _fixture_findings("unordered-iteration-escape", "unordered_iter.py")
        assert len(findings) == 8, findings
        msgs = " | ".join(f.message for f in findings)
        assert "for-loop iterates a set" in msgs
        assert "list() materializes" in msgs
        assert "comprehension over a set" in msgs
        assert "set.pop()" in msgs
        assert "key=id" in msgs
        assert "*-unpacking" in msgs
        src = (FIXTURES / "unordered_iter.py").read_text().splitlines()
        lines = {f.line for f in findings}
        # set-typedness flows through the | operator and name copies...
        assert any("for x in b:" in src[ln - 1] for ln in lines)
        # ...and through self-attributes initialized as set() in __init__
        assert any("self._groups" in src[ln - 1] for ln in lines)
        # the sorted/order-insensitive/literal-display twins stay clean
        for ln, text in enumerate(src, 1):
            if "def ok_" in text:
                assert all(f.line < ln for f in findings), (ln, findings)

    def test_wallclock_rng(self):
        findings = _fixture_findings("wallclock-and-rng-in-solve-path", "wallclock_rng.py")
        assert len(findings) == 8, findings
        msgs = " | ".join(f.message for f in findings)
        # the alias-import pattern (PR 11's `import threading as t`, applied
        # to time/random): renamed modules and renamed from-imports resolve
        assert "clk.time()" in msgs
        assert "perf_counter()" in msgs
        assert "rnd.shuffle()" in msgs
        assert "sneaky_shuffle()" in msgs
        # unseeded constructors are flagged; the seeded twins are not
        assert "rnd.Random()" in msgs
        assert "np.random.default_rng()" in msgs
        assert "np.random.rand()" in msgs
        assert "uuid.uuid4()" in msgs
        src = (FIXTURES / "wallclock_rng.py").read_text().splitlines()
        for f in findings:
            assert "ok_" not in src[f.line - 1], f

    def test_float_reduction_order(self):
        findings = _fixture_findings("float-reduction-order", "float_order.py")
        assert len(findings) == 4, findings
        msgs = " | ".join(f.message for f in findings)
        assert "device-derived" in msgs
        assert "set hash order" in msgs
        # the message names the registered canonical-order helpers
        assert "fsum" in msgs and "stable_host_sum" in msgs
        src = (FIXTURES / "float_order.py").read_text().splitlines()
        # taint flows through name copies; the fsum/sorted/host-only twins
        # and the pragma'd twin stay clean
        assert any("sum(parts)" in src[f.line - 1] for f in findings)
        for f in findings:
            assert "bad_" in src[f.line - 1] or src[f.line - 1].strip().startswith("return"), f

    def test_env_dependent_branch(self):
        findings = _fixture_findings("env-dependent-branch", "env_branch.py")
        assert len(findings) == 8, findings
        msgs = " | ".join(f.message for f in findings)
        # unregistered literal knobs are named; the alias import
        # (`import os as sneaky_os`) and from-imported environ/getenv resolve
        assert "'KARPENTER_SOLVER_SECRET'" in msgs
        assert "'SOLVER_EXPERIMENT'" in msgs
        assert "'SOLVER_FORK_BEHAVIOR'" in msgs
        assert "'SOLVER_TUNING'" in msgs
        assert "non-literal key" in msgs
        assert "bulk os.environ read" in msgs
        src = (FIXTURES / "env_branch.py").read_text().splitlines()
        lines = {f.line for f in findings}
        # the registered KARPENTER_* knobs and the pragma'd twin stay clean
        assert not any("KARPENTER_SOLVER_MESH" in src[ln - 1] for ln in lines)
        assert not any("KARPENTER_SOLVER_DETCHECK" in src[ln - 1] for ln in lines)

    def test_stale_pragma(self):
        findings = _fixture_findings("stale-pragma", "stale_pragma.py")
        assert len(findings) == 2, findings
        msgs = sorted(f.message for f in findings)
        assert any("no longer suppresses any finding" in m for m in msgs)
        assert any("unknown rule 'rule-that-never-existed'" in m for m in msgs)
        # the load-bearing pragma (suppressing a live shared-array-mutation
        # finding) is NOT reported
        src = (FIXTURES / "stale_pragma.py").read_text().splitlines()
        for f in findings:
            assert "live_suppression" not in src[f.line - 1]
            assert "sig_req" not in src[f.line - 1]

    def test_stale_pragma_in_full_scan_mode(self, tmp_path):
        # the default-scan path (rules=None) reaches stale pragmas through
        # the cheap post-pass (usage marked while the other rules ran), not
        # the standalone rule — prove that path too. paths-only mode holds
        # each file to the rules whose globs cover it, so mirror the repo
        # layout under a tmp root.
        import dataclasses

        from karpenter_tpu.analysis.config import Config

        p = tmp_path / "karpenter_tpu" / "obs" / "rotted.py"
        p.parent.mkdir(parents=True)
        p.write_text(
            "def f(registry, why):\n"
            '    registry.counter("m").inc(reason=why)  # solverlint: ok(metric-label-cardinality): live — suppresses the non-enumerable-label finding\n'
            "    return registry.snapshot()  # solverlint: ok(swallowed-exception): rotted — nothing here to suppress\n"
        )
        cfg = dataclasses.replace(Config(), shared_fields=frozenset({"sig_req"}))
        findings = run_analysis(root=tmp_path, config=cfg, paths=[p])
        assert [f.rule for f in findings] == ["stale-pragma"], findings
        assert "'swallowed-exception'" in findings[0].message
        assert findings[0].line == 3

    def test_lock_order_catches_seeded_store_inversion(self, tmp_path):
        """Seeded REAL-module regressions: the store's own `_deliver_lock`
        -> `_lock` edge (the `_drain` pop) is live in the graph, so (a) an
        inverted nesting added anywhere in store.py closes a cycle, and (b)
        `_drain` moved under `_lock` is both a blocking-call finding and a
        call-graph cycle."""
        from karpenter_tpu.analysis.core import repo_root

        src = (repo_root() / "karpenter_tpu" / "kube" / "store.py").read_text()

        inverted = src.replace(
            "    def kind_revision(self, kind: str) -> int:\n"
            "        with self._lock:\n"
            "            return self._kind_rv.get(kind, 0)",
            "    def kind_revision(self, kind: str) -> int:\n"
            "        with self._lock:\n"
            "            with self._deliver_lock:\n"
            "                return self._kind_rv.get(kind, 0)",
        )
        assert inverted != src
        p = tmp_path / "store_inverted.py"
        p.write_text(inverted)
        findings = run_analysis(rules=["lock-order"], paths=[p])
        assert any("cycle" in f.message and "_deliver_lock" in f.message for f in findings), findings

        drained = src.replace(
            '            kind_map[key] = obj\n            self._enqueue("ADDED", obj)\n        self._drain()',
            '            kind_map[key] = obj\n            self._enqueue("ADDED", obj)\n            self._drain()',
        )
        assert drained != src
        p2 = tmp_path / "store_drain_under_lock.py"
        p2.write_text(drained)
        findings = run_analysis(rules=["lock-order"], paths=[p2])
        assert any("blocking call self._drain()" in f.message for f in findings), findings
        assert any("cycle" in f.message for f in findings), findings

    def test_unordered_iter_catches_seeded_encode_reverts(self, tmp_path):
        """Seeded REAL-module regressions pinning the detlint burn-down: the
        canonical-order fixes (sorted matched_keys / universe_ids sentinel
        scatters in encode.py, the sorted repair_sigs mask write in tpu.py)
        are findings the moment any of them is reverted to raw set order."""
        from karpenter_tpu.analysis.core import repo_root

        src = (repo_root() / "karpenter_tpu" / "solver" / "encode.py").read_text()
        unsorted_keys = src.replace("for s, k in sorted(matched_keys):", "for s, k in matched_keys:")
        assert unsorted_keys != src
        p = tmp_path / "encode_unsorted_keys.py"
        p.write_text(unsorted_keys)
        findings = run_analysis(rules=["unordered-iteration-escape"], paths=[p])
        # the sentinel pass appears in both the row and column encoders
        assert sum("for-loop iterates a set" in f.message for f in findings) == 2, findings

        unsorted_universe = src.replace("for d in sorted(universe_ids):", "for d in universe_ids:")
        assert unsorted_universe != src
        p2 = tmp_path / "encode_unsorted_universe.py"
        p2.write_text(unsorted_universe)
        findings = run_analysis(rules=["unordered-iteration-escape"], paths=[p2])
        assert len(findings) == 1 and "hash order" in findings[0].message, findings

        tsrc = (repo_root() / "karpenter_tpu" / "solver" / "tpu.py").read_text()
        unsorted_sigs = tsrc.replace("keep[sorted(repair_sigs)] = False", "keep[list(repair_sigs)] = False")
        assert unsorted_sigs != tsrc
        p3 = tmp_path / "tpu_unsorted_sigs.py"
        p3.write_text(unsorted_sigs)
        findings = run_analysis(rules=["unordered-iteration-escape"], paths=[p3])
        assert len(findings) == 1 and "list() materializes" in findings[0].message, findings

    def test_guarded_field_catches_seeded_prestage_unguard(self, tmp_path):
        """Seeded real-module regression: the PR's original race — a
        prestager stat bumped outside `_lock` — is a finding the moment it
        reappears."""
        from karpenter_tpu.analysis.core import repo_root

        src = (repo_root() / "karpenter_tpu" / "serving" / "prestage.py").read_text()
        unguarded = src.replace(
            '            touch(self, "misses")\n            self.misses += 1\n        if self.podtracer',
            "        self.misses += 1\n        if self.podtracer",
        )
        assert unguarded != src
        p = tmp_path / "prestage_unguarded.py"
        p.write_text(unguarded)
        findings = run_analysis(rules=["guarded-field-access"], paths=[p])
        assert any("'misses'" in f.message for f in findings), findings

    def test_thread_shared_registry_sanctions_real_seams(self):
        # the real serving-stack seams pass: prestage registers its worker
        # and watch callback, the churn driver is a named reviewed function
        from karpenter_tpu.analysis.core import repo_root

        for mod in ("serving/prestage.py", "serving/churn.py", "state/informer.py"):
            assert run_analysis(rules=["thread-escape"], paths=[repo_root() / "karpenter_tpu" / mod]) == []

    def test_pragma_without_justification_is_itself_a_finding(self, tmp_path):
        p = tmp_path / "naked_pragma.py"
        p.write_text(
            "def f(enc):\n"
            "    for x in enc.pods:  # solverlint: ok(python-loop-over-pod-axis)\n"
            "        x.key()\n"
        )
        findings = run_analysis(rules=["python-loop-over-pod-axis"], paths=[p])
        rules = {f.rule for f in findings}
        # the naked pragma does NOT suppress, and is flagged itself
        assert "python-loop-over-pod-axis" in rules
        assert "solverlint-pragma" in rules

    def test_label_cardinality_cap(self, tmp_path):
        import dataclasses

        from karpenter_tpu.analysis.config import Config

        body = "\n".join(f'    registry.counter("m").inc(reason="r{i}")' for i in range(6))
        p = tmp_path / "many_labels.py"
        p.write_text(f"def f(registry):\n{body}\n")
        cfg = dataclasses.replace(Config(), max_label_values=4)
        findings = run_analysis(config=cfg, rules=["metric-label-cardinality"], paths=[p])
        assert len(findings) == 1 and "6 distinct literal values" in findings[0].message


class TestShapeContracts:
    """The KARPENTER_SOLVER_TYPECHECK=1 contracts (enabled suite-wide by
    conftest) catch seeded shape/dtype drifts at the construction seam."""

    def _encode(self):
        from helpers import make_pod
        from karpenter_tpu.solver.encode import EncodeCache, encode
        from test_solver import make_snapshot

        snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(3)])
        return encode(snap, cache=EncodeCache())

    def test_typecheck_enabled_in_tier1(self):
        from karpenter_tpu.solver.contracts import typecheck_enabled

        assert os.environ.get("KARPENTER_SOLVER_TYPECHECK") == "1"
        assert typecheck_enabled()

    def test_clean_encode_passes(self):
        from karpenter_tpu.solver.contracts import check_encoded

        check_encoded(self._encode())

    def test_shape_drift_raises(self):
        import dataclasses

        from karpenter_tpu.solver.contracts import ContractError, check_encoded

        enc = self._encode()
        # drift a non-anchor field (dims bind from sig_req/row_alloc/...)
        bad = dataclasses.replace(enc, row_dom=enc.row_dom[:-1])
        with pytest.raises(ContractError, match="row_dom"):
            check_encoded(bad)

    def test_dtype_drift_raises(self):
        import dataclasses

        from karpenter_tpu.solver.contracts import ContractError, check_encoded

        enc = self._encode()
        bad = dataclasses.replace(enc, sig_taint_ok=enc.sig_taint_ok.astype(np.int32))
        with pytest.raises(ContractError, match="sig_taint_ok"):
            check_encoded(bad)

    def test_sig_of_pod_out_of_range_raises(self):
        import dataclasses

        from karpenter_tpu.solver.contracts import ContractError, check_encoded

        enc = self._encode()
        sig = enc.sig_of_pod.copy()
        sig[0] = enc.n_sigs + 7
        bad = dataclasses.replace(enc, sig_of_pod=sig)
        with pytest.raises(ContractError, match="sig_of_pod"):
            check_encoded(bad)

    def test_pack_array_contract_raises_on_bad_assignment(self):
        from karpenter_tpu.solver.contracts import ContractError, check_pack_arrays

        enc = self._encode()
        n = enc.n_rows
        slot_basis = np.arange(n, dtype=np.int64)
        slot_domset = np.ones((n, enc.n_doms), dtype=bool)
        good = np.zeros(enc.n_pods, dtype=np.int64)
        check_pack_arrays(enc, good, slot_basis, slot_domset)
        with pytest.raises(ContractError, match="assignment"):
            check_pack_arrays(enc, good.astype(np.float64), slot_basis, slot_domset)
        bad = good.copy()
        bad[0] = n + 99
        with pytest.raises(ContractError, match="assignment"):
            check_pack_arrays(enc, bad, slot_basis, slot_domset)


class TestSharedArrayFreeze:
    """Satellite: mask_encode marks reference-shared arrays read-only, so a
    mutation the linter misses raises instead of corrupting the cached base."""

    def _masked(self):
        from helpers import make_pod
        from karpenter_tpu.solver.encode import EncodeCache, encode, mask_encode
        from test_solver import make_snapshot

        snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(4)])
        enc = encode(snap, cache=EncodeCache())
        return enc, mask_encode(enc, range(enc.n_sigs))

    def test_shared_row_arrays_are_frozen(self):
        enc, masked = self._masked()
        assert masked.row_alloc is enc.row_alloc  # still shared by reference
        with pytest.raises(ValueError, match="read-only"):
            masked.row_alloc[0, 0] = 1.0
        with pytest.raises(ValueError, match="read-only"):
            enc.row_alloc[0, 0] = 1.0  # same object: the base is protected too

    def test_sliced_copies_stay_writable(self):
        enc, masked = self._masked()
        assert masked.sig_req is not enc.sig_req  # fancy indexing copies
        masked.sig_req[0, 0] = masked.sig_req[0, 0]  # must not raise
