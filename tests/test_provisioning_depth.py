"""Provisioning depth specs ported from the reference's provisioning
suite_test.go: label/annotation/taint propagation onto nodes, NodeClaim
request contents (requirements, resource requests, owner/nodeclass
references), container/initContainer resource math, and weighted-pool
ordering."""

import pytest

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube import Container, ObjectMeta, Pod, PodSpec
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.taints import Taint
from karpenter_tpu.utils.resources import parse_resource_list

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env(np_kwargs=None, pools=None, **opt_kwargs):
    env = Environment(options=Options(**opt_kwargs))
    for np in pools or [make_nodepool(**dict({"requirements": LINUX_AMD64}, **(np_kwargs or {})))]:
        env.store.create(np)
    return env


def provision(env, pods, rounds=6):
    for p in pods:
        env.store.create(p)
    env.settle(rounds=rounds)
    return env


class TestNodeMetadataPropagation:
    def test_annotations_propagate_to_nodes(self):
        # suite_test.go:1527 "should annotate nodes"
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.template.annotations = {"custom/annotation": "myAnnotation"}
        env = make_env(pools=[np])
        provision(env, [make_pod(cpu="1", name="p0")])
        node = env.store.list("Node")[0]
        assert node.metadata.annotations.get("custom/annotation") == "myAnnotation"

    def test_labels_propagate_to_nodes(self):
        # suite_test.go:1545 "should label nodes" — template labels plus the
        # well-known set (nodepool, instance-type, capacity-type, zone)
        np = make_nodepool(requirements=LINUX_AMD64, labels={"custom/label": "myLabel", "other/label": "v"})
        env = make_env(pools=[np])
        provision(env, [make_pod(cpu="1", name="p0")])
        node = env.store.list("Node")[0]
        lbls = node.metadata.labels
        assert lbls.get("custom/label") == "myLabel"
        assert lbls.get("other/label") == "v"
        assert lbls.get(wk.NODEPOOL_LABEL_KEY) == np.metadata.name
        assert lbls.get(wk.INSTANCE_TYPE_LABEL_KEY)
        assert lbls.get(wk.CAPACITY_TYPE_LABEL_KEY)
        assert lbls.get(wk.ZONE_LABEL_KEY)

    @pytest.mark.parametrize("domain", ["kubernetes.io", "k8s.io", "subdomain.kubernetes.io"])
    def test_kubernetes_domain_labels(self, domain):
        # suite_test.go:1578/1600 — template labels in the kubernetes domains
        # (and their subdomains) are allowed and land on nodes; pods may
        # select on them (reference RestrictedLabelDomains covers only the
        # karpenter.sh group, labels.go:68-71)
        np = make_nodepool(requirements=LINUX_AMD64, labels={f"{domain}/test": "test-value"})
        env = make_env(pools=[np])
        provision(env, [make_pod(cpu="1", name="p0", node_selector={f"{domain}/test": "test-value"})])
        assert env.store.get("Pod", "p0").spec.node_name
        node = env.store.list("Node")[0]
        assert node.metadata.labels.get(f"{domain}/test") == "test-value"


class TestTaintPropagation:
    def test_pods_must_tolerate_template_taints(self):
        # suite_test.go:1644 "should schedule pods that tolerate taints"
        np = make_nodepool(requirements=LINUX_AMD64, taints=[Taint(key="example.com/special", value="true", effect="NoSchedule")])
        env = make_env(pools=[np])
        tolerating = make_pod(
            cpu="1",
            name="ok",
            tolerations=[{"key": "example.com/special", "operator": "Equal", "value": "true", "effect": "NoSchedule"}],
        )
        intolerant = make_pod(cpu="1", name="nope")
        provision(env, [tolerating, intolerant])
        assert env.store.get("Pod", "ok").spec.node_name
        assert not env.store.get("Pod", "nope").spec.node_name
        node = env.store.list("Node")[0]
        assert any(t.key == "example.com/special" for t in node.spec.taints)


class TestNodeClaimRequest:
    def test_claim_requirements_reflect_pod_and_pool(self):
        # suite_test.go:1694/1765 — the claim's requirements restrict
        # architecture/zone per pod selector plus pool requirements
        env = make_env()
        provision(env, [make_pod(cpu="1", name="p0", node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})])
        nc = env.store.list("NodeClaim")[0]
        from karpenter_tpu.scheduling.requirements import Requirements

        reqs = Requirements.from_node_selector_terms(nc.spec.requirements)
        assert reqs.get(wk.ZONE_LABEL_KEY).has("test-zone-b")
        assert not reqs.get(wk.ZONE_LABEL_KEY).has("test-zone-a")
        assert reqs.get(wk.ARCH_LABEL_KEY).has("amd64")

    def test_claim_carries_resource_requests(self):
        # suite_test.go:1912 "should create a nodeclaim with resource requests"
        env = make_env()
        provision(env, [make_pod(cpu="1", memory="1Gi", name="p0")])
        nc = env.store.list("NodeClaim")[0]
        assert nc.spec.resources and nc.spec.resources["cpu"].milli >= 1000
        assert nc.spec.resources["memory"].milli >= 1024**3 * 1000 // 1000

    def test_claim_requests_include_daemon_overhead_once(self):
        # suite_test.go:1938/1958 — daemon overhead counts once per claim,
        # not once per pod
        from karpenter_tpu.kube.objects import DaemonSet

        env = make_env()
        ds = DaemonSet(
            metadata=ObjectMeta(name="ds"),
            template_spec=PodSpec(containers=[Container(resources={"requests": parse_resource_list({"cpu": "1"})})]),
        )
        env.store.create(ds)
        provision(env, [make_pod(cpu="1", name=f"p{i}") for i in range(2)])
        ncs = env.store.list("NodeClaim")
        assert len(ncs) == 1
        # 2 pods x 1cpu + 1cpu daemon overhead = 3cpu, NOT 4
        assert 3000 <= ncs[0].spec.resources["cpu"].milli < 4000

    def test_claim_owner_and_nodeclass_reference(self):
        # suite_test.go:1866/1884
        env = make_env()
        provision(env, [make_pod(cpu="1", name="p0")])
        nc = env.store.list("NodeClaim")[0]
        assert nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == env.store.list("NodePool")[0].metadata.name
        assert nc.spec.node_class_ref is not None and nc.spec.node_class_ref.name


class TestContainerResourceMath:
    def test_max_of_containers_and_init_containers(self):
        # suite_test.go:1069 — effective request = max(sum(containers),
        # max(initContainers)) per resource
        env = make_env()
        pod = Pod(
            metadata=ObjectMeta(name="mixed"),
            spec=PodSpec(
                containers=[
                    Container(resources={"requests": parse_resource_list({"cpu": "1", "memory": "1Gi"})}),
                    Container(resources={"requests": parse_resource_list({"cpu": "1", "memory": "1Gi"})}),
                ],
                init_containers=[
                    Container(resources={"requests": parse_resource_list({"cpu": "3", "memory": "1Gi"})}),
                ],
            ),
        )
        provision(env, [pod])
        assert env.store.get("Pod", "mixed").spec.node_name
        node = env.store.list("Node")[0]
        # the chosen node must fit the 3-cpu init phase, not just 2 cpu
        assert node.status.allocatable["cpu"].milli >= 3000

    def test_oversized_init_container_blocks(self):
        # suite_test.go:1118
        env = make_env()
        pod = Pod(
            metadata=ObjectMeta(name="huge-init"),
            spec=PodSpec(
                containers=[Container(resources={"requests": parse_resource_list({"cpu": "1"})})],
                init_containers=[Container(resources={"requests": parse_resource_list({"cpu": "10000"})})],
            ),
        )
        provision(env, [pod])
        assert not env.store.get("Pod", "huge-init").spec.node_name
        assert env.store.count("NodeClaim") == 0

    def test_requestless_pods_schedule(self):
        # suite_test.go:1134
        env = make_env()
        pod = Pod(metadata=ObjectMeta(name="zero"), spec=PodSpec(containers=[Container()]))
        provision(env, [pod])
        assert env.store.get("Pod", "zero").spec.node_name


class TestWeightedPools:
    def two_pools(self, w_hi=50, w_lo=10, hi_reqs=None):
        hi = make_nodepool(name="hi", requirements=hi_reqs or LINUX_AMD64, weight=w_hi)
        lo = make_nodepool(name="lo", requirements=LINUX_AMD64, weight=w_lo)
        return [hi, lo]

    def test_higher_weight_pool_wins(self):
        # suite_test.go:2813 Weighted NodePools
        env = make_env(pools=self.two_pools())
        provision(env, [make_pod(cpu="1", name="p0")])
        nc = env.store.list("NodeClaim")[0]
        assert nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == "hi"

    def test_falls_through_when_heavy_pool_incompatible(self):
        arm_only = [
            {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["arm64"]},
            {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
        ]
        env = make_env(pools=self.two_pools(hi_reqs=arm_only))
        provision(env, [make_pod(cpu="1", name="p0", node_selector={wk.ARCH_LABEL_KEY: "amd64"})])
        nc = env.store.list("NodeClaim")[0]
        assert nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == "lo"

    def test_pod_nodepool_selector_pins_pool(self):
        env = make_env(pools=self.two_pools())
        provision(env, [make_pod(cpu="1", name="p0", node_selector={wk.NODEPOOL_LABEL_KEY: "lo"})])
        nc = env.store.list("NodeClaim")[0]
        assert nc.metadata.labels.get(wk.NODEPOOL_LABEL_KEY) == "lo"
