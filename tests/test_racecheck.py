"""racecheck: the runtime concurrency sanitizer (ISSUE 11).

Pins the wrapper's contracts:
- off-mode bit-parity: with KARPENTER_SOLVER_RACECHECK unset/0 the factories
  return the PLAIN threading primitives (zero overhead, identical types);
- guarded-field enforcement: touching a GUARDED_FIELDS-declared field
  without its lock raises; with the lock held it passes;
- lock-order: a dynamic inversion (even a transitive 3-cycle) raises at the
  acquisition site; reentrant RLock re-acquisition records no edge;
- observability: wait-time stats land in the named-lock histogram, long
  holds are recorded as outliers;
- the race fixes the static rules drove: prestager stats stay consistent
  under a take/pump hammer, and the OperatorServer/PendingPrestager stop()
  paths survive double and concurrent calls;
- the threaded churn stress: `ChurnHarness.run_concurrent` under the
  sanitizer records ZERO violations.
"""

from __future__ import annotations

import threading
import time

import pytest

from karpenter_tpu.obs import racecheck
from karpenter_tpu.obs.racecheck import (
    InstrumentedLock,
    RaceCheckError,
    make_event,
    make_lock,
    make_rlock,
    racecheck_enabled,
    spawn_thread,
    touch,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    # each test starts from an empty order graph; the suite-wide graph the
    # other suites accumulate is not this file's subject
    racecheck.reset()
    yield
    racecheck.reset()


class TestFactoryParity:
    def test_off_mode_returns_plain_primitives(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_RACECHECK", "0")
        racecheck._refresh()
        try:
            assert isinstance(make_lock("x"), type(threading.Lock()))
            assert isinstance(make_rlock("x"), type(threading.RLock()))
        finally:
            racecheck._refresh()

    def test_on_mode_returns_instrumented(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_RACECHECK", "1")
        racecheck._refresh()
        try:
            lk = make_lock("x")
            assert isinstance(lk, InstrumentedLock)
            with lk:
                assert lk.held_by_me and lk.locked()
            assert not lk.locked()
        finally:
            racecheck._refresh()

    def test_event_and_thread_wrappers(self):
        ev = make_event()
        hits = []
        t = spawn_thread(lambda: (ev.wait(5), hits.append(1)), name="racecheck-test")
        ev.set()
        t.join(timeout=5)
        assert hits == [1]

    def test_conftest_enables_sanitizer(self):
        assert racecheck_enabled()


class TestInstrumentedLock:
    def test_with_and_acquire_release(self):
        lk = InstrumentedLock("t-basic")
        with lk:
            assert lk.held_by_me
        assert lk.acquire()
        lk.release()

    def test_non_reentrant_relock_raises_instead_of_deadlocking(self):
        lk = InstrumentedLock("t-relock")
        with lk:
            with pytest.raises(RaceCheckError, match="re-acquired"):
                lk.acquire()

    def test_reentrant_rlock_allows_and_records_no_self_edge(self):
        lk = InstrumentedLock("t-rlock", reentrant=True)
        with lk:
            with lk:
                assert lk.held_by_me
        assert not lk.locked()
        assert racecheck.snapshot()["edges"] == {}

    def test_foreign_release_raises(self):
        lk = InstrumentedLock("t-foreign")
        lk.acquire()
        err = []
        t = spawn_thread(lambda: err.append(isinstance(_try_release(lk), RaceCheckError)))
        t.join(timeout=5)
        lk.release()
        assert err == [True]


def _try_release(lk):
    try:
        lk.release()
    except Exception as e:  # noqa: BLE001
        return e
    return None


class TestLockOrder:
    def test_direct_inversion_raises(self):
        a, b = InstrumentedLock("t-a"), InstrumentedLock("t-b")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(RaceCheckError, match="inversion"):
                with a:
                    pass
        assert racecheck.snapshot()["violations"]

    def test_transitive_cycle_raises(self):
        # a->b, b->c, then c->a: no directly reversed edge anywhere, but the
        # closure is a cycle — the reachability check must catch it
        a, b, c = InstrumentedLock("t-x"), InstrumentedLock("t-y"), InstrumentedLock("t-z")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with pytest.raises(RaceCheckError, match="inversion"):
                with a:
                    pass

    def test_consistent_order_is_clean(self):
        a, b = InstrumentedLock("t-c1"), InstrumentedLock("t-c2")
        for _ in range(3):
            with a:
                with b:
                    pass
        snap = racecheck.snapshot()
        assert ("t-c1", "t-c2") in snap["edges"]
        assert snap["violations"] == []

    def test_same_name_nesting_records_no_edge(self):
        # two instances of one lock CLASS share a graph node; nesting them
        # is not an order relation (e.g. two different metric objects)
        a, b = InstrumentedLock("t-same"), InstrumentedLock("t-same")
        with a:
            with b:
                pass
        assert racecheck.snapshot()["edges"] == {}


class TestGuardedFields:
    class Stats:
        GUARDED_FIELDS = {"hits": "_lock"}

        def __init__(self):
            self._lock = InstrumentedLock("t-stats")
            self.hits = 0

    def test_touch_without_lock_raises(self):
        s = self.Stats()
        with pytest.raises(RaceCheckError, match="without holding"):
            touch(s, "hits")

    def test_touch_with_lock_passes(self):
        s = self.Stats()
        with s._lock:
            touch(s, "hits")
            s.hits += 1
        assert racecheck.snapshot()["touch_checks"] >= 1

    def test_undeclared_field_raises(self):
        s = self.Stats()
        with pytest.raises(RaceCheckError, match="not declared"):
            touch(s, "nope")


class TestObservability:
    def test_wait_stats_and_histogram(self):
        from karpenter_tpu import metrics as m

        reg = m.make_registry()
        racecheck.set_metrics_registry(reg)
        try:
            lk = InstrumentedLock("t-wait")
            with lk:
                pass
            snap = racecheck.snapshot()
            assert snap["wait"]["t-wait"][0] >= 1
            assert reg.histogram(m.SOLVER_LOCK_WAIT_SECONDS).count(lock="t-wait") >= 1
        finally:
            racecheck.set_metrics_registry(None)

    def test_hold_outlier_recorded(self, monkeypatch):
        monkeypatch.setattr(racecheck, "_HOLD_OUTLIER_SECONDS", 0.0)
        lk = InstrumentedLock("t-hold")
        with lk:
            time.sleep(0.002)
        outliers = racecheck.snapshot()["hold_outliers"]
        assert outliers and outliers[0][0] == "t-hold" and outliers[0][1] > 0


class TestRaceFixes:
    def test_prestager_stats_consistent_under_hammer(self):
        """The PR's seed race: staged/reused/misses were bumped outside
        _lock, so concurrent takes lost increments. Now every take lands in
        exactly one bucket."""
        from karpenter_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
        from karpenter_tpu.serving.prestage import PendingPrestager

        p = PendingPrestager()
        pods = [
            Pod(metadata=ObjectMeta(name=f"h{i}", namespace="default", uid=f"uid-h{i}", resource_version=1),
                spec=PodSpec(containers=[Container()]))
            for i in range(40)
        ]
        n_threads, rounds = 4, 25
        barrier = threading.Barrier(n_threads)

        def hammer():
            barrier.wait()
            for _ in range(rounds):
                for pod in pods:
                    p.take(pod)

        threads = [spawn_thread(hammer, name=f"hammer-{i}") for i in range(n_threads)]
        for t in threads:
            t.join(timeout=30)
        total = n_threads * rounds * len(pods)
        assert p.reused + p.misses == total, (p.reused, p.misses, total)
        # identity contract held: each pod cloned at most once per rv
        assert p.misses >= len(pods)
        assert racecheck.snapshot()["violations"] == []

    def test_prestager_stop_idempotent_and_concurrent(self):
        from karpenter_tpu.serving.prestage import PendingPrestager

        p = PendingPrestager()
        p.start()
        assert p.worker_running()
        threads = [spawn_thread(p.stop, name=f"stop-{i}") for i in range(4)]
        for t in threads:
            t.join(timeout=10)
        p.stop()  # and once more, serially
        assert not p.worker_running()
        p.start()  # restartable after a full stop
        assert p.worker_running()
        p.stop()

    def test_prestager_start_during_stop_does_not_resurrect_old_worker(self):
        """Regression: stop() used to share one _stop event with every
        worker generation, so a start() landing in stop()'s join window
        cleared the event the OLD worker polls — leaving two live _run
        consumers on the single-consumer queue. Each generation now owns
        its stop event."""
        from karpenter_tpu.serving.prestage import PendingPrestager

        p = PendingPrestager()
        for _ in range(10):
            p.start()
            old = p._thread
            stopper = spawn_thread(p.stop, name="race-stop")
            p.start()  # may land anywhere inside stop(): claim, set, join
            stopper.join(timeout=10)
            old.join(timeout=5)
            assert not old.is_alive()  # the old generation always dies
            p.stop()
        assert not p.worker_running()

    def test_operator_server_start_is_idempotent(self):
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.server import OperatorServer

        env = Environment()
        srv = OperatorServer(env, port=0, bind="127.0.0.1")
        port = srv.start()
        assert srv.start() == port  # second start: same listener, no leak
        srv.stop()
        assert srv._httpd is None

    def test_operator_server_stop_idempotent_and_concurrent(self):
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.server import OperatorServer

        env = Environment()
        srv = OperatorServer(env, port=0, bind="127.0.0.1")
        srv.start()
        threads = [spawn_thread(srv.stop, name=f"srvstop-{i}") for i in range(4)]
        for t in threads:
            t.join(timeout=10)
        srv.stop()  # double-call after the fact is a no-op
        assert srv._httpd is None


class TestThreadedChurnStress:
    def test_run_concurrent_zero_violations(self):
        """The acceptance gate: the live serving stack (store watch delivery,
        batcher coalescing, prestager worker, churn driver thread) under the
        sanitizer — zero guarded-field or lock-order violations."""
        from karpenter_tpu.serving import ChurnHarness, ChurnSpec

        assert racecheck_enabled()
        spec = ChurnSpec(
            n_base_pods=120,
            n_types=10,
            arrivals=30,
            cancels=24,
            departures=30,
            bind_every=2,
            iterations=2,
            warmup_cycles=1,
            concurrent_seconds=0.0,
            worker=True,  # the real prestager worker thread, overlapping takes
        )
        h = ChurnHarness(spec).build()
        try:
            h.provision_base_fleet()
            h.run_cycle()
            events, solves = h.run_concurrent(1.0)
            assert events > 0 and solves > 0
        finally:
            h.close()
        snap = racecheck.snapshot()
        assert snap["violations"] == [], snap["violations"]
        # the sanitizer demonstrably observed the serving stack's locks
        assert {"store", "store-deliver", "batcher", "prestage", "cluster"} <= set(snap["wait"])
