"""Topology depth specs ported from the reference's topology_test.go (3,118
LoC): zone/hostname/capacity-type/arch spread, minDomains, skew edges,
ScheduleAnyway, node taint/affinity policies, multi-constraint interplay, and
pod (anti-)affinity families. Solver-level cases additionally run through the
TPU backend where in-window (compare_backends)."""

import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from test_scheduler import LINUX_AMD64, build_env, make_scheduler
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube import PodAffinityTerm, TopologySpreadConstraint


def solve(pods, node_pools=None, types=None, **kw):
    env = build_env(node_pools=node_pools, types=types)
    s = make_scheduler(*env, **kw)
    return s.solve(pods)


def spread(key, max_skew=1, selector=None, when="DoNotSchedule", min_domains=None, taints_policy="Ignore", affinity_policy="Honor"):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=when,
        label_selector=selector,
        min_domains=min_domains,
        node_taints_policy=taints_policy,
        node_affinity_policy=affinity_policy,
    )


def zone_counts(results):
    counts = {}
    for nc in results.new_node_claims:
        z = nc.requirements.get(wk.ZONE_LABEL_KEY)
        assert len(z.values) == 1, f"zone not committed: {sorted(z.values)}"
        counts[z.any()] = counts.get(z.any(), 0) + len(nc.pods)
    return counts


def domain_counts(results, key):
    counts = {}
    for nc in results.new_node_claims:
        r = nc.requirements.get(key)
        d = r.any() if len(r.values) == 1 else tuple(sorted(r.values))
        counts[d] = counts.get(d, 0) + len(nc.pods)
    return counts


SEL = {"matchLabels": {"app": "web"}}


def web_pods(n, **kw):
    return [make_pod(labels={"app": "web"}, **kw) for _ in range(n)]


class TestZoneSpreadDepth:
    def test_balance_across_zones_match_labels(self):
        # topology_test.go:108
        results = solve(web_pods(8, tsc=[zone_spread(1, SEL)]))
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 1
        assert sum(counts.values()) == 8

    def test_balance_across_zones_match_expressions(self):
        # topology_test.go:121
        sel = {"matchExpressions": [{"key": "app", "operator": "In", "values": ["web"]}]}
        results = solve(web_pods(6, tsc=[zone_spread(1, sel)]))
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_respects_nodepool_zonal_constraints(self):
        # topology_test.go:142 — pool pinned to one zone: all pods land there
        np = make_nodepool(requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}])
        results = solve(web_pods(5, tsc=[zone_spread(1, SEL)]), node_pools=[np])
        assert results.all_pods_scheduled()
        assert set(zone_counts(results)) == {"test-zone-a"}

    def test_respects_nodepool_zonal_subset(self):
        # topology_test.go:157 — two zones allowed: spread is over the subset
        np = make_nodepool(
            requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]
        )
        results = solve(web_pods(6, tsc=[zone_spread(1, SEL)]), node_pools=[np])
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert set(counts) == {"test-zone-a", "test-zone-b"}
        assert counts["test-zone-a"] == counts["test-zone-b"] == 3

    def test_zonal_subset_with_labels(self):
        # topology_test.go:173 — template label pins the domain
        np = make_nodepool(requirements=LINUX_AMD64, labels={wk.ZONE_LABEL_KEY: "test-zone-b"})
        results = solve(web_pods(4, tsc=[zone_spread(1, SEL)]), node_pools=[np])
        assert results.all_pods_scheduled()
        assert set(zone_counts(results)) == {"test-zone-b"}

    def test_zonal_subset_across_nodepools(self):
        # topology_test.go:204 — two single-zone pools split the spread
        np_a = make_nodepool(name="pool-a", requirements=LINUX_AMD64, labels={wk.ZONE_LABEL_KEY: "test-zone-a"})
        np_b = make_nodepool(name="pool-b", requirements=LINUX_AMD64, labels={wk.ZONE_LABEL_KEY: "test-zone-b"})
        results = solve(web_pods(6, tsc=[zone_spread(1, SEL)]), node_pools=[np_a, np_b])
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert counts.get("test-zone-a", 0) == counts.get("test-zone-b", 0) == 3

    def test_max_skew_2(self):
        results = solve(web_pods(9, tsc=[zone_spread(2, SEL)]))
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 2

    def test_do_not_schedule_never_violates_skew(self):
        # topology_test.go:347 — single available zone + skew 1: only 1 pod
        # can go until other domains exist; with one zone all pods CAN land
        # there (skew vs min over available domains)
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a"])]
        results = solve(web_pods(5, tsc=[zone_spread(1, SEL)]), types=types)
        assert results.all_pods_scheduled()
        assert set(zone_counts(results)) == {"test-zone-a"}

    def test_unknown_topology_key_blocks(self):
        # the reference schedules pods with topology keys no node carries by
        # treating the constraint as having no domains -> unschedulable until
        # a domain exists; our host treats it as zero supported domains
        results = solve(web_pods(2, tsc=[spread("custom.io/rack", selector=SEL)]))
        assert len(results.pod_errors) == 2

    def test_matches_all_pods_when_selector_omitted(self):
        # topology_test.go:445 — nil selector counts nothing but still spreads
        # the constrained pod itself
        results = solve([make_pod(tsc=[zone_spread(1, None)]) for _ in range(3)])
        assert results.all_pods_scheduled()

    def test_interdependent_selectors(self):
        # topology_test.go:457 — two deployments whose spreads select each other
        sel_both = {"matchExpressions": [{"key": "app", "operator": "In", "values": ["a", "b"]}]}
        pods = [make_pod(labels={"app": "a"}, tsc=[zone_spread(1, sel_both)]) for _ in range(3)] + [
            make_pod(labels={"app": "b"}, tsc=[zone_spread(1, sel_both)]) for _ in range(3)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 1


class TestMinDomains:
    def test_min_domains_forces_extra_zones(self):
        # topology_test.go:482 — minDomains=3: even 2 pods must open 2 zones
        # and a third domain must be possible; counts spread over >= minDomains
        results = solve(web_pods(3, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, min_domains=3)]))
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) >= 3

    def test_min_domains_equal_available_allows_scheduling(self):
        # topology_test.go:502 — minDomains == available domains
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a", "test-zone-b", "test-zone-c"])]
        results = solve(web_pods(6, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, min_domains=3)]), types=types)
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) == 3

    def test_min_domains_greater_than_available_caps_at_skew(self):
        # k8s semantics: with fewer domains than minDomains the global minimum
        # is treated as 0, so each zone accepts up to maxSkew pods and the
        # rest wedge (upstream minDomains contract)
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a", "test-zone-b"])]
        results = solve(web_pods(3, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, min_domains=3)]), types=types)
        assert len(results.pod_errors) == 1
        counts = zone_counts(results)
        assert counts == {"test-zone-a": 1, "test-zone-b": 1}

    def test_min_domains_pvc_spread(self):
        # topology_test.go:3060 analogue (without PVC): 3 zones, minDomains=3
        results = solve(web_pods(9, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, min_domains=3)]))
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert len(counts) >= 3 and max(counts.values()) - min(counts.values()) <= 1


class TestHostnameSpreadDepth:
    def test_balance_across_nodes(self):
        # topology_test.go:545
        results = solve(web_pods(4, cpu="100m", tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=SEL)]))
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 4
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)

    def test_same_hostname_up_to_max_skew(self):
        # topology_test.go:558 — maxSkew=4: up to 4 pods per fresh node
        results = solve(web_pods(4, cpu="100m", tsc=[spread(wk.HOSTNAME_LABEL_KEY, max_skew=4, selector=SEL)]))
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1

    def test_multiple_deployments_hostname_spread(self):
        # topology_test.go:571 — two deployments, each spreading by hostname
        sel_a, sel_b = {"matchLabels": {"app": "a"}}, {"matchLabels": {"app": "b"}}
        pods = [make_pod(cpu="100m", labels={"app": "a"}, tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=sel_a)]) for _ in range(2)] + [
            make_pod(cpu="100m", labels={"app": "b"}, tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=sel_b)]) for _ in range(2)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        # each deployment's pods land on distinct nodes; deployments may share
        for nc in results.new_node_claims:
            apps = [p.metadata.labels["app"] for p in nc.pods]
            assert len(apps) == len(set(apps))


class TestCapacityTypeAndArchSpread:
    def test_balance_across_capacity_types(self):
        # topology_test.go:653
        results = solve(web_pods(4, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)]))
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_respects_nodepool_capacity_type_constraint(self):
        # topology_test.go:666 — OD-only pool: all pods one domain
        np = make_nodepool(
            requirements=LINUX_AMD64
            + [{"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]}]
        )
        results = solve(web_pods(3, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)]), node_pools=[np])
        assert results.all_pods_scheduled()
        assert set(domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)) == {wk.CAPACITY_TYPE_ON_DEMAND}

    def test_balance_across_arch(self):
        # topology_test.go:895 — no arch constraint on the pool
        np = make_nodepool(requirements=[{"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]}])
        results = solve(
            [make_pod(labels={"app": "web"}, tsc=[spread(wk.ARCH_LABEL_KEY, selector=SEL)]) for _ in range(4)],
            node_pools=[np],
        )
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ARCH_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1


def env_with_labeled_nodes(node_labels_list, node_pools, cpu="100m"):
    """Existing tiny nodes carrying custom labels (the reference's
    NodeInclusionPolicy specs build domains from unreachable nodes)."""
    from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
    from karpenter_tpu.kube import Node, ObjectMeta, Store
    from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
    from karpenter_tpu.state import Cluster
    from karpenter_tpu.state.informer import start_informers
    from karpenter_tpu.utils.clock import FakeClock
    from karpenter_tpu.utils.resources import parse_resource_list

    store, clock = Store(), FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    for np in node_pools:
        store.create(np)
    for i, labels in enumerate(node_labels_list):
        nc = NodeClaim(metadata=ObjectMeta(name=f"ec-{i}"))
        nc.status.provider_id = f"kwok://en-{i}"
        nc.status.conditions.set_true(COND_REGISTERED)
        nc.status.conditions.set_true(COND_INITIALIZED)
        store.create(nc)
        store.create(
            Node(
                metadata=ObjectMeta(name=f"en-{i}", labels={wk.HOSTNAME_LABEL_KEY: f"en-{i}", **labels}),
                spec=NodeSpec(provider_id=f"kwok://en-{i}"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": cpu, "memory": "256Mi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": cpu, "memory": "256Mi", "pods": "110"}),
                ),
            )
        )
    return store, clock, cluster, node_pools, catalog.construct_instance_types()


class TestSpreadPolicies:
    def _tainted_pools(self):
        from karpenter_tpu.scheduling.taints import Taint

        tainted = make_nodepool(
            name="tainted",
            requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-d"]}],
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        )
        open_np = make_nodepool(
            name="open",
            requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}],
        )
        return open_np, tainted

    def test_node_taints_policy_honor_excludes_tainted_pool(self):
        # topology_test.go:1392 — under Honor an intolerant pod doesn't count
        # the tainted pool's zone as a domain: spread balances over a and b
        open_np, tainted = self._tainted_pools()
        results = solve(
            web_pods(4, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, taints_policy="Honor")]),
            node_pools=[open_np, tainted],
        )
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert "test-zone-d" not in counts
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_node_taints_policy_ignore_counts_tainted_domains(self):
        # topology_test.go:1336 — under Ignore the tainted pool's zone counts
        # as a 0-domain the pod can never reach: the spread wedges at maxSkew
        open_np, tainted = self._tainted_pools()
        results = solve(
            web_pods(4, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, taints_policy="Ignore")]),
            node_pools=[open_np, tainted],
        )
        # zone-d stuck at 0: only maxSkew pods per reachable zone (a, b)
        assert len(results.pod_errors) == 2

    def _affinity_policy_fixture(self, policy):
        # topology_test.go:1529/1596 — two tiny existing nodes carry
        # spread-label domains foo/bar with selector=mismatch; the pool offers
        # baz with selector=value; pods select selector=value
        np = make_nodepool(requirements=LINUX_AMD64, labels={"fake-label": "baz", "selector": "value"})
        env = env_with_labeled_nodes(
            [{"fake-label": "foo", "selector": "mismatch"}, {"fake-label": "bar", "selector": "mismatch"}],
            [np],
        )
        s = make_scheduler(*env)
        pods = web_pods(
            5,
            node_selector={"selector": "value"},
            tsc=[spread("fake-label", selector=SEL, affinity_policy=policy)],
        )
        return s.solve(pods)

    def test_node_affinity_policy_ignore_counts_filtered_domains(self):
        # Ignore: foo/bar count although the pod can't reach them; only one
        # pod may land on baz before skew wedges
        results = self._affinity_policy_fixture("Ignore")
        assert len(results.pod_errors) == 4
        assert sum(len(nc.pods) for nc in results.new_node_claims) == 1

    def test_node_affinity_policy_honor_filters_domains(self):
        # Honor: the unreachable foo/bar nodes are filtered out; all pods
        # schedule onto baz
        results = self._affinity_policy_fixture("Honor")
        assert results.all_pods_scheduled()


class TestMultiConstraintInterplay:
    def test_hostname_and_zone_together(self):
        # topology_test.go:941
        pods = web_pods(6, cpu="100m", tsc=[zone_spread(1, SEL), spread(wk.HOSTNAME_LABEL_KEY, max_skew=1, selector=SEL)])
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 1
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)

    def test_zone_and_capacity_type_together(self):
        # topology_test.go:1049
        pods = web_pods(8, tsc=[zone_spread(1, SEL), spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)])
        results = solve(pods)
        assert results.all_pods_scheduled()
        zc = zone_counts(results)
        cc = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(zc.values()) - min(zc.values()) <= 1
        assert max(cc.values()) - min(cc.values()) <= 1

    def test_spread_limited_by_node_selector(self):
        # topology_test.go:1740 — nodeSelector narrows spread domains
        pods = web_pods(4, node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"}, tsc=[zone_spread(1, SEL)])
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert set(zone_counts(results)) == {"test-zone-b"}

    def test_spread_limited_by_required_node_affinity(self):
        # topology_test.go:1788
        pods = web_pods(
            6,
            required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]],
            tsc=[zone_spread(1, SEL)],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert set(counts) <= {"test-zone-a", "test-zone-b"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_spread_not_limited_by_preferred_affinity(self):
        # topology_test.go:1832 — preferences do NOT narrow spread domains
        pods = web_pods(
            8,
            preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}])],
            tsc=[zone_spread(1, SEL)],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) > 1


class TestPodAffinityDepth:
    def test_empty_affinity_schedules(self):
        # topology_test.go:1926
        from karpenter_tpu.kube import Affinity

        p = make_pod()
        p.spec.affinity = Affinity()
        results = solve([p])
        assert results.all_pods_scheduled()

    def test_pod_affinity_hostname_colocates(self):
        # topology_test.go:1936
        sel = {"matchLabels": {"app": "cache"}}
        pods = [make_pod(cpu="100m", labels={"app": "cache"}, pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.HOSTNAME_LABEL_KEY)]) for _ in range(3)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert len([nc for nc in results.new_node_claims if nc.pods]) == 1

    def test_self_affinity_zone(self):
        # topology_test.go:2123
        sel = {"matchLabels": {"app": "self"}}
        pods = [make_pod(labels={"app": "self"}, pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)]) for _ in range(4)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) == 1

    def test_affinity_to_nonexistent_pod_blocks(self):
        # topology_test.go:2710
        sel = {"matchLabels": {"app": "ghost"}}
        pods = [make_pod(pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)])]
        results = solve(pods)
        assert len(results.pod_errors) == 1

    def test_affinity_namespace_filtering_no_match(self):
        # topology_test.go:2840 — target exists in another namespace only
        sel = {"matchLabels": {"app": "t"}}
        target = make_pod(ns="other", labels={"app": "t"})
        chaser = make_pod(ns="default", pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)])
        results = solve([target, chaser])
        assert chaser.key() in results.pod_errors

    def test_affinity_namespace_list_matches(self):
        # topology_test.go:2878 — hostname affinity across an explicit
        # namespace list colocates with the target pod
        sel = {"matchLabels": {"app": "t"}}
        target = make_pod(ns="other", labels={"app": "t"})
        chaser = make_pod(
            ns="default",
            pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.HOSTNAME_LABEL_KEY, namespaces=["other"])],
        )
        results = solve([target, chaser])
        assert results.all_pods_scheduled()
        homes = [nc for nc in results.new_node_claims if nc.pods]
        assert len(homes) == 1, "affinity must colocate the chaser with its target"

    def test_two_affinity_groups_with_incompatible_selectors(self):
        # topology_test.go:2178
        sel_a, sel_b = {"matchLabels": {"g": "a"}}, {"matchLabels": {"g": "b"}}
        pods = [
            make_pod(labels={"g": "a"}, node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"}, pod_affinity=[PodAffinityTerm(label_selector=sel_a, topology_key=wk.ZONE_LABEL_KEY)]),
            make_pod(labels={"g": "b"}, node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"}, pod_affinity=[PodAffinityTerm(label_selector=sel_b, topology_key=wk.ZONE_LABEL_KEY)]),
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) == 2


class TestPodAntiAffinityDepth:
    def test_simple_hostname_anti_affinity_separates(self):
        # topology_test.go:2297
        sel = {"matchLabels": {"app": "db"}}
        pods = [make_pod(cpu="100m", labels={"app": "db"}, anti_affinity=[hostname_anti_affinity(sel)]) for _ in range(4)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)

    def test_zone_anti_affinity_not_violated(self):
        # topology_test.go:2319 — 4 zones, 5 zone-anti pods: at most one
        # schedules per batch (late committal blocks the rest)
        sel = {"matchLabels": {"app": "db"}}
        pods = [make_pod(labels={"app": "db"}, anti_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)]) for _ in range(5)]
        results = solve(pods)
        placed = [nc for nc in results.new_node_claims if nc.pods]
        zones = set()
        for nc in placed:
            zones.update(nc.requirements.get(wk.ZONE_LABEL_KEY).values)
        # no two placed pods share a zone
        assert len(zones) >= len(placed)

    def test_anti_affinity_against_running_pod(self):
        # topology_test.go:2530 analogue via cluster state is covered in
        # test_solver fallback; here: the anti pod schedules when no match runs
        sel = {"matchLabels": {"app": "lonely"}}
        pods = [make_pod(labels={"app": "lonely"}, anti_affinity=[hostname_anti_affinity(sel)])]
        results = solve(pods)
        assert results.all_pods_scheduled()

    def test_anti_affinity_different_selector_coexists(self):
        sel_other = {"matchLabels": {"app": "other"}}
        pods = [make_pod(cpu="100m", labels={"app": "db"}, anti_affinity=[hostname_anti_affinity(sel_other)]) for _ in range(3)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        # the selector matches nothing: pods pack onto one node
        assert len([nc for nc in results.new_node_claims if nc.pods]) == 1


class TestMatchLabelKeys:
    """topology_test.go MatchLabelKeys context (k8s >= 1.27): the pod's
    values for the listed label keys merge into the spread selector, giving
    per-revision spread groups (topology.go:467-475)."""

    def test_match_label_keys_split_spread_groups(self):
        # two "revisions" of one deployment: hostname spread with
        # matchLabelKeys=[rev] must spread WITHIN each revision, not across —
        # 2+2 pods land as skew (2, 2), not (1, 1, 1, 1)
        from karpenter_tpu.kube import TopologySpreadConstraint

        sel = {"matchLabels": {"app": "web"}}
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.HOSTNAME_LABEL_KEY,
            label_selector=sel,
            match_label_keys=["rev"],
        )
        pods = [
            make_pod(cpu="1", name=f"a{i}", labels={"app": "web", "rev": "value-a"}, tsc=[tsc])
            for i in range(2)
        ]
        pods += [
            make_pod(cpu="1", name=f"b{i}", labels={"app": "web", "rev": "value-b"}, tsc=[tsc])
            for i in range(2)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        placed = [nc for nc in results.new_node_claims if nc.pods]
        # per-revision spread: each node hosts one a-pod and one b-pod, so 2
        # nodes with 2 pods each (without matchLabelKeys: 4 nodes of 1)
        assert sorted(len(nc.pods) for nc in placed) == [2, 2]
        for nc in placed:
            revs = {p.metadata.labels["rev"] for p in nc.pods}
            assert revs == {"value-a", "value-b"}

    def test_unknown_match_label_key_ignored(self):
        # topology_test.go "should ignore unknown labels specified in
        # matchLabelKeys": pods lacking the key use the plain selector
        from karpenter_tpu.kube import TopologySpreadConstraint

        sel = {"matchLabels": {"app": "web"}}
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.HOSTNAME_LABEL_KEY,
            label_selector=sel,
            match_label_keys=["missing-label"],
        )
        pods = [make_pod(cpu="1", name=f"p{i}", labels={"app": "web"}, tsc=[tsc]) for i in range(4)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        placed = [nc for nc in results.new_node_claims if nc.pods]
        assert sorted(len(nc.pods) for nc in placed) == [1, 1, 1, 1]

    def test_match_label_keys_zone_spread_tensor_path(self):
        # the keyed-domain kernel sees per-revision groups too: each revision
        # spreads over zones independently on the TPU path
        from karpenter_tpu.kube import TopologySpreadConstraint
        from karpenter_tpu.solver.encode import check_capability
        from karpenter_tpu.solver.tpu import TPUSolver
        from test_solver import make_snapshot

        sel = {"matchLabels": {"app": "web"}}
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.ZONE_LABEL_KEY,
            label_selector=sel,
            match_label_keys=["rev"],
        )
        pods = []
        for rev in ("r1", "r2"):
            pods += [
                make_pod(cpu="2", name=f"{rev}-{i}", labels={"app": "web", "rev": rev}, tsc=[tsc])
                for i in range(8)
            ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        # per-revision zone balance
        from collections import Counter

        for rev in ("r1", "r2"):
            zone_counts = Counter()
            for nc in results.new_node_claims:
                z = next(iter(nc.requirements.get(wk.ZONE_LABEL_KEY).values), None)
                n = sum(1 for p in nc.pods if p.metadata.labels.get("rev") == rev)
                if n:
                    zone_counts[z] += n
            assert max(zone_counts.values()) - min(zone_counts.values()) <= 1, (rev, zone_counts)

    def test_match_label_keys_end_to_end_binding(self):
        # the binder (kube-scheduler stand-in) must honor per-revision
        # semantics too: a second revision binds even when combined-selector
        # skew would forbid it
        from karpenter_tpu.kube import TopologySpreadConstraint
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options

        env = Environment(options=Options())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        sel = {"matchLabels": {"app": "web"}}
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.HOSTNAME_LABEL_KEY,
            label_selector=sel,
            match_label_keys=["rev"],
        )
        for rev in ("r1", "r2"):
            for i in range(2):
                env.store.create(
                    make_pod(cpu="1", name=f"{rev}-{i}", labels={"app": "web", "rev": rev}, tsc=[tsc])
                )
        env.settle(rounds=8)
        pods = env.store.list("Pod")
        assert all(p.spec.node_name for p in pods), "all revisions must bind"
        # per-revision spread: each revision's pods on distinct nodes
        for rev in ("r1", "r2"):
            nodes = {p.spec.node_name for p in pods if p.metadata.labels["rev"] == rev}
            assert len(nodes) == 2
