"""Reserved-offering reservation accounting.

Reference specs: scheduling/reservationmanager_test.go + the reserved paths
of nodeclaim.go:303-350 (offeringsToReserve) and FinalizeScheduling:394-404.
Core guarantee: two NodeClaims in ONE solve can never oversubscribe a
reservation, on either solver backend.
"""

import pytest

from helpers import make_nodepool, make_pod
from test_solver import LINUX_AMD64, make_snapshot
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.types import order_by_price
from karpenter_tpu.controllers.provisioning.scheduling.reservationmanager import ReservationManager
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements
from karpenter_tpu.solver import FFDSolver
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results


def reserved_types(reserved_capacity=1, cpu=16, zones=("test-zone-a",)):
    return [catalog.make_instance_type("c", cpu, zones=list(zones), include_reserved=True, reserved_capacity=reserved_capacity)]


def claim_capacity_types(nc):
    r = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
    return r


class TestReservationManager:
    def test_capacity_tracks_minimum_across_duplicate_ids(self):
        a = reserved_types(reserved_capacity=5)[0]
        b = reserved_types(reserved_capacity=2)[0]  # same rid, smaller capacity
        rm = ReservationManager({"p1": [a], "p2": [b]})
        o = next(o for o in a.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED)
        assert rm.remaining_capacity(o) == 2

    def test_reserve_is_idempotent_per_host(self):
        it = reserved_types(reserved_capacity=1)[0]
        o = next(o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED)
        rm = ReservationManager({"p": [it]})
        assert rm.can_reserve("h1", o)
        rm.reserve("h1", o)
        rm.reserve("h1", o)  # idempotent: no second unit consumed
        assert rm.remaining_capacity(o) == 0
        assert rm.has_reservation("h1", o)
        # capacity exhausted for other hosts, still reservable for h1
        assert not rm.can_reserve("h2", o)
        assert rm.can_reserve("h1", o)

    def test_release_returns_capacity(self):
        it = reserved_types(reserved_capacity=1)[0]
        o = next(o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED)
        rm = ReservationManager({"p": [it]})
        rm.reserve("h1", o)
        rm.release("h1", o)
        assert rm.remaining_capacity(o) == 1
        assert rm.can_reserve("h2", o)
        # releasing an unheld reservation is a no-op
        rm.release("h2", o)
        assert rm.remaining_capacity(o) == 1

    def test_over_reserve_raises(self):
        it = reserved_types(reserved_capacity=1)[0]
        o = next(o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED)
        rm = ReservationManager({"p": [it]})
        rm.reserve("h1", o)
        with pytest.raises(RuntimeError, match="over-reserve"):
            rm.reserve("h2", o)


class TestOrderByPrice:
    def test_reserved_priced_under_spot_wins(self):
        its = reserved_types(reserved_capacity=1)
        reqs = Requirements(
            Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_RESERVED, wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND])
        )
        it = its[0]
        reserved_price = next(o.price for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED)
        spot_price = next(o.price for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_SPOT)
        assert reserved_price < spot_price
        ordered = order_by_price(its, reqs)
        assert ordered[0] is it
        # excluding reserved raises the effective launch price to spot
        no_reserved = Requirements(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "NotIn", [wk.CAPACITY_TYPE_RESERVED]))
        assert min(o.price for o in it.offerings if no_reserved.intersects(o.requirements) is None) == spot_price


def two_big_pods_snapshot(types, **kw):
    # each pod fills most of a 16-cpu node: two claims result
    pods = [make_pod(cpu="12") for _ in range(2)]
    snap = make_snapshot(pods, types=types)
    for k, v in kw.items():
        setattr(snap, k, v)
    return snap


class TestSchedulerReservations:
    def test_two_claims_cannot_oversubscribe(self):
        # one reservation unit; two claims — exactly one may pin reserved
        results = FFDSolver().solve(two_big_pods_snapshot(reserved_types(reserved_capacity=1)))
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2
        pinned = [nc for nc in results.new_node_claims if claim_capacity_types(nc).values_list() == [wk.CAPACITY_TYPE_RESERVED]]
        assert len(pinned) == 1, [claim_capacity_types(nc).values_list() for nc in results.new_node_claims]
        # the reserved claim carries its reservation id requirement
        rid_req = pinned[0].requirements.get(wk.RESERVATION_ID_LABEL_KEY)
        assert rid_req.operator() == Operator.IN and rid_req.values_list() == ["r-c-16x-amd64-linux-test-zone-a"]

    def test_capacity_two_serves_two_claims(self):
        results = FFDSolver().solve(two_big_pods_snapshot(reserved_types(reserved_capacity=2)))
        assert not results.pod_errors
        pinned = [nc for nc in results.new_node_claims if claim_capacity_types(nc).values_list() == [wk.CAPACITY_TYPE_RESERVED]]
        assert len(pinned) == 2

    def test_gate_off_leaves_claims_unpinned(self):
        snap = two_big_pods_snapshot(reserved_types(reserved_capacity=1), reserved_capacity_enabled=False)
        results = FFDSolver().solve(snap)
        assert not results.pod_errors
        for nc in results.new_node_claims:
            # no reservation accounting: claims are never pinned to reserved
            assert claim_capacity_types(nc).values_list() != [wk.CAPACITY_TYPE_RESERVED]
            assert not nc.reserved_offerings
            # the API claim still narrows capacity types from offerings alone
            api = nc.to_api_node_claim()
            cts = next(r for r in api.spec.requirements if r["key"] == wk.CAPACITY_TYPE_LABEL_KEY)
            assert wk.CAPACITY_TYPE_RESERVED in cts["values"]

    def test_strict_mode_fails_pod_when_unreservable(self):
        # capacity 0: compatible reserved offerings exist, none reservable
        snap = two_big_pods_snapshot(reserved_types(reserved_capacity=0), reserved_offering_mode="strict")
        results = FFDSolver().solve(snap)
        assert len(results.pod_errors) == 2
        assert all("reserved offering" in e for e in results.pod_errors.values())

    def test_fallback_mode_schedules_without_reservation(self):
        snap = two_big_pods_snapshot(reserved_types(reserved_capacity=0))
        results = FFDSolver().solve(snap)
        assert not results.pod_errors
        for nc in results.new_node_claims:
            assert claim_capacity_types(nc).values_list() != [wk.CAPACITY_TYPE_RESERVED]


class TestTPUDecodeReservations:
    def test_decode_caps_reservations_across_claims(self):
        snap = two_big_pods_snapshot(reserved_types(reserved_capacity=1))
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2
        pinned, unpinned = [], []
        for nc in results.new_node_claims:
            r = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
            if r.operator() == Operator.IN and r.values_list() == [wk.CAPACITY_TYPE_RESERVED]:
                pinned.append(nc)
            else:
                unpinned.append(nc)
        assert len(pinned) == 1 and len(unpinned) == 1
        # the unpinned claim can no longer land on reserved capacity
        assert not unpinned[0].requirements.get(wk.CAPACITY_TYPE_LABEL_KEY).has(wk.CAPACITY_TYPE_RESERVED)
        assert not validate_results(two_big_pods_snapshot(reserved_types(reserved_capacity=1)), results)

    def test_strict_mode_falls_back_to_ffd(self):
        snap = two_big_pods_snapshot(reserved_types(reserved_capacity=1), reserved_offering_mode="strict")
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        assert "strict reserved-offering" in " ".join(solver.last_fallback_reasons)

    def test_tpu_and_ffd_agree_on_reserved_outcome(self):
        tpu = TPUSolver(force=True)
        r_tpu = tpu.solve(two_big_pods_snapshot(reserved_types(reserved_capacity=1)))
        r_ffd = FFDSolver().solve(two_big_pods_snapshot(reserved_types(reserved_capacity=1)))

        def reserved_count(results):
            n = 0
            for nc in results.new_node_claims:
                r = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
                if r.operator() == Operator.IN and r.values_list() == [wk.CAPACITY_TYPE_RESERVED]:
                    n += 1
            return n

        assert reserved_count(r_tpu) == reserved_count(r_ffd) == 1


class TestKWOKLaunchEnforcement:
    def test_launch_skips_exhausted_reservations(self):
        # launch-side guard (real providers enforce in their fleet APIs): even
        # an unpinned claim must not launch into a consumed reservation
        from karpenter_tpu.apis.kwoknodeclass import KWOKNodeClass
        from karpenter_tpu.apis.nodeclaim import NodeClaim, NodeClassReference
        from karpenter_tpu.cloudprovider.kwok import KWOKCloudProvider
        from karpenter_tpu.kube import ObjectMeta, Store

        store = Store()
        store.create(KWOKNodeClass())
        its = reserved_types(reserved_capacity=1)
        cp = KWOKCloudProvider(store, its)

        def claim(i):
            nc = NodeClaim(metadata=ObjectMeta(name=f"nc-{i}"))
            nc.spec.node_class_ref = NodeClassReference(name="default")
            nc.spec.requirements = [
                {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": [its[0].name]},
            ]
            return nc

        first = cp.create(claim(0))
        second = cp.create(claim(1))
        assert first.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == wk.CAPACITY_TYPE_RESERVED
        # reservation consumed: the second node falls to the next-cheapest
        # (spot) offering instead of oversubscribing
        assert second.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == wk.CAPACITY_TYPE_SPOT
        assert wk.RESERVATION_ID_LABEL_KEY not in second.metadata.labels


class TestReservedOfferingDepth:
    """suite_test.go reserved-offering provisioning behaviors :4713-:5195."""

    def _snap(self, pods, types, node_pools=None, **kw):
        snap = make_snapshot(pods, types=types, node_pools=node_pools)
        snap.reserved_capacity_enabled = True
        for k, v in kw.items():
            setattr(snap, k, v)
        return snap

    def test_no_fallback_while_reserved_capacity_remains(self):
        # :4713 "shouldn't fallback to on-demand or spot when compatible
        # reserved offerings are available" — claims within reservation
        # capacity pin to reserved; the overflow claim is EXCLUDED from
        # reserved, falling to spot/on-demand
        types = reserved_types(reserved_capacity=2)
        pods = [make_pod(cpu="12") for _ in range(3)]
        snap = self._snap(pods, types)
        results = FFDSolver().solve(snap)
        assert results.all_pods_scheduled()
        kinds = []
        for nc in results.new_node_claims:
            r = claim_capacity_types(nc)
            kinds.append(tuple(sorted(r.values)) if r.operator() == Operator.IN else ("non-reserved",))
        reserved_claims = [k for k in kinds if k == (wk.CAPACITY_TYPE_RESERVED,)]
        assert len(reserved_claims) == 2, kinds

    def test_higher_weight_pool_with_reservation_not_abandoned(self):
        # :4974 "shouldn't fallback to a lower weight NodePool if a reserved
        # offering is available" — the heavy pool's reserved offering wins
        # even though the light pool could also host the pod
        heavy = make_nodepool(name="np-primary", requirements=LINUX_AMD64, weight=100)
        light = make_nodepool(name="np-fallback", requirements=LINUX_AMD64, weight=50)
        types = reserved_types(reserved_capacity=1)
        pod = make_pod(cpu="12")
        snap = self._snap([pod], types, node_pools=[heavy, light])
        results = FFDSolver().solve(snap)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        pool_req = nc.requirements.get(wk.NODEPOOL_LABEL_KEY)
        assert pool_req is not None and set(pool_req.values) == {"np-primary"}
        r = claim_capacity_types(nc)
        assert r.operator() == Operator.IN and set(r.values) == {wk.CAPACITY_TYPE_RESERVED}

    def test_multiple_pods_share_reserved_node(self):
        # :5140 "should handle multiple pods on reserved nodes" — two small
        # co-locating pods consume ONE reservation unit, not two
        types = reserved_types(reserved_capacity=1)
        pods = [make_pod(cpu="4") for _ in range(2)]
        snap = self._snap(pods, types)
        results = FFDSolver().solve(snap)
        assert results.all_pods_scheduled()
        claims = [nc for nc in results.new_node_claims if nc.pods]
        assert len(claims) == 1 and len(claims[0].pods) == 2
        r = claim_capacity_types(claims[0])
        assert set(r.values) == {wk.CAPACITY_TYPE_RESERVED}
