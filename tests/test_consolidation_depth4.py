"""Consolidation depth, batch 4: method priority order, candidate filtering
(nominated/terminating/unowned/orphaned nodes), and the same-instance-type
churn guard — ported from consolidation_test.go + controller.go families."""

from helpers import hostname_anti_affinity, make_nodepool, make_pod
from test_disruption import OD_ONLY, make_env, provision, run_disruption
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget


def one_pod_per_node(env, n, cpu="500m", labels=None, prefix="s"):
    sel = {"matchLabels": {"app": "x"}}
    base = {"app": "x"}
    if labels:
        base.update(labels)
    pods = [
        make_pod(cpu=cpu, name=f"{prefix}{i}", labels=dict(base), anti_affinity=[hostname_anti_affinity(sel)])
        for i in range(n)
    ]
    provision(env, pods)
    return pods


class TestMethodPriority:
    def test_emptiness_deletes_before_consolidation_replaces(self):
        # controller.go:101-115 — methods run in priority order and the first
        # method producing commands wins the round: empty nodes delete
        # without any scheduling simulation before consolidation is tried
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        np = env.store.list("NodePool")[0]

        def full_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="100%")]

        env.store.patch("NodePool", np.metadata.name, full_budget)
        pods = one_pod_per_node(env, 4)
        n0 = env.store.count("Node")
        assert n0 == 4
        # empty two nodes; keep two underutilized
        env.store.delete("Pod", "s0")
        env.store.delete("Pod", "s1")
        env.clock.step(40)
        env.tick(provision_force=True)
        env.disruption.reconcile(force=True)
        # the first round's commands are emptiness deletes (no replacements)
        deleting = [
            sn for sn in env.cluster.nodes() if sn.marked_for_deletion
        ]
        assert len(deleting) >= 1
        assert env.store.count("NodeClaim") == 4, "emptiness never creates replacements"

    def test_drift_has_priority_over_consolidation(self):
        # a drifted underutilized node is handled by Drift (1:1 replace), not
        # merged by consolidation, because Drift runs first
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        one_pod_per_node(env, 2)
        np = env.store.list("NodePool")[0]

        def relabel(p):
            p.spec.template.labels["roll"] = "v2"

        env.store.patch("NodePool", np.metadata.name, relabel)
        env.clock.step(40)
        env.tick(provision_force=True)
        env.nodeclaim_disruption.reconcile()
        drifted = [
            nc for nc in env.store.list("NodeClaim")
            if nc.status.conditions.is_true("Drifted")
        ]
        assert drifted, "hash change must mark claims drifted"
        env.disruption.reconcile(force=True)
        env.settle(rounds=25)
        # the roll replaced nodes 1:1 with the new template label
        for nc in env.store.list("NodeClaim"):
            assert nc.metadata.labels.get("roll") == "v2"
        assert env.store.count("Pod") == 2


class TestCandidateFiltering:
    def test_nominated_node_not_a_candidate(self):
        # a node nominated for incoming pods is protected from disruption
        # (statenode.go Nominated / ValidateNodeDisruptable)
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        one_pod_per_node(env, 2)
        for sn in env.cluster.nodes():
            env.cluster.nominate_node(sn.name())
        cands = env.disruption.get_candidates()
        assert cands == []

    def test_marked_for_deletion_node_not_a_candidate(self):
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        one_pod_per_node(env, 2)
        sns = env.cluster.nodes()
        env.cluster.mark_for_deletion([sns[0].provider_id()])
        cands = env.disruption.get_candidates()
        assert len(cands) == 1

    def test_node_without_nodepool_label_not_a_candidate(self):
        # bring-your-own nodes are never voluntarily disrupted
        # (candidate build requires the nodepool label, types.go:160-211)
        from helpers import parse_resource_list
        from karpenter_tpu.kube.objects import Node, NodeSpec, NodeStatus, ObjectMeta

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        env.store.create(
            Node(
                metadata=ObjectMeta(name="byo", labels={wk.HOSTNAME_LABEL_KEY: "byo"}),
                spec=NodeSpec(provider_id="byo://x"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                ),
            )
        )
        env.settle(rounds=3)
        cands = env.disruption.get_candidates()
        assert all(c.state_node.name() != "byo" for c in cands)

    def test_orphaned_pool_node_not_a_candidate(self):
        # candidate build needs the owning NodePool object; nodes of a
        # deleted pool are left alone (helpers.go candidate filtering)
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        one_pod_per_node(env, 2)
        np = env.store.list("NodePool")[0]
        env.store.delete("NodePool", np.metadata.name)
        env.settle(rounds=2)
        cands = env.disruption.get_candidates()
        assert cands == []


class TestSameTypeChurnGuard:
    def test_wont_replace_fleet_with_type_already_present(self):
        # multinodeconsolidation.go filterOutSameInstanceType scenario:
        # merging a fleet whose replacement would be the same instance type as
        # a member is churn, not savings — the command must be rejected or
        # choose a strictly cheaper type
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        np = env.store.list("NodePool")[0]

        def full_budget(p):
            p.spec.disruption.budgets = [Budget(nodes="100%")]
            p.spec.disruption.consolidate_after = "30s"

        env.store.patch("NodePool", np.metadata.name, full_budget)
        one_pod_per_node(env, 3, cpu="400m")
        # release the anti-affinity so pods can co-locate
        for i in range(3):
            env.store.delete("Pod", f"s{i}")
        for i in range(3):
            env.store.create(make_pod(cpu="400m", name=f"f{i}"))
        env.settle(rounds=4)
        prices_before = sorted(
            c.price for c in (env.disruption.get_candidates() or [])
        )
        run_disruption(env, rounds=25)
        # fleet consolidated: strictly fewer nodes, pods intact
        assert env.store.count("Node") < 3
        assert env.store.count("Pod") == 3
        assert prices_before, "setup: candidates existed pre-consolidation"
        # anti-churn: the consolidated state is STABLE — further rounds never
        # replace the survivor with a same-priced node (pointless churn guard,
        # multinodeconsolidation.go:150-170)
        survivors = {n.metadata.name for n in env.store.list("Node")}
        run_disruption(env, rounds=12)
        assert {n.metadata.name for n in env.store.list("Node")} == survivors
