"""faultline (ISSUE 15): fault injection, failure-domain isolation, and the
graceful-degradation ladder.

Pins the subsystem's contracts:
- circuit breaker: K consecutive failures quarantine ONE tenant, exponential
  -backoff half-open probes re-admit it, and the state machine is observable
  (tenant_state gauge, transitions counter, /debug/tenants surface);
- degradation ladder: a solve that RAISES retries as a quarantined full
  re-encode (a poisoned delta base never serves a second solve), then the
  exact host FFD; each step is attributed (SolveTrace + recovery_total), the
  answer matches a clean solver's, and the delta path re-warms afterward;
- fault injection: a seeded FaultSpec fires deterministically at the named
  seams; watch drop/dup/reorder leave placements bit-identical (the store
  is authoritative; the stream is at-least-once and unordered);
- prestager supervision: a worker death (injected SystemExit) is detected,
  counted, and healed by restart instead of silently degrading;
- overload protection: a tenant past its backlog cap sheds its own window
  (bounded by the oldest-event-age watchdog) — never the fleet's;
- chaos soak: a randomized seeded FaultSpec under run_concurrent with the
  racecheck sanitizer ON — zero loop deaths, healthy-tenant placements
  bit-identical to a no-fault fleet run, delta-hit recovery after
  quarantine.
"""

from __future__ import annotations

import time

import pytest

from test_churn_loop import placement_shape, small_spec
from test_fleet import add_churn_tenant
from test_solver import make_snapshot
from helpers import make_pod
from karpenter_tpu import metrics as m
from karpenter_tpu.metrics import make_registry
from karpenter_tpu.serving import ChurnHarness, ChurnSpec
from karpenter_tpu.serving.faults import (
    FAULT_SEAMS,
    TENANT_STATES,
    CircuitBreaker,
    FaultInjected,
    FaultInjector,
    FaultRule,
    FaultSpec,
)
from karpenter_tpu.serving.fleet import FleetFrontend, fleet_debug_surfaces, reset_tenant_labels
from karpenter_tpu.solver.tpu import RECOVERY_STAGES, TPUSolver


@pytest.fixture(autouse=True)
def _fresh_labels():
    reset_tenant_labels()
    yield
    reset_tenant_labels()


def claim_shape(results) -> set:
    """Placement identity for solver-level parity: pods grouped per claim."""
    return {frozenset(p.metadata.name for p in nc.pods) for nc in results.new_node_claims}


class TestCircuitBreaker:
    def test_opens_after_k_failures_then_probe_readmits(self):
        t = [0.0]
        b = CircuitBreaker(failures_to_open=3, backoff_seconds=1.0, backoff_max=8.0, now_fn=lambda: t[0])
        assert b.allow() and b.state_name() == "healthy"
        assert b.record_failure(RuntimeError("a")) is None
        assert b.record_failure(RuntimeError("b")) is None
        assert b.allow(), "under K failures the tenant still dispatches"
        assert b.record_failure(RuntimeError("c")) == "quarantined"
        assert not b.allow(), "quarantined + backoff pending: no dispatch"
        t[0] = 1.0
        assert b.allow(), "backoff elapsed: one half-open probe admitted"
        assert b.state_name() == "probing"
        assert not b.allow(), "only ONE probe per window"
        assert b.record_success() is True
        assert b.state_name() == "healthy"
        assert b.snapshot()["backoff_seconds"] == 1.0, "success resets the backoff"

    def test_probe_failure_doubles_backoff_capped(self):
        t = [0.0]
        b = CircuitBreaker(failures_to_open=1, backoff_seconds=1.0, backoff_max=3.0, now_fn=lambda: t[0])
        assert b.record_failure("x") == "quarantined"
        backoffs = []
        for _ in range(4):
            t[0] += b.remaining_backoff() + 1e-9
            assert b.allow()
            assert b.record_failure("probe failed") == "quarantined"
            backoffs.append(b.snapshot()["backoff_seconds"])
        assert backoffs == [2.0, 3.0, 3.0, 3.0], "exponential, capped"
        assert b.snapshot()["opens"] == 5

    def test_probe_inconclusive_requarantines_without_doubling(self):
        t = [0.0]
        b = CircuitBreaker(failures_to_open=1, backoff_seconds=1.0, now_fn=lambda: t[0])
        b.record_failure("x")
        t[0] = 1.0
        assert b.allow() and b.state_name() == "probing"
        b.probe_inconclusive()
        assert b.state_name() == "quarantined"
        assert b.snapshot()["backoff_seconds"] == 1.0
        t[0] = 2.0
        assert b.allow(), "the NEXT window probes again"


class TestFaultSpec:
    def test_rule_schedule_semantics(self):
        r = FaultRule("watch-drop", at=3, every=4, count=2)
        fired = 0
        hits = []
        for i in range(20):
            if r.due(i, fired):
                fired += 1
                hits.append(i)
        assert hits == [3, 7], "at + every, bounded by count"
        one_shot = FaultRule("watch-drop", at=5)
        assert [i for i in range(10) if one_shot.due(i, 0)] == [5]

    def test_unknown_seam_rejected(self):
        with pytest.raises(ValueError, match="unknown fault seam"):
            FaultRule("not-a-seam")

    def test_roundtrip_and_randomized(self):
        spec = FaultSpec.randomized(seed=5)
        assert FaultSpec.from_dict(spec.to_dict()) == spec
        assert FaultSpec.randomized(seed=5) == spec, "seeded: reproducible"
        assert {r.seam for r in spec.rules} <= set(FAULT_SEAMS)

    def test_reorder_swaps_at_unit_level(self):
        class _O:
            kind = "Pod"

        a, b = _O(), _O()
        fi = FaultInjector(FaultSpec(rules=(FaultRule("watch-reorder", at=0),)))
        assert fi.on_watch_event("ADDED", a, 1.0) == []
        out = fi.on_watch_event("ADDED", b, 2.0)
        assert [o[1] for o in out] == [b, a], "successor delivered first, deferred after"
        assert fi.take_deferred() is None


class TestRecoveryLadder:
    def _solver_with_faults(self, rules, registry=None):
        registry = registry or make_registry()
        solver = TPUSolver(registry=registry)
        fi = FaultInjector(FaultSpec(rules=tuple(rules)), registry=registry)
        solver.fault_hook = fi.solver_hook
        return solver, fi, registry

    def test_solve_exception_recovers_via_full_reencode(self):
        pods = [make_pod(cpu="1", name=f"r-{i}") for i in range(8)]
        clean = TPUSolver().solve(make_snapshot(pods))
        solver, fi, registry = self._solver_with_faults([FaultRule("solve-exception", at=0, ladder=1)])
        results = solver.solve(make_snapshot(pods))
        assert claim_shape(results) == claim_shape(clean), "recovered answer matches a clean solver's"
        assert solver.last_backend == "tpu"
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="full-reencode") == 1
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="host-ffd") == 0
        tr = solver.recorder.last()
        assert tr.attribution.get("recovery") == "full-reencode"
        assert "FaultInjected" in tr.attribution.get("recovery_error", "")
        assert fi.summary() == {"solve-exception": 1}

    def test_double_fault_degrades_to_host_ffd(self):
        pods = [make_pod(cpu="1", name=f"h-{i}") for i in range(8)]
        solver, fi, registry = self._solver_with_faults([FaultRule("decode-failure", at=0, ladder=2)])
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert not results.pod_errors
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="full-reencode") == 1
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="host-ffd") == 1
        assert solver.recorder.last().attribution.get("recovery") == "host-ffd"
        assert tuple(RECOVERY_STAGES) == ("full-reencode", "host-ffd")

    def test_unrecoverable_fault_escapes_the_ladder(self):
        solver, fi, _ = self._solver_with_faults([FaultRule("solve-exception", at=0, ladder=0)])
        with pytest.raises(FaultInjected):
            solver.solve(make_snapshot([make_pod(cpu="1")]))

    def test_force_mode_still_raises(self):
        registry = make_registry()
        solver = TPUSolver(force=True, registry=registry)
        fi = FaultInjector(FaultSpec(rules=(FaultRule("solve-exception", at=0),)))
        solver.fault_hook = fi.solver_hook
        with pytest.raises(FaultInjected):
            solver.solve(make_snapshot([make_pod(cpu="1")]))

    def test_poisoned_carry_never_serves_again_and_delta_rewarns(self):
        # warm a delta base, fault the next solve, and pin: the recovery
        # quarantined every cache (the poisoned base cannot serve again),
        # and the solve AFTER the recovery classifies as delta off the
        # RECOVERED encode — the re-warm contract
        pods = [make_pod(cpu="500m", name=f"w-{i}") for i in range(12)]
        solver, fi, registry = self._solver_with_faults([FaultRule("solve-exception", at=1, ladder=1)])
        snap = make_snapshot(list(pods))
        solver.solve(snap)  # warm: full, establishes carry + delta base
        base_cache = solver.encode_cache
        assert solver.last_solve_mode == "full"
        snap.pods.append(make_pod(cpu="500m", name="w-extra"))
        solver.solve(snap)  # the fault fires here -> ladder recovery
        assert solver.encode_cache is not base_cache, "quarantine replaced the EncodeCache"
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="full-reencode") == 1
        snap.pods.append(make_pod(cpu="500m", name="w-extra2"))
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta", "delta path re-warmed after recovery"
        assert not results.pod_errors

    def test_slow_solve_injects_latency_only(self):
        pods = [make_pod(cpu="1", name=f"s-{i}") for i in range(4)]
        solver, fi, registry = self._solver_with_faults([FaultRule("slow-solve", at=0, arg=0.05)])
        t0 = time.perf_counter()
        solver.solve(make_snapshot(pods))
        assert time.perf_counter() - t0 >= 0.05
        assert registry.counter(m.SOLVER_RECOVERY_TOTAL).total() == 0
        assert fi.summary() == {"slow-solve": 1}


class TestPrestagerSupervision:
    def test_worker_death_detected_counted_restarted(self):
        from karpenter_tpu.kube import Store
        from karpenter_tpu.serving.prestage import PendingPrestager

        registry = make_registry()
        p = PendingPrestager()
        p.metrics = registry
        p.attach(Store())
        fi = FaultInjector(FaultSpec(rules=(FaultRule("prestage-death", at=1),)), registry=registry)
        p.fault_hook = fi.prestage_hook
        p.start()
        deadline = time.time() + 5
        while p.worker_alive() and time.time() < deadline:
            time.sleep(0.01)
        assert not p.worker_alive(), "injected SystemExit killed the worker"
        assert p.worker_running(), "the DEAD thread still holds the handle — the silent-death state"
        assert p.ensure_worker() is True
        assert p.worker_alive()
        assert p.restarts == 1
        assert registry.counter(m.SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL).total() == 1
        assert p.ensure_worker() is False, "a live worker is not restarted"
        p.stop()
        assert p.ensure_worker() is False, "a stopped prestager stays stopped"

    def test_serving_loop_supervises_on_pump(self):
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.serving.loop import ServingLoop

        env = Environment(options=Options(solver_backend="tpu"))
        loop = ServingLoop(env.provisioner, env.store, double_buffer=True, worker=True)
        try:
            fi = FaultInjector(FaultSpec(rules=(FaultRule("prestage-death", at=0),)), registry=env.registry)
            loop.prestager.fault_hook = fi.prestage_hook
            deadline = time.time() + 5
            while loop.prestager.worker_alive() and time.time() < deadline:
                time.sleep(0.01)
            assert not loop.prestager.worker_alive()
            loop.pump()
            assert loop.prestager.worker_alive(), "pump's supervisor restarted the worker"
            assert env.registry.counter(m.SOLVER_PRESTAGE_WORKER_RESTARTS_TOTAL).total() >= 1
        finally:
            loop.close()


def tiny_spec(**kw) -> ChurnSpec:
    base = dict(
        n_base_pods=100,
        n_types=10,
        arrivals=24,
        cancels=18,
        departures=24,
        bind_every=2,
        iterations=4,
        warmup_cycles=1,
        concurrent_seconds=0.0,
    )
    base.update(kw)
    return ChurnSpec(**base)


class TestWatchStreamFaults:
    def test_drop_dup_reorder_placements_bit_identical(self):
        """The store CONTENT is authoritative: a lossy, at-least-once,
        unordered watch stream must not change placements."""
        shapes = []
        for faults in (
            None,
            FaultSpec(
                rules=(
                    FaultRule("watch-drop", at=20, every=23, count=5),
                    FaultRule("watch-dup", at=11, every=17, count=5),
                    FaultRule("watch-reorder", at=5, every=29, count=4),
                ),
                seed=3,
            ),
        ):
            h = ChurnHarness(tiny_spec(faults=faults))
            try:
                h.run()
                # settle: a dropped trigger may leave a window un-fired
                for _ in range(3):
                    h.solve(force=True)
                    h.bind_flush()
                shapes.append(placement_shape(h.env))
            finally:
                h.close()
        assert shapes[0] == shapes[1], "watch faults changed placements"

    def test_store_level_drop_and_dup_counts(self):
        from karpenter_tpu.kube import Store
        from karpenter_tpu.kube.objects import ObjectMeta, Pod, PodSpec

        registry = make_registry()
        fi = FaultInjector(
            FaultSpec(rules=(FaultRule("watch-drop", at=1), FaultRule("watch-dup", at=3))),
            registry=registry,
        )
        store = Store()
        seen: list[str] = []
        store.watch("Pod", lambda e, p: seen.append(p.metadata.name))  # solverlint: ok(thread-escape): single-threaded test callback appending to a local list
        store.set_fault_injector(fi)
        for i in range(5):
            store.create(Pod(metadata=ObjectMeta(name=f"p{i}", namespace="default", uid=f"u{i}"), spec=PodSpec()))
        # event 1 dropped, event 3 duplicated
        assert seen == ["p0", "p2", "p3", "p3", "p4"]
        c = registry.counter(m.SOLVER_FAULT_INJECTIONS_TOTAL)
        assert c.value(seam="watch-drop") == 1 and c.value(seam="watch-dup") == 1
        # the store's gap tracker publishes exactly the DROP as loss (the
        # dup self-heals): this is the level-trigger Provisioner.reconcile
        # polls to re-converge the Cluster mirror from store content
        assert store.watch_loss_epoch("Pod") == 1

    def test_loss_epoch_only_counts_drops(self):
        """Dup and reorder are at-least-once/unordered noise the stream
        contract absorbs; only a drop — an event that NEVER arrives — may
        bump the loss epoch and trigger a resync."""
        from karpenter_tpu.kube import Store
        from karpenter_tpu.kube.objects import ObjectMeta, Pod, PodSpec

        for rule, lost in (
            (FaultRule("watch-dup", at=1, every=2, count=3), 0),
            (FaultRule("watch-reorder", at=1, every=3, count=2), 0),
            (FaultRule("watch-drop", at=1, every=3, count=2), 2),
        ):
            store = Store()
            store.set_fault_injector(FaultInjector(FaultSpec(rules=(rule,))))
            for i in range(8):
                store.create(Pod(metadata=ObjectMeta(name=f"p{i}", namespace="default", uid=f"u{i}"), spec=PodSpec()))
            assert store.watch_loss_epoch("Pod") == lost, rule.seam

    def test_resync_converges_cluster_after_drop(self):
        """A dropped bind echo leaves the Cluster mirror stale; the next
        reconcile's level-triggered resync re-derives it from store content
        — and with nothing lost, resync_pods mutates nothing."""
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options

        env = Environment(options=Options(solver_backend="host"))
        # no drift: a resync is a pure read (no generation bump)
        gen0 = env.cluster.generation
        assert env.cluster.resync_pods() == (0, 0)
        assert env.cluster.generation == gen0
        # drop the NEXT Pod event (a create), then converge
        env.store.set_fault_injector(FaultInjector(FaultSpec(rules=(FaultRule("watch-drop", at=0),)), registry=env.registry))
        from helpers import make_pod

        env.store.create(make_pod("lost-pod"))
        key = "default/lost-pod"
        with env.cluster._lock:
            assert key not in env.cluster._pod_rvs, "event was dropped"
        # even a TAIL drop (no successor seq behind it) is caught at
        # queue-quiet: the drain compares its watermark against the
        # committed per-kind seq, so a lost final event can't hide
        assert env.store.watch_loss_epoch("Pod") == 1
        assert env.cluster.resync_pods() == (1, 0)
        with env.cluster._lock:
            assert key in env.cluster._pod_rvs, "resync converged on store content"
        # with the injector cleared the DELETED is delivered normally, so
        # the mirror tracks it at once and resync has nothing to repair
        env.store.set_fault_injector(None)
        env.store.try_delete("Pod", "lost-pod", namespace="default")
        assert env.cluster.resync_pods() == (0, 0)

    def test_reorder_at_tail_is_flushed_never_lost(self):
        from karpenter_tpu.kube import Store
        from karpenter_tpu.kube.objects import ObjectMeta, Pod, PodSpec

        fi = FaultInjector(FaultSpec(rules=(FaultRule("watch-reorder", at=2),)))
        store = Store()
        seen: list[str] = []
        store.watch("Pod", lambda e, p: seen.append(p.metadata.name))  # solverlint: ok(thread-escape): single-threaded test callback appending to a local list
        store.set_fault_injector(fi)
        for i in range(3):
            store.create(Pod(metadata=ObjectMeta(name=f"p{i}", namespace="default", uid=f"u{i}"), spec=PodSpec()))
        assert seen == ["p0", "p1", "p2"], "tail reorder flushed at queue-empty"
        assert fi.take_deferred() is None


class TestFleetFailureDomains:
    def test_quarantine_isolates_and_probe_readmits(self):
        """Tenant b's solver hard-fails (unrecoverable); the fleet loop never
        dies, tenant a keeps serving, b quarantines after K failures, and a
        backoff probe re-admits b once the fault plan is exhausted."""
        fleet = FleetFrontend(breaker_failures=2, breaker_backoff_seconds=1.0)
        try:
            sa = tiny_spec()
            sb = tiny_spec(faults=FaultSpec(rules=(FaultRule("solve-exception", at=0, every=1, count=2, ladder=0),)))
            ha = add_churn_tenant(fleet, "good", sa)
            hb = add_churn_tenant(fleet, "bad", sb)
            ha.provision_base_fleet()
            # drive tenant b: its first K=2 solves raise unrecoverably
            for _ in range(2):
                hb.apply_arrivals(8)
                hb.env.clock.step(sb.batch_idle_seconds + 0.05)
                fleet.pump(only="bad")  # contained: never raises
            surf = fleet.debug_tenants()
            assert surf["bad"]["state"] == "quarantined", surf["bad"]
            assert surf["bad"]["consecutive_failures"] >= 2
            assert "FaultInjected" in surf["bad"]["last_error"]
            assert surf["good"]["state"] == "healthy"
            assert fleet_debug_surfaces()["bad"]["state"] == "quarantined"
            # the state gauge + transition counter carry the bounded enum
            g = fleet.registry.gauge(m.SOLVER_TENANT_STATE)
            assert g.value(tenant="bad", state="quarantined") == 1.0
            assert g.value(tenant="bad", state="healthy") == 0.0
            assert fleet.registry.counter(m.SOLVER_BREAKER_TRANSITIONS_TOTAL).value(tenant="bad", state="quarantined") >= 1
            assert set(TENANT_STATES) == {"healthy", "quarantined", "probing"}
            # tenant a is UNAFFECTED: its pump still serves
            ha.apply_arrivals(8)
            ha.env.clock.step(sa.batch_idle_seconds + 0.05)
            assert fleet.pump(only="good"), "healthy tenant starved by b's quarantine"
            # quarantined: b's window is ready but nothing dispatches
            hb.apply_arrivals(4)
            hb.env.clock.step(sb.batch_idle_seconds + 0.05)
            assert fleet.pump(only="bad") == {}
            # fault plan exhausted (count=2): advance past the backoff and
            # the half-open probe re-admits b
            hb.env.clock.step(1.1)
            served = fleet.pump(only="bad")
            assert served.get("bad", 0) >= 1, "probe did not re-admit"
            assert fleet.debug_tenants()["bad"]["state"] == "healthy"
            assert fleet.registry.counter(m.SOLVER_BREAKER_TRANSITIONS_TOTAL).value(tenant="bad", state="healthy") == 1
        finally:
            fleet.close()

    def test_ladder_absorbs_recoverable_fault_without_tripping_breaker(self):
        fleet = FleetFrontend(breaker_failures=1)
        try:
            sb = tiny_spec(faults=FaultSpec(rules=(FaultRule("solve-exception", at=1, ladder=1),)))
            hb = add_churn_tenant(fleet, "t", sb)
            hb.provision_base_fleet()
            assert fleet.debug_tenants()["t"]["state"] == "healthy", "ladder-recovered fault must not count as a pump failure"
            assert fleet.registry.counter(m.SOLVER_RECOVERY_TOTAL).value(stage="full-reencode") >= 1
        finally:
            fleet.close()

    def test_overload_shed_and_watchdog(self):
        fleet = FleetFrontend(watchdog_age_seconds=3600.0)
        try:
            s = tiny_spec()
            h = add_churn_tenant(fleet, "hot", s)
            h.provision_base_fleet()
            fleet.overload_backlog_cap = 5
            sess = fleet.session("hot")
            # flood: way past the backlog cap, then a ready window
            for i in range(40):
                sess.env.provisioner.trigger(f"flood-{i}")
            sess.env.clock.step(s.batch_idle_seconds + 0.05)
            assert sess.pending() > 5
            served = fleet.pump(only="hot")
            assert served == {}, "overloaded tenant must be shed, not served"
            assert sess.pending() == 0, "shed drops the batch generation"
            assert fleet.registry.counter(m.SOLVER_FLEET_SHED_TOTAL).value(tenant="hot") >= 40
            # watchdog bound: with age 0 the next flood is force-served
            fleet.watchdog_age = 0.0
            for i in range(40):
                sess.env.provisioner.trigger(f"flood2-{i}")
            sess.env.clock.step(s.batch_idle_seconds + 0.05)
            served = fleet.pump(only="hot")
            assert served.get("hot", 0) >= 1, "watchdog must bound shedding"
            assert fleet.registry.counter(m.SOLVER_FLEET_WATCHDOG_TOTAL).value(tenant="hot") >= 1
        finally:
            fleet.close()

    def test_pump_contains_arbitrary_loop_exceptions(self):
        fleet = FleetFrontend(breaker_failures=1)
        try:
            h = add_churn_tenant(fleet, "t", tiny_spec())
            sess = fleet.session("t")

            def boom(force=False):
                raise RuntimeError("not a solver failure at all")

            sess.loop.pump = boom
            sess.env.provisioner.trigger("x")
            sess.env.clock.step(1.0)
            assert fleet.pump(only="t") == {}, "exception contained at the dispatch seam"
            assert fleet.debug_tenants()["t"]["state"] == "quarantined"
        finally:
            fleet.close()


class TestRecordReplayWithFaults:
    def test_fault_plan_rides_the_log_and_replays(self, tmp_path):
        path = str(tmp_path / "chaos.jsonl")
        faults = FaultSpec(
            rules=(
                FaultRule("solve-exception", at=6, ladder=1),
                FaultRule("watch-dup", at=30, every=31, count=3),
                FaultRule("revocation", at=2, count=1, arg=1),
            ),
            seed=11,
        )
        h = ChurnHarness(tiny_spec(faults=faults, record_path=path))
        try:
            rep = h.run()
            for _ in range(3):
                h.solve(force=True)
                h.bind_flush()
            shape_recorded = placement_shape(h.env)
        finally:
            h.close()
        assert rep.revoked_nodes == 1
        assert rep.faults_injected.get("revocation") == 1
        rspec = ChurnSpec.from_event_log(path)
        assert rspec.faults is not None and rspec.faults == faults, "plan rides the header"
        h2 = ChurnHarness(rspec)
        try:
            h2.run()
            for _ in range(3):
                h2.solve(force=True)
                h2.bind_flush()
            assert placement_shape(h2.env) == shape_recorded, "faulted replay diverged"
            # revocations came from the LOGGED revoke ops, not the plan
            assert h2.injector is not None
            assert h2.injector.summary().get("revocation", 0) == 0
        finally:
            h2.close()


class TestChaosSoak:
    def test_randomized_faultspec_chaos_soak(self):
        """The acceptance matrix (tier-1 scale): a 4-tenant fleet under the
        racecheck sanitizer (suite-wide), one tenant under a randomized
        seeded FaultSpec covering every seam (solve exception, decode
        failure, watch drop/dup/reorder, prestager death, revocation) plus
        an unrecoverable burst that quarantines it; asserts zero fleet-loop
        deaths, healthy-tenant placements bit-identical to a no-fault fleet
        run, and post-quarantine delta-hit recovery."""
        from karpenter_tpu.models.scheduler_model import reset_bucket_highwater

        healthy_ids = ["t0", "t1", "t2"]

        def run_fleet(victim_faults):
            reset_tenant_labels()
            fleet = FleetFrontend(breaker_failures=2, breaker_backoff_seconds=0.5)
            try:
                harnesses = {tid: add_churn_tenant(fleet, tid, tiny_spec()) for tid in healthy_ids}
                # the victim runs a LIVE prestager worker so the injected
                # prestage-death kills (and the supervisor heals) a real
                # thread under the sanitizer
                harnesses["victim"] = add_churn_tenant(fleet, "victim", tiny_spec(faults=victim_faults, worker=True))
                for h in harnesses.values():
                    h.provision_base_fleet()
                for _cycle in range(6):
                    for h in harnesses.values():
                        h.apply_arrivals(h.spec.arrivals)
                        h.apply_cancels(h.spec.cancels)
                        h.env.clock.step(h.spec.batch_idle_seconds + 0.05)
                    fleet.rearm_ready()
                    fleet.pump()  # must never raise — zero loop deaths
                    for h in harnesses.values():
                        h.apply_departures(h.spec.departures)
                        if h.injector is not None:
                            h.apply_revocations(h.injector.take_revocations())
                        h.bind_flush()
                # settle (forced; quarantine may have deferred victim work)
                for _ in range(8):
                    for h in harnesses.values():
                        h.env.clock.step(1.0)
                    fleet.pump(force=True)
                    for h in harnesses.values():
                        h.bind_flush()
                # post-quarantine delta-hit recovery: within a few arrival
                # batches the re-admitted victim must serve as a delta again.
                # The victim's solve counter stalled while quarantined, so
                # residual solver faults from the plan (each bounded by its
                # rule count, each absorbed by the ladder as an attributed
                # full re-encode) may still fire here before the plan
                # exhausts — the loop bound covers the worst-case residue
                # plus the one legitimate full re-encode for settle churn.
                hv = harnesses["victim"]
                victim_mode = ""
                for _ in range(6):
                    hv.apply_arrivals(4)
                    hv.env.clock.step(hv.spec.batch_idle_seconds + 0.05)
                    fleet.pump(only="victim")
                    victim_mode = hv.env.provisioner.solver.last_solve_mode
                    if victim_mode == "delta":
                        break
                shapes = {tid: placement_shape(harnesses[tid].env) for tid in healthy_ids}
                return shapes, placement_shape(hv.env), fleet.debug_tenants(), victim_mode
            finally:
                fleet.close()

        base = FaultSpec.randomized(seed=42, solves=24, events=800, cycles=6)
        chaos = FaultSpec(
            rules=base.rules + (FaultRule("solve-exception", at=8, every=1, count=2, ladder=0),),
            seed=base.seed,
        )
        shapes_clean, _, _, _ = run_fleet(None)
        reset_bucket_highwater()
        shapes_chaos, shape_victim, surf, victim_mode = run_fleet(chaos)
        assert shapes_chaos == shapes_clean, "chaos leaked across the failure domain"
        assert surf["victim"]["opens"] >= 1, "the unrecoverable burst never quarantined the victim"
        assert surf["victim"]["state"] == "healthy", "victim was not re-admitted after the plan exhausted"
        assert shape_victim, "victim never converged"
        assert victim_mode == "delta", f"victim's delta path did not re-warm: {victim_mode!r}"

    def test_concurrent_churn_with_faults_under_racecheck(self):
        """run_concurrent with a live driver thread + prestager death +
        watch faults, sanitizer ON (conftest): no violations, no loop death,
        backlog settles."""
        spec = tiny_spec(
            worker=True,
            concurrent_seconds=1.0,
            faults=FaultSpec(
                rules=(
                    FaultRule("prestage-death", at=2),
                    FaultRule("watch-drop", at=50, every=41, count=4),
                    FaultRule("watch-dup", at=60, every=43, count=4),
                    FaultRule("solve-exception", at=10, ladder=1),
                ),
                seed=9,
            ),
        )
        h = ChurnHarness(spec)
        try:
            rep = h.run()
            assert rep.concurrent_solves >= 1
            assert rep.prestage_worker_restarts >= 1, "the dead worker was never healed"
            assert not h._pending, "backlog did not settle after the chaos segment"
        finally:
            h.close()
