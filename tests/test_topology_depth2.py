"""Topology depth batch 2, ported from the reference's topology_test.go
specs not yet pinned by test_topology_depth.py / test_domain_topology.py:
multi-phase skew recovery through the full Environment, capacity-type and
arch spread edges, spread-option limiting, preferred pod (anti-)affinity
violation rules, inverse anti-affinity variants, dependent affinity chains,
and NodePool taint generation. Each spec cites its reference It() line."""

import pytest

from helpers import make_nodepool, make_pod, zone_spread
from test_scheduler import LINUX_AMD64, build_env, make_scheduler
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.kube.objects import Affinity, WeightedPodAffinityTerm
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.taints import Taint


def solve(pods, node_pools=None, types=None, **kw):
    env = build_env(node_pools=node_pools, types=types)
    s = make_scheduler(*env, **kw)
    return s.solve(pods)


def make_env(node_pools=None, freeze_disruption=False):
    """`freeze_disruption` sets the pool budgets to 0 nodes — the reference
    provisioning suite runs no disruption controllers, so multi-phase specs
    that edit pool requirements must not fight drift replacement here."""
    from karpenter_tpu.apis.nodepool import Budget

    env = Environment(options=Options())
    for np in node_pools or [make_nodepool(requirements=LINUX_AMD64)]:
        if freeze_disruption:
            np.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.create(np)
    return env


def skew_counts(env, sel_labels, key=wk.ZONE_LABEL_KEY):
    """Bound selector-matched pods per domain value — ExpectSkew analogue."""
    counts = {}
    for p in env.store.list("Pod"):
        if not p.spec.node_name:
            continue
        if any(p.metadata.labels.get(k) != v for k, v in sel_labels.items()):
            continue
        node = env.store.try_get("Node", p.spec.node_name)
        if node is None:
            continue
        d = node.metadata.labels.get(key)
        if d is None:
            continue
        counts[d] = counts.get(d, 0) + 1
    return counts


def spread(key, max_skew=1, selector=None, when="DoNotSchedule", min_domains=None):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=when,
        label_selector=selector,
        min_domains=min_domains,
    )


SEL = {"matchLabels": {"app": "web"}}
WEB = {"app": "web"}


def domain_counts(results, key):
    counts = {}
    for nc in results.new_node_claims:
        r = nc.requirements.get(key)
        d = r.any() if len(r.values) == 1 else tuple(sorted(r.values))
        counts[d] = counts.get(d, 0) + len(nc.pods)
    return counts


class TestSpreadGuards:
    def test_unknown_topology_key_ignored(self):
        # topology_test.go:58 "should ignore unknown topology keys" — the
        # reference leaves such pods pending (it cannot discover domains)
        pod = make_pod(cpu="1", labels=WEB, tsc=[spread("unknown.com/key", selector=SEL)])
        results = solve([pod])
        assert not results.all_pods_scheduled()

    def test_invalid_label_selector_not_spread(self):
        # :76 "should not spread an invalid label selector" — an invalid
        # selector matches nothing, so the pods are NOT spread (the reference
        # asserts skew ConsistOf(2): both pods pack together); must not panic
        # (admission denies such selectors on k8s >= 1.27 — the reference
        # SKIPS there; we pin only the must-not-panic / must-schedule part)
        bad = {"matchExpressions": [{"key": "app", "operator": "Bogus", "values": []}]}
        pods = [make_pod(cpu="500m", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=bad)]) for _ in range(2)]
        results = solve(pods)
        assert results.all_pods_scheduled()

    def test_nil_label_selector_matches_nothing_but_schedules(self):
        # :92 "should not spread when a nil label selector is defined"
        pod = make_pod(cpu="1", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=None)])
        results = solve([pod])
        assert results.all_pods_scheduled()


class TestMultiPhaseSkew:
    def test_non_minimum_domain_when_its_all_thats_available(self):
        # :266 "should schedule to the non-minimum domain if its all that's
        # available" — maxSkew 5; phases force zones 1, 2, then only 3: ten
        # pods land 6 in zone-3 (bounded by min 1 + skew 5), rest pend
        env = make_env(freeze_disruption=True)
        np_name = env.store.list("NodePool")[0].metadata.name
        tsc = [spread(wk.ZONE_LABEL_KEY, max_skew=5, selector=SEL)]

        def pin(zone):
            def patch(np):
                np.spec.template.requirements = LINUX_AMD64 + [
                    {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": [zone]}
                ]

            env.store.patch("NodePool", np_name, patch)

        pin("test-zone-a")
        env.store.create(make_pod(cpu="1100m", name="a0", labels=WEB, tsc=tsc))
        env.settle(rounds=6)
        assert skew_counts(env, WEB) == {"test-zone-a": 1}
        pin("test-zone-b")
        env.store.create(make_pod(cpu="1100m", name="b0", labels=WEB, tsc=tsc))
        env.settle(rounds=6)
        assert skew_counts(env, WEB) == {"test-zone-a": 1, "test-zone-b": 1}
        pin("test-zone-c")
        for i in range(10):
            env.store.create(make_pod(cpu="1100m", name=f"c{i}", labels=WEB, tsc=tsc))
        env.settle(rounds=10)
        counts = skew_counts(env, WEB)
        assert counts == {"test-zone-a": 1, "test-zone-b": 1, "test-zone-c": 6}, counts

    def test_only_minimum_domains_when_already_violating_skew(self):
        # :308 "should only schedule to minimum domains if already violating
        # max skew" — delete two zones' pods, then new pods rebalance toward
        # the vacated zones
        three_zones = make_nodepool(
            requirements=LINUX_AMD64
            + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b", "test-zone-c"]}]
        )
        env = make_env(node_pools=[three_zones], freeze_disruption=True)
        tsc = [spread(wk.ZONE_LABEL_KEY, max_skew=1, selector=SEL)]
        for i in range(9):
            env.store.create(make_pod(cpu="1100m", name=f"p{i}", labels=WEB, tsc=tsc))
        env.settle(rounds=10)
        counts = skew_counts(env, WEB)
        assert sorted(counts.values()) == [3, 3, 3], counts
        keep_zone = sorted(counts)[0]
        for p in env.store.list("Pod"):
            node = env.store.try_get("Node", p.spec.node_name)
            if node is not None and node.metadata.labels.get(wk.ZONE_LABEL_KEY) != keep_zone:
                env.store.try_delete("Pod", p.metadata.name)
        env.settle(rounds=4)
        assert list(skew_counts(env, WEB).values()) == [3]
        for i in range(3):
            env.store.create(make_pod(cpu="1100m", name=f"r{i}", labels=WEB, tsc=tsc))
        env.settle(rounds=10)
        counts = skew_counts(env, WEB)
        # the three new pods go to the two vacated zones (skew recovery)
        assert counts[keep_zone] == 3
        assert sum(counts.values()) == 6
        assert len(counts) == 3, counts

    def test_zonal_constraint_with_existing_pod(self):
        # :232 "should respect NodePool zonal constraints (existing pod)" —
        # a running pod's zone counts into the spread even when the pool can
        # no longer produce that zone
        env = make_env(freeze_disruption=True)
        np_name = env.store.list("NodePool")[0].metadata.name
        tsc = [spread(wk.ZONE_LABEL_KEY, max_skew=1, selector=SEL)]

        def pin(zones):
            def patch(np):
                np.spec.template.requirements = LINUX_AMD64 + [
                    {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": zones}
                ]

            env.store.patch("NodePool", np_name, patch)

        pin(["test-zone-a"])
        env.store.create(make_pod(cpu="1", name="seed", labels=WEB, tsc=tsc))
        env.settle(rounds=6)
        assert skew_counts(env, WEB) == {"test-zone-a": 1}
        pin(["test-zone-a", "test-zone-b"])
        for i in range(5):
            env.store.create(make_pod(cpu="1", name=f"p{i}", labels=WEB, tsc=tsc))
        env.settle(rounds=8)
        counts = skew_counts(env, WEB)
        assert sum(counts.values()) == 6
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_only_matching_pods_on_domain_nodes_count(self):
        # :412 — selector-matched pods on nodes WITHOUT the topology label
        # must not count into the spread
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        env = make_env()
        # an unmanaged zone-less node hosting a matching pod
        env.store.create(
            Node(
                metadata=ObjectMeta(name="legacy", labels={wk.HOSTNAME_LABEL_KEY: "legacy"}),
                spec=NodeSpec(provider_id="legacy://1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                ),
            )
        )
        env.store.create(make_pod(cpu="100m", name="legacy-pod", labels=WEB, node_name="legacy"))
        tsc = [spread(wk.ZONE_LABEL_KEY, max_skew=1, selector=SEL)]
        for i in range(6):
            env.store.create(make_pod(cpu="1", name=f"p{i}", labels=WEB, tsc=tsc))
        env.settle(rounds=8)
        counts = skew_counts(env, WEB)
        assert sum(counts.values()) == 6  # the legacy pod has no zone: uncounted
        assert max(counts.values()) - min(counts.values()) <= 1, counts


class TestCapacityTypeAndArchSpread:
    def test_capacity_type_do_not_schedule_respects_skew(self):
        # :681 — capacity-type spread with DoNotSchedule never violates skew
        results = solve(
            [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)]) for _ in range(6)]
        )
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_capacity_type_schedule_anyway_may_violate(self):
        # :716 "should violate max-skew when unsat = schedule anyway" — the
        # pool is pinned to one capacity type; ScheduleAnyway pods all land
        np = make_nodepool(
            requirements=LINUX_AMD64 + [{"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": ["on-demand"]}]
        )
        pods = [
            make_pod(cpu="1", labels=WEB, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL, when="ScheduleAnyway")])
            for _ in range(4)
        ]
        results = solve(pods, node_pools=[np])
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert counts == {"on-demand": 4}

    def test_capacity_type_spread_with_node_affinity_constraint(self):
        # :815 "(node required affinity constrained)" — affinity restricts to
        # both capacity types explicitly; spread balances across them
        pods = [
            make_pod(
                cpu="1",
                labels=WEB,
                required_affinity=[[{"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": ["spot", "on-demand"]}]],
                tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)],
            )
            for _ in range(6)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert set(counts) == {"spot", "on-demand"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_capacity_type_spread_unconstrained(self):
        # :852 "(no constraints)"
        pods = [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)]) for _ in range(4)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_hostname_spread_varying_arch(self):
        # :607 "balance multiple deployments with hostname topology spread &
        # varying arch" — two deployments, one per arch, each hostname-spread
        np = make_nodepool(
            requirements=[
                {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
                {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64", "arm64"]},
            ]
        )
        sel_a = {"matchLabels": {"app": "amd"}}
        sel_b = {"matchLabels": {"app": "arm"}}
        pods = [
            make_pod(
                cpu="1", labels={"app": "amd"}, node_selector={wk.ARCH_LABEL_KEY: "amd64"},
                tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=sel_a)],
            )
            for _ in range(3)
        ] + [
            make_pod(
                cpu="1", labels={"app": "arm"}, node_selector={wk.ARCH_LABEL_KEY: "arm64"},
                tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=sel_b)],
            )
            for _ in range(3)
        ]
        results = solve(pods, node_pools=[np])
        assert results.all_pods_scheduled()
        # hostname spread with skew 1: one pod per claim within a deployment
        for nc in results.new_node_claims:
            apps = {p.metadata.labels.get("app") for p in nc.pods}
            assert len(nc.pods) <= len(apps), "same-deployment pods must not share a host"


class TestSpreadOptionLimiting:
    def test_node_requirements_limit_spread_options(self):
        # :1766 "should limit spread options by node requirements" — pods
        # restricted to two zones spread across exactly those
        pods = [
            make_pod(
                cpu="1",
                labels=WEB,
                required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]],
                tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL)],
            )
            for _ in range(6)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert set(counts) == {"test-zone-a", "test-zone-b"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_node_selector_limits_spread_options_capacity_type(self):
        # :1857/:1881 — a capacity-type selector pins the whole spread there
        pods = [
            make_pod(
                cpu="1", labels=WEB, node_selector={wk.CAPACITY_TYPE_LABEL_KEY: "spot"},
                tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY) == {"spot": 4}


class TestPreferredPodAffinityViolation:
    def test_preferred_pod_affinity_violable(self):
        # :2231 "should allow violation of preferred pod affinity" — the
        # affinity target doesn't exist; the pod schedules anyway
        pref = Affinity(
            pod_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=50,
                    term=PodAffinityTerm(label_selector={"matchLabels": {"security": "s2"}}, topology_key=wk.HOSTNAME_LABEL_KEY),
                )
            ]
        )
        aff_pod = make_pod(cpu="1")
        aff_pod.spec.affinity = pref
        pods = [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.HOSTNAME_LABEL_KEY, selector=SEL)]) for _ in range(10)]
        results = solve(pods + [aff_pod])
        assert results.all_pods_scheduled()

    def test_preferred_pod_anti_affinity_violable(self):
        # :2264 "should allow violation of preferred pod anti-affinity" —
        # preferred anti between spread pods still lets everything schedule
        anti_pref = Affinity(
            pod_anti_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=50,
                    term=PodAffinityTerm(label_selector={"matchLabels": WEB}, topology_key=wk.ZONE_LABEL_KEY),
                )
            ]
        )
        pods = []
        for _ in range(6):
            p = make_pod(cpu="1", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL)])
            p.spec.affinity = anti_pref
            pods.append(p)
        results = solve(pods)
        assert results.all_pods_scheduled()

    def test_affinity_preference_with_conflicting_required_constraint(self):
        # :2630 "should allow violation of a pod affinity preference with a
        # conflicting required constraint" — required zone In a; preferred
        # affinity to a pod pinned in zone b; the preference loses
        target = make_pod(cpu="1", labels={"security": "s2"}, node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})
        pod = make_pod(
            cpu="1",
            required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}]],
        )
        pod.spec.affinity.pod_affinity_preferred = [
            WeightedPodAffinityTerm(
                weight=50,
                term=PodAffinityTerm(label_selector={"matchLabels": {"security": "s2"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
        results = solve([target, pod])
        assert results.all_pods_scheduled()
        zones = {nc.requirements.get(wk.ZONE_LABEL_KEY).any() for nc in results.new_node_claims if nc.pods}
        assert zones == {"test-zone-a", "test-zone-b"}


class TestAntiAffinityDepth:
    def test_anti_affinity_arch(self):
        # :2380 "should not violate pod anti-affinity (arch)" — anti over the
        # arch key separates the two pods onto different arches
        np = make_nodepool(
            requirements=[
                {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
                {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64", "arm64"]},
            ]
        )
        sel = {"app": "arch-anti"}
        term = PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ARCH_LABEL_KEY)
        pods = [make_pod(cpu="1", labels=sel, anti_affinity=[term]) for _ in range(2)]
        results = solve(pods, node_pools=[np])
        scheduled = [nc for nc in results.new_node_claims if nc.pods]
        archs = [nc.requirements.get(wk.ARCH_LABEL_KEY).any() for nc in scheduled]
        # late-committal may leave the second replica pending this round; the
        # scheduled ones must occupy distinct arches
        assert len(archs) == len(set(archs))

    def test_schroedinger_anti_affinity_target_blocks_then_commits(self):
        # :2499 "(Schrödinger)" — an anti-affinity pod whose zone is
        # uncommitted blocks the matching pod in round 1; once the node EXISTS
        # (zone committed), round 2 schedules the matching pod elsewhere
        env = make_env()
        sel = {"security": "s2"}
        anywhere = make_pod(cpu="2", name="anywhere", anti_affinity=[
            PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY)
        ])
        target = make_pod(cpu="1", name="target", labels=sel)
        env.store.create(anywhere)
        env.store.create(target)
        env.settle(rounds=8)
        a = env.store.get("Pod", "anywhere")
        t = env.store.get("Pod", "target")
        assert a.spec.node_name, "anti-affinity pod schedules first (FFD order)"
        assert t.spec.node_name, "target schedules once the zone is committed"
        za = env.store.get("Node", a.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        zt = env.store.get("Node", t.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        assert za != zt

    def test_anti_affinity_zone_other_schedules_first(self):
        # :2358 "(other schedules first)" — the plain pod lands first; the
        # anti pod avoids its zone
        env = make_env()
        sel = {"app": "first"}
        env.store.create(make_pod(cpu="1", name="plain", labels=sel))
        env.settle(rounds=6)
        anti = make_pod(cpu="1", name="anti", anti_affinity=[
            PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY)
        ])
        env.store.create(anti)
        env.settle(rounds=8)
        p1 = env.store.get("Pod", "plain")
        p2 = env.store.get("Pod", "anti")
        assert p1.spec.node_name and p2.spec.node_name
        z1 = env.store.get("Node", p1.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        z2 = env.store.get("Node", p2.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        assert z1 != z2

    def test_preferred_inverse_anti_affinity_violable(self):
        # :2423 "should violate preferred pod anti-affinity on zone
        # (inverse)" — a running pod's PREFERRED anti-affinity never blocks
        # new pods into its zone
        env = make_env()
        sel = {"app": "victim"}
        holder = make_pod(cpu="1", name="holder")
        holder.spec.affinity = Affinity(
            pod_anti_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=50, term=PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY)
                )
            ]
        )
        env.store.create(holder)
        env.settle(rounds=6)
        for i in range(4):
            env.store.create(make_pod(cpu="1", name=f"v{i}", labels=sel))
        env.settle(rounds=8)
        assert all(env.store.get("Pod", f"v{i}").spec.node_name for i in range(4))


class TestPodAffinityDepth:
    def test_pod_affinity_zone_unconstrained_target(self):
        # :2727 "should support pod affinity with zone topology
        # (unconstrained target)" — the target floats; both co-locate
        env = make_env()
        sel = {"security": "s2"}
        env.store.create(make_pod(cpu="1", name="target", labels=sel))
        env.store.create(
            make_pod(cpu="1", name="follower", pod_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY)
            ])
        )
        env.settle(rounds=8)
        t = env.store.get("Pod", "target")
        f = env.store.get("Pod", "follower")
        assert t.spec.node_name and f.spec.node_name
        zt = env.store.get("Node", t.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        zf = env.store.get("Node", f.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        assert zt == zf

    def test_pod_affinity_zone_constrained_target(self):
        # :2760 "(constrained target)" — the target is pinned; the follower
        # must land in the target's zone
        env = make_env()
        sel = {"security": "s2"}
        env.store.create(make_pod(cpu="1", name="target", labels=sel, node_selector={wk.ZONE_LABEL_KEY: "test-zone-c"}))
        env.store.create(
            make_pod(cpu="1", name="follower", pod_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY)
            ])
        )
        env.settle(rounds=8)
        f = env.store.get("Pod", "follower")
        assert f.spec.node_name
        assert env.store.get("Node", f.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY] == "test-zone-c"

    def test_multiple_dependent_affinities_chain(self):
        # :2789 "should handle multiple dependent affinities" — a -> b -> c
        # chain of hostname affinities lands together over rounds
        env = make_env()
        env.store.create(make_pod(cpu="100m", name="a", labels={"d": "a"}))
        env.store.create(
            make_pod(cpu="100m", name="b", labels={"d": "b"}, pod_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": {"d": "a"}}, topology_key=wk.HOSTNAME_LABEL_KEY)
            ])
        )
        env.store.create(
            make_pod(cpu="100m", name="c", labels={"d": "c"}, pod_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": {"d": "b"}}, topology_key=wk.HOSTNAME_LABEL_KEY)
            ])
        )
        env.settle(rounds=10)
        hosts = {env.store.get("Pod", n).spec.node_name for n in ("a", "b", "c")}
        assert all(hosts)
        assert len(hosts) == 1, hosts

    def test_unsatisfiable_dependency_fails(self):
        # :2824 "should fail to schedule pods with unsatisfiable
        # dependencies" — affinity to a selector no pod ever carries
        env = make_env()
        env.store.create(
            make_pod(cpu="100m", name="orphan", pod_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": {"never": "exists"}}, topology_key=wk.HOSTNAME_LABEL_KEY)
            ])
        )
        env.settle(rounds=6)
        assert not env.store.get("Pod", "orphan").spec.node_name

    def test_empty_namespace_selector_limits_to_own_namespace(self):
        # :2917 "should filter pod affinity topologies by namespace, empty
        # namespace selector" — {} namespaceSelector means ALL namespaces in
        # k8s semantics; the reference treats an empty selector object as
        # all-namespaces for affinity counting
        env = make_env()
        sel = {"security": "s2"}
        env.store.create(make_pod(cpu="1", name="target", ns="other", labels=sel))
        follower = make_pod(cpu="1", name="follower", pod_affinity=[
            PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.ZONE_LABEL_KEY, namespace_selector={})
        ])
        env.store.create(follower)
        env.settle(rounds=8)
        f = env.store.get("Pod", "follower")
        t = env.store.get("Pod", "target", namespace="other")
        assert f.spec.node_name and t.spec.node_name
        zf = env.store.get("Node", f.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        zt = env.store.get("Node", t.spec.node_name).metadata.labels[wk.ZONE_LABEL_KEY]
        assert zf == zt


class TestNodePoolTaints:
    def test_nodes_carry_nodepool_taints(self):
        # :2981 "should taint nodes with NodePool taints"
        np = make_nodepool(requirements=LINUX_AMD64, taints=[Taint(key="dedicated", value="infra", effect="NoSchedule")])
        env = make_env(node_pools=[np])
        env.store.create(
            make_pod(cpu="1", name="tolerant", tolerations=[{"key": "dedicated", "operator": "Exists"}])
        )
        env.settle(rounds=8)
        nodes = env.store.list("Node")
        assert nodes
        assert any(t.key == "dedicated" and t.value == "infra" for t in nodes[0].spec.taints)

    def test_intolerant_pods_never_schedule_to_tainted_pool(self):
        # :2991 inverse — a pod without the toleration stays pending
        np = make_nodepool(requirements=LINUX_AMD64, taints=[Taint(key="dedicated", value="infra", effect="NoSchedule")])
        env = make_env(node_pools=[np])
        env.store.create(make_pod(cpu="1", name="plain"))
        env.settle(rounds=6)
        assert not env.store.get("Pod", "plain").spec.node_name


class TestSpreadDiscoveryAndPolicies:
    def test_zonal_subset_with_requirements_and_labels(self):
        # topology_test.go:188 "(subset) with requirements and labels" — the
        # pod's own selector AND the pool's zone subset both narrow the
        # spread universe
        np = make_nodepool(
            requirements=LINUX_AMD64
            + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]
        )
        pods = [
            make_pod(
                cpu="1", labels=WEB,
                node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"},
                tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL)],
            )
            for _ in range(3)
        ]
        results = solve(pods, node_pools=[np])
        assert results.all_pods_scheduled()
        assert domain_counts(results, wk.ZONE_LABEL_KEY) == {"test-zone-a": 3}

    def test_do_not_schedule_discovers_domains_from_pool(self):
        # :380 "(discover domains)" — the spread universe comes from the
        # POOL's producible zones, not from existing nodes
        np = make_nodepool(
            requirements=LINUX_AMD64
            + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]
        )
        pods = [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL)]) for _ in range(6)]
        results = solve(pods, node_pools=[np])
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert set(counts) == {"test-zone-a", "test-zone-b"}
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_min_domains_greater_than_minimum_allows_scheduling(self):
        # :522 "satisfied minDomains constraints (greater than minimum)"
        pods = [
            make_pod(cpu="1", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL, min_domains=2)])
            for _ in range(6)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert len(counts) >= 2

    def test_balance_across_nodepool_requirements(self):
        # :981 "should balance pods across NodePool requirements" — two pools
        # producing DISJOINT zone sets; the spread spans their union
        np_a = make_nodepool(
            name="pool-a",
            requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}],
        )
        np_b = make_nodepool(
            name="pool-b",
            requirements=LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}],
        )
        pods = [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL)]) for _ in range(6)]
        results = solve(pods, node_pools=[np_a, np_b])
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert counts == {"test-zone-a": 3, "test-zone-b": 3}

    def test_zone_and_hostname_constraints_together(self):
        # :1090 "should spread pods while respecting both constraints" —
        # zone skew 1 AND hostname skew 1 simultaneously
        pods = [
            make_pod(
                cpu="1", labels=WEB,
                tsc=[spread(wk.ZONE_LABEL_KEY, selector=SEL), spread(wk.HOSTNAME_LABEL_KEY, selector=SEL)],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        zc = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert max(zc.values()) - min(zc.values()) <= 1
        for nc in results.new_node_claims:
            assert len(nc.pods) <= 1, "hostname skew 1: one pod per node"

    def test_unknown_match_label_keys_ignored(self):
        # :1168 "should ignore unknown labels specified in matchLabelKeys" —
        # a matchLabelKeys entry absent from the pod's labels is skipped
        tsc = spread(wk.ZONE_LABEL_KEY, selector=SEL)
        tsc.match_label_keys = ["pod-template-hash"]  # pods don't carry it
        pods = [make_pod(cpu="1", labels=WEB, tsc=[tsc]) for _ in range(6)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_taints_policy_honor_with_mutually_exclusive_pools(self):
        # :1448 "mutually exclusive NodePools (by taints) share domains
        # (NodeTaintsPolicy=honor)" — the tolerating pods count domains of
        # both pools; intolerant spread pods count only the untainted pool's
        np_plain = make_nodepool(name="plain", requirements=LINUX_AMD64)
        np_tainted = make_nodepool(
            name="tainted",
            requirements=LINUX_AMD64,
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        )
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.ZONE_LABEL_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector=SEL,
            node_taints_policy="Honor",
        )
        pods = [make_pod(cpu="1", labels=WEB, tsc=[tsc]) for _ in range(4)]
        results = solve(pods, node_pools=[np_plain, np_tainted])
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_affinity_policy_honor_limits_to_affine_domains(self):
        # :1596 "(NodeAffinityPolicy=honor)" — with Honor, the pod's node
        # affinity narrows the spread universe to its allowed zones
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.ZONE_LABEL_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector=SEL,
            node_affinity_policy="Honor",
        )
        pods = [
            make_pod(
                cpu="1", labels=WEB,
                required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}]],
                tsc=[tsc],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.ZONE_LABEL_KEY)
        assert set(counts) <= {"test-zone-a", "test-zone-b"}
        assert max(counts.values()) - min(counts.values()) <= 1


class TestCapacityTypeCounting:
    """topology_test.go :747-:792 — the capacity-type mirror of the zone
    counting family."""

    def test_only_matching_pods_count_capacity_type(self):
        # :747 — non-matching pods in a capacity-type domain don't count
        decoy = make_pod(cpu="1", labels={"app": "other"}, node_selector={wk.CAPACITY_TYPE_LABEL_KEY: "spot"})
        pods = [make_pod(cpu="1", labels=WEB, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL)]) for _ in range(4)]
        results = solve([decoy] + pods)
        assert results.all_pods_scheduled()
        web_counts = {}
        for nc in results.new_node_claims:
            ct = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
            d = ct.any() if len(ct.values) == 1 else tuple(sorted(ct.values))
            n = sum(1 for p in nc.pods if p.metadata.labels.get("app") == "web")
            if n:
                web_counts[d] = web_counts.get(d, 0) + n
        assert max(web_counts.values()) - min(web_counts.values()) <= 1

    def test_no_selector_matches_all_pods_capacity_type(self):
        # :780 "should match all pods when labelSelector is not specified"
        pods = [make_pod(cpu="1", tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector={"matchLabels": {}})]) for _ in range(4)]
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_interdependent_selectors_capacity_type(self):
        # :792 "should handle interdependent selectors" — two deployments
        # each spreading on the OTHER's label set still all schedule
        sel_a = {"matchLabels": {"app": "a"}}
        sel_b = {"matchLabels": {"app": "b"}}
        pods = [make_pod(cpu="1", labels={"app": "a"}, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=sel_b)]) for _ in range(3)]
        pods += [make_pod(cpu="1", labels={"app": "b"}, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=sel_a)]) for _ in range(3)]
        results = solve(pods)
        assert results.all_pods_scheduled()


class TestTaintsPolicyBalance:
    CUSTOM = "company.com/tier"

    def _pools(self):
        np_a = make_nodepool(
            name="tier-a", requirements=LINUX_AMD64 + [{"key": self.CUSTOM, "operator": "In", "values": ["a"]}]
        )
        np_b = make_nodepool(
            name="tier-b",
            requirements=LINUX_AMD64 + [{"key": self.CUSTOM, "operator": "In", "values": ["b"]}],
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        )
        return [np_a, np_b]

    def test_taints_policy_ignore_balances_tolerant_pods(self):
        # topology_test.go:1196 "(NodeTaintsPolicy=ignore)" — tolerant pods
        # count both pools' domains and balance across them
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=self.CUSTOM, when_unsatisfiable="DoNotSchedule",
            label_selector=SEL, node_taints_policy="Ignore",
        )
        pods = [
            make_pod(cpu="1", labels=WEB, tsc=[tsc], tolerations=[{"key": "dedicated", "operator": "Exists"}])
            for _ in range(4)
        ]
        results = solve(pods, node_pools=self._pools())
        assert results.all_pods_scheduled()
        counts = domain_counts(results, self.CUSTOM)
        assert counts == {"a": 2, "b": 2}

    def test_taints_policy_honor_restricts_intolerant_pods(self):
        # :1267 "(NodeTaintsPolicy=honor)" — intolerant pods' spread universe
        # excludes the tainted pool's domain; everything lands in pool a
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=self.CUSTOM, when_unsatisfiable="DoNotSchedule",
            label_selector=SEL, node_taints_policy="Honor",
        )
        pods = [make_pod(cpu="1", labels=WEB, tsc=[tsc]) for _ in range(4)]
        results = solve(pods, node_pools=self._pools())
        assert results.all_pods_scheduled()
        assert domain_counts(results, self.CUSTOM) == {"a": 4}


class TestMultiConstraintInterplay:
    def test_zone_and_custom_key_spread_together(self):
        # topology_test.go:1662 "should spread pods while respecting both
        # constraints" — zone skew 1 AND a custom-key skew 1 simultaneously
        custom = "company.com/shard"
        np_1 = make_nodepool(
            name="shard-1", requirements=LINUX_AMD64 + [{"key": custom, "operator": "In", "values": ["s1"]}]
        )
        np_2 = make_nodepool(
            name="shard-2", requirements=LINUX_AMD64 + [{"key": custom, "operator": "In", "values": ["s2"]}]
        )
        pods = [
            make_pod(
                cpu="1", labels=WEB,
                tsc=[
                    spread(wk.ZONE_LABEL_KEY, selector=SEL),
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=custom, when_unsatisfiable="DoNotSchedule", label_selector=SEL
                    ),
                ],
            )
            for _ in range(4)
        ]
        results = solve(pods, node_pools=[np_1, np_2])
        assert results.all_pods_scheduled()
        zc = domain_counts(results, wk.ZONE_LABEL_KEY)
        cc = domain_counts(results, custom)
        assert max(zc.values()) - min(zc.values()) <= 1
        assert cc == {"s1": 2, "s2": 2}

    def test_zone_hostname_capacity_type_all_respected(self):
        # :1702 "should spread pods while respecting all constraints"
        pods = [
            make_pod(
                cpu="1", labels=WEB,
                tsc=[
                    spread(wk.ZONE_LABEL_KEY, selector=SEL),
                    spread(wk.HOSTNAME_LABEL_KEY, max_skew=2, selector=SEL),
                    spread(wk.CAPACITY_TYPE_LABEL_KEY, selector=SEL),
                ],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        zc = domain_counts(results, wk.ZONE_LABEL_KEY)
        ctc = domain_counts(results, wk.CAPACITY_TYPE_LABEL_KEY)
        assert max(zc.values()) - min(zc.values()) <= 1
        assert max(ctc.values()) - min(ctc.values()) <= 1
        for nc in results.new_node_claims:
            assert len(nc.pods) <= 2

    def test_self_affinity_constrained_zones_single_domain(self):
        # :2079 "should respect self pod affinity for first empty topology
        # domain only (hostname/constrained zones)" — hostname self-affinity
        # pods whose zone set is constrained co-locate on ONE host in an
        # allowed zone
        sel = {"app": "huddle"}
        pods = [
            make_pod(
                cpu="100m", labels=sel,
                required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}]],
                pod_affinity=[PodAffinityTerm(label_selector={"matchLabels": sel}, topology_key=wk.HOSTNAME_LABEL_KEY)],
            )
            for _ in range(3)
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        claims = [nc for nc in results.new_node_claims if nc.pods]
        assert len(claims) == 1 and len(claims[0].pods) == 3
        assert set(claims[0].requirements.get(wk.ZONE_LABEL_KEY).values) == {"test-zone-b"}
