"""Mesh-default specs: the multi-device mesh is the production architecture.

Whenever >1 device is visible, `TPUSolver()` constructs a mesh by default
(parallel/sharded.py default_mesh) and runs the pack through the batch-sharded
feasibility pre-pass + the slot-sharded shard_map scan. These specs pin:

- the default's engage/disengage rules (n_devices>1, KARPENTER_SOLVER_MESH=0,
  explicit mesh=None, 1-device degeneration to the unsharded kernels);
- BIT-IDENTICAL placements/errors vs the single-device pack across full,
  delta (add AND removal), hybrid, and hybrid-delta modes — the mesh composes
  with the EncodeCache delta and hybrid residual paths instead of bypassing
  them;
- padding edge cases: pod/item and slot counts not divisible by the device
  count, and non-power-of-two meshes;
- the solvetrace surface: the sharded kernels are on the recompile sentinel's
  watchlist (pack_sharded / shard_feas), warm meshed re-solves record ZERO
  recompiles, and the meshed pack runs under a `shard_exchange` span.

conftest pins KARPENTER_SOLVER_MESH=0 for the rest of the unit suite (so
every solver test doesn't pay shard_map compiles); tests here re-enable it
per-test via monkeypatch.
"""

import jax
import numpy as np
import pytest

from helpers import make_pod
from karpenter_tpu.obs import TraceRecorder
from karpenter_tpu.obs.trace import sentinel
from karpenter_tpu.parallel import sharded as sh
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot
from test_solvetrace import _odd_pod, canon


def _mixed_pods(n_small=13, n_big=5):
    """A pod set whose item count is NOT a multiple of 8 (padding path)."""
    pods = [make_pod(cpu="500m", memory="512Mi", name=f"p{i}") for i in range(n_small)]
    pods += [make_pod(cpu="2", memory="3Gi", name=f"big{i}") for i in range(n_big)]
    return pods


class TestDefaultEngagement:
    def test_engages_on_multi_device(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        s = TPUSolver()
        assert s.mesh is not None and s.mesh.size == len(jax.devices())

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "0")
        assert TPUSolver().mesh is None

    def test_explicit_none_overrides_auto(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        assert TPUSolver(mesh=None).mesh is None

    def test_one_device_returns_none(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        one = jax.devices()[:1]
        monkeypatch.setattr(jax, "devices", lambda *a: one)
        assert sh.default_mesh() is None
        assert TPUSolver().mesh is None

    def test_one_device_mesh_degenerates_to_unsharded(self):
        """An explicit 1-device mesh must take the plain single-device path
        (mesh.size > 1 gate in _pack) and still carry resident delta state."""
        s = TPUSolver(force=True, mesh=sh.make_mesh(jax.devices()[:1]))
        snap = make_snapshot(_mixed_pods(5, 0))
        s.solve(snap)
        assert s.last_solve_mode == "full"
        assert s._resident is not None
        snap.pods.append(make_pod(cpu="500m", memory="512Mi", name="x"))
        r = s.solve(snap)
        assert s.last_solve_mode == "delta"
        assert not r.pod_errors


class TestShardedParity:
    """Bit-identical placements vs the single-device pack, every mode."""

    def test_full_parity(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        on = TPUSolver(force=True)
        off = TPUSolver(force=True, mesh=None)
        assert on.mesh is not None
        r_on = on.solve(make_snapshot(_mixed_pods()))
        r_off = off.solve(make_snapshot(_mixed_pods()))
        assert on.last_solve_mode == "full" == off.last_solve_mode
        assert canon(r_on) == canon(r_off)

    def test_delta_parity_add_and_remove(self, monkeypatch):
        """The EncodeCache delta path must still classify and serve a pod
        delta under the mesh — the sharded carry feeds the delta kernels."""
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        on = TPUSolver(force=True)
        snap = make_snapshot(_mixed_pods())
        on.solve(snap)
        # add
        snap.pods.append(make_pod(cpu="500m", memory="512Mi", name="extra"))
        r_on = on.solve(snap)
        assert on.last_solve_mode == "delta", on.last_solve_mode
        r_off = TPUSolver(force=True, mesh=None).solve(make_snapshot(list(snap.pods)))
        assert canon(r_on) == canon(r_off)
        # remove (re-credit into the shard-resident carry)
        snap.pods.pop()
        snap.pods.pop(0)
        r_on = on.solve(snap)
        assert on.last_solve_mode == "delta", on.last_solve_mode
        r_off = TPUSolver(force=True, mesh=None).solve(make_snapshot(list(snap.pods)))
        assert canon(r_on) == canon(r_off)

    def test_hybrid_and_hybrid_delta_parity(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")

        def build():
            return make_snapshot(_mixed_pods(12, 0) + [_odd_pod()])

        on, off = TPUSolver(), TPUSolver(mesh=None)
        r_on, r_off = on.solve(build()), off.solve(build())
        assert on.last_backend == "hybrid" == off.last_backend
        assert on.last_solve_mode == "hybrid" == off.last_solve_mode
        assert canon(r_on) == canon(r_off)
        # hybrid-delta: one more in-window pod against the retained masked
        # carry, both arms driven through the same snapshot lineage
        snap_on, snap_off = build(), build()
        on2, off2 = TPUSolver(), TPUSolver(mesh=None)
        on2.solve(snap_on)
        off2.solve(snap_off)
        for s in (snap_on, snap_off):
            s.pods.append(make_pod(cpu="500m", memory="512Mi", name="late"))
        r_on, r_off = on2.solve(snap_on), off2.solve(snap_off)
        assert on2.last_solve_mode == "hybrid-delta", on2.last_solve_mode
        assert off2.last_solve_mode == "hybrid-delta"
        assert canon(r_on) == canon(r_off)

    @pytest.mark.parametrize("n_dev,n_pods", [(3, 7), (5, 9)])
    def test_padding_edges_non_divisible(self, n_dev, n_pods):
        """Pod, item, and slot counts not divisible by the device count, on
        non-power-of-two meshes: the item axis pads in sharded_feasibility,
        the slot axis in pad_slots_for_mesh — placements stay bit-identical."""
        mesh = sh.make_mesh(jax.devices()[:n_dev])
        on = TPUSolver(force=True, mesh=mesh)
        off = TPUSolver(force=True, mesh=None)
        r_on = on.solve(make_snapshot(_mixed_pods(n_pods, 2)))
        r_off = off.solve(make_snapshot(_mixed_pods(n_pods, 2)))
        assert canon(r_on) == canon(r_off)
        assert not r_on.pod_errors


class TestShardedTraceSurface:
    def test_watchlist_covers_sharded_kernels(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        TPUSolver(force=True).solve(make_snapshot(_mixed_pods(6, 0)))
        snap = sentinel().snapshot()
        assert snap.get("pack_sharded", 0) >= 1
        assert snap.get("shard_feas", 0) >= 1

    def test_warm_mesh_resolve_zero_recompiles(self, monkeypatch):
        """The steady-state contract under a mesh: an identical warm
        re-solve reuses every per-(mesh, statics) kernel — the sentinel must
        record zero recompiles, sharded entries included."""
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        rec = TraceRecorder(enabled=True)
        s = TPUSolver(force=True, recorder=rec)
        snap = make_snapshot(_mixed_pods(6, 0))
        s.solve(snap)  # cold: compiles are attributed here
        s.solve(snap)
        assert rec.last().recompiles == {}, rec.last().recompiles

    def test_shard_exchange_span_recorded(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_MESH", "auto")
        rec = TraceRecorder(enabled=True)
        s = TPUSolver(force=True, recorder=rec)
        s.solve(make_snapshot(_mixed_pods(6, 0)))
        tr = rec.last()
        pack = next(sp for sp in tr.spans if sp.name == "pack")
        exch = [c for c in pack.children if c.name == "shard_exchange"]
        assert exch and exch[0].attrs.get("n_dev") == len(jax.devices())
        assert tr.phase_totals.get("shard_exchange", 0) > 0
