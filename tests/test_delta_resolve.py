"""Incremental (delta) re-solve on the device-resident pack carry.

The steady-state reconcile stream is small pod deltas over an unchanged row
side: pods APPENDING (scale-up), pods LEAVING pending (they bound or were
deleted — the dominant event), or both in one reconcile. The solver must hit
a device-side delta path for all three (VERDICT r4 #4), falling back to the
full pack — never the FFD host path — when the carry is too stale to extend
(reference analogue: event-driven state updates, cluster.go:945-964).
"""

from helpers import hostname_anti_affinity, make_pod, zone_spread
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot


def _warm_solver(pods, **kw):
    """Solve once on the full set to land the device-resident carry."""
    snap = make_snapshot(list(pods), **kw)
    solver = TPUSolver(force=True)
    results = solver.solve(snap)
    assert solver.last_backend == "tpu"
    assert solver.last_solve_mode == "full"
    assert not results.pod_errors
    return snap, solver


def _placed_pod_names(results):
    names = set()
    for nc in results.new_node_claims:
        names.update(p.metadata.name for p in nc.pods)
    for en in results.existing_nodes:
        names.update(p.metadata.name for p in en.pods)
    return names


class TestRemovalDelta:
    def test_single_removal_takes_delta_path(self):
        pods = [make_pod(cpu="500m") for _ in range(20)]
        snap, solver = _warm_solver(pods)
        gone = snap.pods.pop()
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        placed = _placed_pod_names(results)
        assert gone.metadata.name not in placed
        assert len(placed) == 19

    def test_removal_from_middle_of_list(self):
        pods = [make_pod(cpu="500m") for _ in range(12)]
        snap, solver = _warm_solver(pods)
        gone = snap.pods.pop(5)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert gone.metadata.name not in _placed_pod_names(results)
        assert len(_placed_pod_names(results)) == 11

    def test_multiple_removals_one_reconcile(self):
        pods = [make_pod(cpu="250m") for _ in range(30)]
        snap, solver = _warm_solver(pods)
        del snap.pods[3:9]
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert len(_placed_pod_names(results)) == 24

    def test_removal_recredits_capacity_for_later_add(self):
        # fill nodes tightly, remove one pod, add one of the same shape: the
        # add must reuse the freed capacity instead of opening a new node
        pods = [make_pod(cpu="1") for _ in range(8)]
        snap, solver = _warm_solver(pods)
        full = solver.solve(snap)
        n_claims_full = len([nc for nc in full.new_node_claims if nc.pods])
        snap.pods.pop()
        solver.solve(snap)
        snap.pods.append(make_pod(cpu="1"))
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        n_claims_after = len([nc for nc in results.new_node_claims if nc.pods])
        assert n_claims_after <= n_claims_full

    def test_mixed_churn_single_reconcile(self):
        # one pod leaves AND one arrives between reconciles — both sides of
        # the delta must land in one incremental solve
        pods = [make_pod(cpu="500m") for _ in range(16)]
        snap, solver = _warm_solver(pods)
        snap.pods.pop(2)
        newcomer = make_pod(cpu="500m")
        snap.pods.append(newcomer)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        assert newcomer.metadata.name in _placed_pod_names(results)
        assert len(_placed_pod_names(results)) == 16

    def test_chained_deltas_stay_incremental(self):
        pods = [make_pod(cpu="250m") for _ in range(20)]
        snap, solver = _warm_solver(pods)
        for _ in range(3):
            snap.pods.pop()
            assert not solver.solve(snap).pod_errors
            assert solver.last_solve_mode == "delta"
        for _ in range(3):
            snap.pods.append(make_pod(cpu="250m"))
            assert not solver.solve(snap).pod_errors
            assert solver.last_solve_mode == "delta"

    def test_removed_then_readded_same_object(self):
        pods = [make_pod(cpu="500m") for _ in range(10)]
        snap, solver = _warm_solver(pods)
        gone = snap.pods.pop(0)
        solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        # the SAME pod object returns (unbound again): known signature
        snap.pods.append(gone)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert gone.metadata.name in _placed_pod_names(results)

    def test_reordered_pod_list_takes_full_path(self):
        pods = [make_pod(cpu="500m") for _ in range(10)]
        snap, solver = _warm_solver(pods)
        snap.pods.reverse()
        results = solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert not results.pod_errors


class TestRemovalDeltaSpread:
    def test_spread_pod_removal_decrements_domain_count(self):
        # 8 zone-spread pods over 4 zones -> 2 per zone; remove one, add one
        # of the same shape: the newcomer must land in the vacated zone to
        # keep skew 0/1 — proving counts_zone was re-credited on device
        sel = {"app": "web"}
        pods = [make_pod(cpu="500m", labels=sel, tsc=[zone_spread(selector=sel)]) for _ in range(8)]
        snap, solver = _warm_solver(pods)
        snap.pods.pop()
        r1 = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not r1.pod_errors
        snap.pods.append(make_pod(cpu="500m", labels=sel, tsc=[zone_spread(selector=sel)]))
        r2 = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not r2.pod_errors
        assert len(_placed_pod_names(r2)) == 8

    def test_removal_breaking_skew_falls_back_to_full_pack(self):
        # skew-1 spread, counts balanced; removing enough pods from one zone
        # can leave the survivors outside the skew envelope — the solver must
        # RETRY ON THE FULL TENSOR PACK (not FFD), which re-places everyone
        sel = {"app": "skew"}
        pods = [make_pod(cpu="500m", labels=sel, tsc=[zone_spread(max_skew=1, selector=sel)]) for _ in range(8)]
        snap, solver = _warm_solver(pods)
        # remove half — guaranteed to vacate whole domains
        del snap.pods[0:4]
        results = solver.solve(snap)
        # either the delta survived validation (balanced removal) or the full
        # pack re-ran; both must succeed on the tensor backend
        assert solver.last_backend == "tpu"
        assert not results.pod_errors
        assert len(_placed_pod_names(results)) == 4


class TestRemovalDeltaGates:
    """Takes that cannot be cleanly reversed route to the full pack."""

    def test_hostname_anti_affinity_removal_stays_delta(self):
        # hostname anti-affinity counts decrement cleanly (the vacated host
        # becomes placeable again) — removal is reversible, delta-eligible
        sel = {"app": "anti"}
        pods = [make_pod(cpu="500m", labels=sel, anti_affinity=[hostname_anti_affinity(sel)]) for _ in range(4)]
        snap, solver = _warm_solver(pods)
        snap.pods.pop()
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        # the vacated host is reusable: a replacement replica still fits in
        # 4 single-pod nodes total, proving the host count was re-credited
        snap.pods.append(make_pod(cpu="500m", labels=sel, anti_affinity=[hostname_anti_affinity(sel)]))
        r2 = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not r2.pod_errors
        assert len([nc for nc in r2.new_node_claims if nc.pods]) == 4

    def test_zone_anti_affinity_pod_removal_stays_delta(self):
        # zone-keyed anti-affinity blocks the placed pod's whole reachable
        # domain set (late committal); the widened recredit RECOMPUTES the
        # touched groups' count rows from the surviving assignment, so the
        # removal stays on the delta path and the vacated zone re-opens
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.kube.objects import PodAffinityTerm

        sel = {"app": "zanti"}
        term = PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)
        # zone-pinned replicas (unpinned zone-anti sets place one pod per
        # solve by late-committal design)
        def anti_pod(z):
            return make_pod(
                cpu="500m",
                labels=sel,
                anti_affinity=[term],
                node_selector={wk.ZONE_LABEL_KEY: f"test-zone-{z}"},
            )

        pods = [anti_pod(z) for z in ("a", "b", "c")]
        snap, solver = _warm_solver(pods)
        snap.pods.pop()
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        assert len(_placed_pod_names(results)) == 2
        # the vacated zone is genuinely unblocked: a replacement replica
        # pinned there places on the SAME carry (a stale block would leave it
        # unplaced and bounce the solve to the full pack)
        replacement = anti_pod("c")
        snap.pods.append(replacement)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        assert replacement.metadata.name in _placed_pod_names(results)

    def test_host_port_pod_removal_stays_delta(self):
        pods = [make_pod(cpu="500m") for _ in range(6)]
        ported = make_pod(cpu="500m")
        ported.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]
        pods.append(ported)
        snap, solver = _warm_solver(pods)
        # remove the ported pod: the port planes rebuild from the surviving
        # assignment (unions are not subtractable, but they are a pure
        # function of the survivors), so the removal stays a delta
        snap.pods.remove(ported)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        # the port is genuinely released: a new pod claiming the same host
        # port places on the same carry
        ported2 = make_pod(cpu="500m")
        ported2.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]
        snap.pods.append(ported2)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors
        assert ported2.metadata.name in _placed_pod_names(results)

    def test_plain_pod_removal_beside_ported_pod_stays_delta(self):
        # only the REMOVED pod's reversibility matters: removing a plain pod
        # while a ported pod stays placed is still a delta
        pods = [make_pod(cpu="500m") for _ in range(6)]
        ported = make_pod(cpu="500m")
        ported.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]
        pods.append(ported)
        snap, solver = _warm_solver(pods)
        snap.pods.pop(0)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors

    def test_unassigned_removed_pod_needs_no_recredit(self):
        # a pod the previous solve could not place (pod_errors) removes
        # without touching the carry
        pods = [make_pod(cpu="500m") for _ in range(5)]
        giant = make_pod(cpu="4000")  # no instance type fits
        pods.append(giant)
        snap = make_snapshot(list(pods))
        solver = TPUSolver(force=True)
        r0 = solver.solve(snap)
        assert giant.key() in r0.pod_errors
        snap.pods.remove(giant)
        results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not results.pod_errors


class TestFallbackUnpinning:
    def test_removing_the_out_of_window_pod_reengages_tensor_path(self):
        # review finding: with the offending pod removed, a removal delta
        # must NOT chain the base's stale fallback reason — the full encode
        # re-derives and the tensor path re-engages
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm

        plain = [make_pod(cpu="500m") for _ in range(6)]
        # preferred pod affinity is out-of-window (host relaxation owns it)
        odd = make_pod(cpu="500m")
        odd.spec.affinity = Affinity(
            pod_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=1,
                    term=PodAffinityTerm(label_selector={"x": "y"}, topology_key=wk.ZONE_LABEL_KEY),
                )
            ]
        )
        snap = make_snapshot(plain + [odd])
        solver = TPUSolver(hybrid=False)  # legacy whole-snapshot fallback
        solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        snap.pods.remove(odd)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu", solver.last_fallback_reasons
        assert not results.pod_errors

    def test_removing_the_out_of_window_pod_after_hybrid_reengages_tensor_path(self):
        # same shape through the DEFAULT (hybrid) solver: the pod-local
        # reason routes to the hybrid partition first, and the pure tensor
        # path re-engages once the offending pod leaves
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm

        plain = [make_pod(cpu="500m") for _ in range(6)]
        odd = make_pod(cpu="500m")
        odd.spec.affinity = Affinity(
            pod_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=1,
                    term=PodAffinityTerm(label_selector={"x": "y"}, topology_key=wk.ZONE_LABEL_KEY),
                )
            ]
        )
        snap = make_snapshot(plain + [odd])
        solver = TPUSolver()
        solver.solve(snap)
        assert solver.last_backend == "hybrid"
        snap.pods.remove(odd)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu", solver.last_fallback_reasons
        assert not results.pod_errors


class TestDeltaEquivalence:
    def test_churned_delta_matches_fresh_full_solve(self):
        # after a removal+add churn sequence, the delta placement must be
        # exactly as good as a fresh full solve on the same snapshot
        import random

        rng = random.Random(7)
        pods = [make_pod(cpu=f"{rng.choice([250, 500, 1000])}m") for _ in range(24)]
        snap, solver = _warm_solver(pods)
        for _ in range(4):
            snap.pods.pop(rng.randrange(len(snap.pods)))
        for _ in range(2):
            snap.pods.append(make_pod(cpu="500m"))
        delta_results = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        fresh = TPUSolver(force=True)
        full_results = fresh.solve(make_snapshot(list(snap.pods)))
        assert not delta_results.pod_errors and not full_results.pod_errors
        assert _placed_pod_names(delta_results) == _placed_pod_names(full_results)
        # claim count parity: the carry may keep an extra open slot, but the
        # delta must not fragment placements vs fresh by more than one node
        n_delta = len([nc for nc in delta_results.new_node_claims if nc.pods])
        n_full = len([nc for nc in full_results.new_node_claims if nc.pods])
        assert n_delta <= n_full + 1
