"""Tier-1 gate for the detcheck runtime arm (obs/detcheck.py): the
hash-seed-perturbed dual-run sanitizer behind the bit-identical-placement
contract.

Contract families pinned here:
  1. mode-matrix dual run — one solver driven through every tensor exit path
     (full / delta / hybrid / hybrid-delta / fallback) plus a multi-group
     grouped-pack snapshot, then `check_determinism()`: the subprocess replay
     under a DIFFERENT PYTHONHASHSEED and adversarially REVERSED dict/set
     insertion order must reproduce the exact mode sequence AND every
     placement digest. Mode equality matters as much as digest equality —
     a replay that falls back to `full` where the parent took `delta` would
     vacuously pass the digest check without exercising the warm path.
  2. globalpack dual run — `check_globalpack` over real disruption
     candidates (churn-harness fleet after departures): the joint
     provisioning+retirement plan is digest-identical under reversed
     insertion order of its inputs.
  3. tamper sensitivity — a corrupted recorded digest makes `run_dual`
     raise `DetCheckError` naming the solve; proves the comparison is live,
     not vacuous.
  4. perturb semantics — dicts/sets come back content-equal but
     iteration-REVERSED; lists/tuples keep order (they are meaningful
     sequences); shared sub-objects keep identity via the memo; plain
     `__dict__` objects are perturbed IN PLACE (same id).
  5. digest semantics — node-name-free, order-insensitive over claims and
     per-node pod sets, sensitive to actual placement changes.
  6. off-switch parity — with the env flag unset, solve() records nothing,
     attaches nothing to the solver, and produces bit-identical results to
     the flag-on run (the recording seam never influences placement).
"""

import pytest

from helpers import make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.obs import detcheck
from karpenter_tpu.obs.detcheck import DetCheckError, perturb, results_digest
from karpenter_tpu.solver.tpu import TPUSolver
from test_domain_topology import spread
from test_solve_modes import _global_pod, _odd_pod
from test_solver import make_snapshot

ZONE = wk.ZONE_LABEL_KEY


@pytest.fixture
def detcheck_on(monkeypatch):
    monkeypatch.setenv("KARPENTER_SOLVER_DETCHECK", "1")
    detcheck._refresh()
    yield
    monkeypatch.delenv("KARPENTER_SOLVER_DETCHECK", raising=False)
    detcheck._refresh()


def _grouped_pods():
    """Pods in TWO zone-spread groups (own app selector + shared tier) — the
    lrapack merged multi-group shape, so the replay exercises grouped pack."""
    pods = []
    for g in range(2):
        labels = {"app": f"g{g}", "tier": "web"}
        tsc = [
            spread(ZONE, 1, {"matchLabels": {"app": f"g{g}"}}),
            spread(ZONE, 2, {"matchLabels": {"tier": "web"}}),
        ]
        pods += [make_pod(cpu="500m", name=f"g{g}-{i}", labels=labels, tsc=tsc) for i in range(3)]
    return pods


EXPECTED_MODES = ["full", "delta", "hybrid", "hybrid-delta", "full", "fallback"]


def _matrix_walk(solver):
    """Drive one solver through every exit path; returns the modes taken."""
    modes = []
    results = []
    snap = make_snapshot([make_pod(cpu="500m", name=f"p{i}") for i in range(5)])
    results.append(solver.solve(snap))  # full
    modes.append(solver.last_solve_mode)
    snap.pods.append(make_pod(cpu="500m", name="p5"))
    results.append(solver.solve(snap))  # delta
    modes.append(solver.last_solve_mode)
    snap.pods.append(_odd_pod())
    results.append(solver.solve(snap))  # hybrid
    modes.append(solver.last_solve_mode)
    snap.pods.append(make_pod(cpu="500m", name="p6"))
    results.append(solver.solve(snap))  # hybrid-delta
    modes.append(solver.last_solve_mode)
    results.append(solver.solve(make_snapshot(_grouped_pods())))  # grouped full
    modes.append(solver.last_solve_mode)
    snap2 = make_snapshot(
        [_global_pod()] + [make_pod(cpu="1", labels={"app": "other"}, name=f"o{i}") for i in range(2)]
    )
    results.append(solver.solve(snap2))  # fallback
    modes.append(solver.last_solve_mode)
    return modes, results


class TestDualRunMatrix:
    def test_mode_matrix_dual_run(self, detcheck_on):
        solver = TPUSolver()
        modes, _ = _matrix_walk(solver)
        assert modes == EXPECTED_MODES
        assert len(detcheck.solve_log(solver).entries) == len(EXPECTED_MODES)
        out = solver.check_determinism()
        assert out["solves"] == len(EXPECTED_MODES)
        assert out["parent_modes"] == EXPECTED_MODES
        # the replay must retrace the SAME paths, not converge via full re-encodes
        assert out["child_modes"] == EXPECTED_MODES
        assert out["hash_seed"] != ""
        # clear=True drained the log, so a second check has nothing to verify
        with pytest.raises(DetCheckError, match="no recorded solves"):
            solver.check_determinism()

    def test_tampered_digest_raises(self, detcheck_on):
        solver = TPUSolver()
        solver.solve(make_snapshot([make_pod(cpu="500m", name="t0")]))
        log = detcheck.solve_log(solver)
        assert len(log.entries) == 1
        log.entries[0]["digest"] = "0" * 64
        with pytest.raises(DetCheckError, match="diverged"):
            solver.check_determinism()

    def test_not_enabled_raises(self):
        assert not detcheck.detcheck_enabled()
        with pytest.raises(DetCheckError, match="not enabled"):
            TPUSolver().check_determinism()


class TestGlobalpackDual:
    def test_plan_digest_stable_under_reversal(self):
        from karpenter_tpu.serving.churn import ChurnHarness, ChurnSpec

        h = ChurnHarness(ChurnSpec(n_base_pods=16, n_types=4, seed=11, concurrent_seconds=0.0))
        h.build()
        try:
            h.provision_base_fleet()
            h.apply_departures(8)
            env = h.env
            env.clock.step(40)
            env.nodeclaim_disruption.reconcile()
            candidates = env.disruption.get_candidates()
            if len(candidates) < 2:
                pytest.skip("fleet too small to surface >=2 consolidation candidates")
            pools = {c.node_pool.metadata.name: c.node_pool for c in candidates}
            its = []
            for pool in pools.values():
                its.extend(env.provisioner.cloud_provider.get_instance_types(pool))
            pending = env.provisioner.get_pending_pods()
            out = detcheck.check_globalpack(
                env.provisioner.solver, candidates, its, pending_pods=pending, seed=3
            )
            assert set(out) == {"proposals", "digest"}
            assert out["proposals"] >= 0
        finally:
            h.close()


class TestPerturb:
    def test_dict_reversed_content_equal(self):
        d = {"a": 1, "b": 2, "c": 3}
        out = perturb(d)
        assert out == d
        assert list(out) == ["c", "b", "a"]

    def test_set_rebuilt_content_equal(self):
        # set iteration order is hash-determined, so the reversed REINSERTION
        # is only observable under collisions — the contract here is a fresh,
        # content-equal set (frozenset stays frozen)
        s = {10, 20, 30}
        out = perturb(s)
        assert out == s and out is not s
        fz = perturb(frozenset({"a", "b"}))
        assert fz == frozenset({"a", "b"}) and isinstance(fz, frozenset)

    def test_sequences_keep_order(self):
        # lists/tuples are meaningful sequences — reversing them would change
        # the INPUT, not just its incidental iteration order
        v = [{"x": 1, "y": 2}, ({"p": 3, "q": 4},)]
        out = perturb(v)
        assert out == v
        assert list(out[0]) == ["y", "x"]
        assert list(out[1][0]) == ["q", "p"]

    def test_shared_identity_preserved(self):
        shared = {"k": 1, "j": 2}
        out = perturb([shared, shared])
        assert out[0] is out[1]

    def test_object_dict_rotated_in_place(self):
        class Box:
            pass

        b = Box()
        b.first, b.second, b.third = 1, 2, 3
        out = perturb(b)
        assert out is b
        assert list(vars(b)) == ["third", "second", "first"]
        assert (b.first, b.second, b.third) == (1, 2, 3)


class _It:
    def __init__(self, name):
        self.name = name


class _Pod:
    def __init__(self, k):
        self._k = k

    def key(self):
        return self._k


class _Claim:
    def __init__(self, pool, its, pods):
        self.nodepool_name = pool
        self.instance_type_options = [_It(n) for n in its]
        self.pods = [_Pod(k) for k in pods]


class _Node:
    def __init__(self, name, pods):
        self._name = name
        self.pods = [_Pod(k) for k in pods]

    def name(self):
        return self._name


class _Res:
    def __init__(self, claims=(), nodes=(), errors=None, timed_out=False):
        self.new_node_claims = list(claims)
        self.existing_nodes = list(nodes)
        self.pod_errors = errors or {}
        self.timed_out = timed_out


class TestResultsDigest:
    def test_order_insensitive(self):
        a = _Res(
            claims=[_Claim("np", ["t1", "t2"], ["a", "b"]), _Claim("np", ["t3"], ["c"])],
            nodes=[_Node("n1", ["d"])],
            errors={"e1": ValueError("x"), "e2": ValueError("y")},
        )
        b = _Res(
            claims=[_Claim("np", ["t3"], ["c"]), _Claim("np", ["t2", "t1"], ["b", "a"])],
            nodes=[_Node("n1", ["d"])],
            errors={"e2": ValueError("y"), "e1": ValueError("x")},
        )
        assert results_digest(a) == results_digest(b)

    def test_node_claim_names_do_not_matter_but_placement_does(self):
        base = _Res(claims=[_Claim("np", ["t1"], ["a", "b"])])
        moved = _Res(claims=[_Claim("np", ["t1"], ["a", "c"])])
        assert results_digest(base) != results_digest(moved)
        # an empty existing node is invisible — it carries no placement
        with_empty = _Res(claims=[_Claim("np", ["t1"], ["a", "b"])], nodes=[_Node("idle", [])])
        assert results_digest(base) == results_digest(with_empty)

    def test_timeout_and_errors_are_part_of_the_contract(self):
        assert results_digest(_Res()) != results_digest(_Res(timed_out=True))
        assert results_digest(_Res()) != results_digest(_Res(errors={"p": RuntimeError("no fit")}))


class TestOffSwitch:
    def test_disabled_records_nothing(self):
        assert not detcheck.detcheck_enabled()
        solver = TPUSolver()
        solver.solve(make_snapshot([make_pod(cpu="500m", name="q0")]))
        assert getattr(solver, "_detcheck_log", None) is None

    def test_recording_never_changes_placement(self, detcheck_on):
        pods = lambda: [make_pod(cpu="500m", name=f"r{i}") for i in range(4)]  # noqa: E731
        on = TPUSolver()
        r_on = on.solve(make_snapshot(pods()))
        detcheck.solve_log(on).entries.clear()
        detcheck._refresh()  # still on; explicit off below
        import os

        os.environ.pop("KARPENTER_SOLVER_DETCHECK", None)
        detcheck._refresh()
        try:
            off = TPUSolver()
            r_off = off.solve(make_snapshot(pods()))
        finally:
            os.environ["KARPENTER_SOLVER_DETCHECK"] = "1"
            detcheck._refresh()
        assert results_digest(r_on) == results_digest(r_off)
