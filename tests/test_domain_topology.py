"""Keyed-domain topology: arbitrary spread topology keys, minDomains, and
domain-keyed required anti-affinity on the tensor path.

Reference behaviors: topology.go buildDomainGroups/countDomains (domain
universes from NodePool x InstanceType requirements + existing nodes),
topologygroup.go nextDomainTopologySpread (za-masked minimum, minDomains
force-zero) and nextDomainAntiAffinity (count==0 domains only).
"""

import pytest

from helpers import make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube import Store, TopologySpreadConstraint
from karpenter_tpu.kube.objects import PodAffinityTerm
from karpenter_tpu.solver import FFDSolver, SolverSnapshot
from karpenter_tpu.solver.encode import check_capability, encode
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]

RACK_KEY = "example.com/rack"


def spread(key, max_skew=1, selector=None, min_domains=None, **kw):
    return TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key, label_selector=selector, min_domains=min_domains, **kw
    )


def anti(selector, key):
    return PodAffinityTerm(label_selector=selector, topology_key=key)


def make_snapshot(pods, node_pools=None, types=None):
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    node_pools = node_pools or [make_nodepool(requirements=LINUX_AMD64)]
    for np_ in node_pools:
        store.create(np_)
    types = types if types is not None else catalog.construct_instance_types()
    return SolverSnapshot(
        store=store,
        cluster=cluster,
        node_pools=node_pools,
        instance_types={np_.metadata.name: types for np_ in node_pools},
        state_nodes=cluster.nodes(),
        daemonset_pods=[],
        pods=pods,
        clock=clock,
    )


def solve_both(pods, node_pools=None, types=None):
    ffd = FFDSolver().solve(make_snapshot(pods, node_pools, types))
    snap = make_snapshot(pods, node_pools, types)
    tpu = TPUSolver(force=True)
    res = tpu.solve(snap)
    assert tpu.last_backend == "tpu"
    assert set(res.pod_errors) == set(ffd.pod_errors), (res.pod_errors, ffd.pod_errors)
    violations = validate_results(snap, res)
    assert not violations, violations
    return res, ffd


class TestCapacityTypeSpread:
    def test_spread_over_capacity_type_in_window(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, 1, sel)])
            for _ in range(10)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        # every claim committed to a single capacity type, and the split is
        # balanced within maxSkew
        cts = {}
        for nc in res.new_node_claims:
            r = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
            assert len(r.values) == 1, "capacity-type spread member must commit its domain"
            cts[r.any()] = cts.get(r.any(), 0) + len(nc.pods)
        assert cts and max(cts.values()) - min(cts.values()) <= 1, cts

    def test_capacity_type_spread_with_zone_selector(self):
        # a zone selector under the DEFAULT Honor affinity policy filters
        # which nodes count toward the capacity-type spread — that filter
        # lives on a different key than the spread's domain axis, so the
        # snapshot is host-only...
        sel = {"matchLabels": {"app": "w"}}

        def pods_with(policy_kw):
            return [
                make_pod(
                    cpu="1",
                    labels={"app": "w"},
                    node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"},
                    tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, 1, sel, **policy_kw)],
                )
                for _ in range(8)
            ]

        reasons = check_capability(make_snapshot(pods_with({})))
        assert any("node-filtered spread" in r for r in reasons), reasons

        # ...while an explicit Ignore policy removes the node filter and the
        # tensor path handles it, pinning zones via the selector alone
        pods = pods_with({"node_affinity_policy": "Ignore"})
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        for nc in res.new_node_claims:
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert set(zr.values) <= {"test-zone-a"}


class TestCustomKeySpread:
    def two_rack_pools(self):
        reqs_a = LINUX_AMD64 + [{"key": RACK_KEY, "operator": "In", "values": ["r1"]}]
        reqs_b = LINUX_AMD64 + [{"key": RACK_KEY, "operator": "In", "values": ["r2"]}]
        return [
            make_nodepool(name="rack-1", requirements=reqs_a),
            make_nodepool(name="rack-2", requirements=reqs_b),
        ]

    def test_custom_key_spread_across_pools(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(RACK_KEY, 1, sel)]) for _ in range(9)]
        snap = make_snapshot(pods, self.two_rack_pools())
        assert check_capability(snap) == []
        res, _ = solve_both(pods, self.two_rack_pools())
        racks = {}
        for nc in res.new_node_claims:
            r = nc.requirements.get(RACK_KEY)
            assert len(r.values) == 1
            racks[r.any()] = racks.get(r.any(), 0) + len(nc.pods)
        assert set(racks) == {"r1", "r2"}
        assert max(racks.values()) - min(racks.values()) <= 1

    def test_multi_value_template_requirement_provides_domains(self):
        # ONE pool whose template carries rack In [r1, r2]: domains come from
        # the template requirement (buildDomainGroups), commitment pins racks
        reqs = LINUX_AMD64 + [{"key": RACK_KEY, "operator": "In", "values": ["r1", "r2"]}]
        pools = [make_nodepool(requirements=reqs)]
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(RACK_KEY, 1, sel)]) for _ in range(6)]
        snap = make_snapshot(pods, pools)
        assert check_capability(snap) == []
        res, _ = solve_both(pods, pools)
        racks = {nc.requirements.get(RACK_KEY).any() for nc in res.new_node_claims if nc.pods}
        assert racks == {"r1", "r2"}

    def test_unconstrained_template_cannot_serve_custom_spread(self):
        # the pool knows nothing about rack: no domains exist, members cannot
        # schedule (host: nextDomain over an empty universe)
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(RACK_KEY, 1, sel)]) for _ in range(3)]
        res, ffd = solve_both(pods)
        assert len(res.pod_errors) == 3
        assert set(res.pod_errors) == set(ffd.pod_errors)

    def test_two_keys_on_different_deployments(self):
        # one snapshot, two deployments spreading over DIFFERENT keys — each
        # item commits its own key; no pod uses two keys, so all in-window
        sel_a = {"matchLabels": {"app": "a"}}
        sel_b = {"matchLabels": {"app": "b"}}
        pods = [make_pod(cpu="1", labels={"app": "a"}, tsc=[zone_spread(1, sel_a)]) for _ in range(6)] + [
            make_pod(cpu="500m", labels={"app": "b"}, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, 1, sel_b)])
            for _ in range(6)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        solve_both(pods)

    def test_pod_with_two_dom_keys_falls_back(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "w"},
                tsc=[zone_spread(1, sel), spread(wk.CAPACITY_TYPE_LABEL_KEY, 1, sel)],
            )
        ]
        reasons = check_capability(make_snapshot(pods))
        assert any("multiple domain keys" in r for r in reasons), reasons


class TestRegisteredUniverse:
    def test_pool_zone_restriction_narrows_registered_universe(self):
        # NodePool requires zone In [a]; its ITs advertise zones a-d. The
        # pool's base requirements NARROW the instance domains
        # (buildDomainGroups: "zones from an instance type don't expand the
        # universe of valid domains") — phantom empty zones must not pin the
        # spread minimum at zero
        reqs = LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}]
        pools = [make_nodepool(requirements=reqs)]
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(6)]
        res, ffd = solve_both(pods, pools)
        assert not ffd.pod_errors
        assert not res.pod_errors

    def test_advertised_unlaunchable_zone_pins_minimum(self):
        # the converse: the pool does NOT restrict zones, one IT advertises a
        # zone no offering can launch in — the registered-but-empty domain
        # pins the minimum at zero and caps every zone at maxSkew, exactly
        # like the host (reference domainMinCount over empty domains)
        from karpenter_tpu.cloudprovider.types import InstanceType

        it = catalog.make_instance_type("c", 8, zones=["test-zone-a", "test-zone-b"])
        from karpenter_tpu.scheduling.requirements import Requirement

        it.requirements.replace(
            Requirement(wk.ZONE_LABEL_KEY, "In", ["test-zone-a", "test-zone-b", "test-zone-ghost"])
        )
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(6)]
        ffd = FFDSolver().solve(make_snapshot(pods, types=[it]))
        snap = make_snapshot(pods, types=[it])
        tpu = TPUSolver(force=True)
        res = tpu.solve(snap)
        assert tpu.last_backend == "tpu"
        assert not validate_results(snap, res)
        # zones a,b take one pod each (min pinned 0 by the ghost domain); the
        # rest stay pending. The host may additionally waste placements
        # committing pods to the unlaunchable ghost domain (count-0
        # tie-breaking follows domain-set iteration order, as in the
        # reference), so it schedules AT MOST as many pods as the
        # availability-aware kernel — anywhere from 0 to 2
        assert len(res.pod_errors) == 4
        assert 4 <= len(ffd.pod_errors) <= 6


class TestMinDomains:
    def test_min_domains_unmet_forces_zero_min(self):
        # 4 zones available but minDomains=6: the global minimum is treated as
        # zero, so no domain may exceed maxSkew — with maxSkew=2 and 9 pods,
        # the FFD leaves one pod unschedulable (4 domains x 2 = 8 slots)
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(wk.ZONE_LABEL_KEY, 2, sel, min_domains=6)])
            for _ in range(9)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, ffd = solve_both(pods)
        assert len(ffd.pod_errors) == 1
        assert len(res.pod_errors) == 1

    def test_min_domains_met_is_plain_spread(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(wk.ZONE_LABEL_KEY, 1, sel, min_domains=3)])
            for _ in range(8)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        assert not res.pod_errors

    def test_min_domains_hostname_is_noop(self):
        # hostname domains are unbounded (a new claim is always a fresh
        # domain): minDomains never forces the zero minimum (host
        # _domain_min_count returns 0 for hostname regardless)
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(wk.HOSTNAME_LABEL_KEY, 1, sel, min_domains=50)])
            for _ in range(4)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        assert not res.pod_errors


class TestDomainAntiAffinity:
    def test_unpinned_zone_anti_schedules_one_per_batch(self):
        # reference late-committal semantics (topology_test.go "should support
        # pod anti-affinity with a zone topology"): an unpinned self-anti
        # replica set schedules exactly ONE pod per solve — the placed pod's
        # claim could land in any zone, so it blocks them all
        sel = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(cpu="1", labels={"app": "db"}, anti_affinity=[anti(sel, wk.ZONE_LABEL_KEY)])
            for _ in range(4)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, ffd = solve_both(pods)
        assert len(res.pod_errors) == 3
        assert sum(len(nc.pods) for nc in res.new_node_claims) == 1

    def test_zone_pinned_anti_replicas_all_schedule(self):
        # selector-pinned replicas consume exactly their pinned zone, so a
        # full set schedules in one solve (reference "should not violate pod
        # anti-affinity on zone" — with the declaring side symmetric)
        sel = {"matchLabels": {"app": "db"}}
        zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "db"},
                node_selector={wk.ZONE_LABEL_KEY: z},
                anti_affinity=[anti(sel, wk.ZONE_LABEL_KEY)],
            )
            for z in zones
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        assert not res.pod_errors
        placed = sorted(nc.requirements.get(wk.ZONE_LABEL_KEY).any() for nc in res.new_node_claims if nc.pods)
        assert placed == zones

    def test_pinned_overflow_is_unschedulable(self):
        # two replicas pinned to the SAME zone: the second violates and stays
        # pending, parity with the FFD
        sel = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "db"},
                node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"},
                anti_affinity=[anti(sel, wk.ZONE_LABEL_KEY)],
            )
            for _ in range(2)
        ]
        res, ffd = solve_both(pods)
        assert len(res.pod_errors) == 1
        assert set(res.pod_errors) == set(ffd.pod_errors)

    def test_capacity_type_anti_affinity_blocks_possible_set(self):
        sel = {"matchLabels": {"app": "q"}}
        pods = [
            make_pod(cpu="1", labels={"app": "q"}, anti_affinity=[anti(sel, wk.CAPACITY_TYPE_LABEL_KEY)])
            for _ in range(2)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, ffd = solve_both(pods)
        # the first unpinned pod blocks both capacity types
        assert len(res.pod_errors) == 1

    def test_zone_anti_respects_running_pods(self):
        # a running matched pod occupies test-zone-a: the new replicas must
        # avoid it (counts_dom_init feeds the domain caps)
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        sel = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(cpu="1", labels={"app": "db"}, anti_affinity=[anti(sel, wk.ZONE_LABEL_KEY)])
            for _ in range(2)
        ]

        def snap():
            store = Store()
            clock = FakeClock()
            cluster = Cluster(store, clock)
            start_informers(store, cluster)
            np_ = make_nodepool(requirements=LINUX_AMD64)
            store.create(np_)
            nc = NodeClaim(metadata=ObjectMeta(name="c1", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
            nc.status.provider_id = "kwok://n1"
            nc.status.conditions.set_true(COND_REGISTERED)
            nc.status.conditions.set_true(COND_INITIALIZED)
            store.create(nc)
            store.create(
                Node(
                    metadata=ObjectMeta(
                        name="n1",
                        labels={
                            wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                            wk.HOSTNAME_LABEL_KEY: "n1",
                            wk.ZONE_LABEL_KEY: "test-zone-a",
                        },
                    ),
                    spec=NodeSpec(provider_id="kwok://n1"),
                    status=NodeStatus(
                        capacity=parse_resource_list({"cpu": "8", "memory": "32Gi", "pods": "110"}),
                        allocatable=parse_resource_list({"cpu": "8", "memory": "32Gi", "pods": "110"}),
                    ),
                )
            )
            running = make_pod(name="r0", cpu="100m", labels={"app": "db"}, node_name="n1")
            store.create(running)
            return SolverSnapshot(
                store=store,
                cluster=cluster,
                node_pools=[np_],
                instance_types={np_.metadata.name: catalog.construct_instance_types()},
                state_nodes=cluster.nodes(),
                daemonset_pods=[],
                pods=pods,
                clock=clock,
            )

        ffd_res = FFDSolver().solve(snap())
        tpu = TPUSolver(force=True)
        res = tpu.solve(snap())
        assert tpu.last_backend == "tpu"
        assert set(res.pod_errors) == set(ffd_res.pod_errors)
        # the running matched pod blocks test-zone-a; the first new pod takes
        # (and blocks) the remaining zones, leaving the second pending
        assert len(res.pod_errors) == 1
        for nc in res.new_node_claims:
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert "test-zone-a" not in zr.values

    def test_asymmetric_anti_affinity_falls_back(self):
        # the declarer does not match its own selector: the symmetric group
        # model would over-constrain the matched pods — host path only
        sel = {"matchLabels": {"app": "other"}}
        pods = [make_pod(cpu="1", labels={"app": "me"}, anti_affinity=[anti(sel, wk.ZONE_LABEL_KEY)])] + [
            make_pod(cpu="1", labels={"app": "other"}) for _ in range(2)
        ]
        reasons = check_capability(make_snapshot(pods))
        assert any("asymmetric anti-affinity" in r for r in reasons), reasons
        # the plain solver falls back; the host handles the inverse semantics
        # (the declarer's uncommitted claim blocks the matched pods)
        tpu = TPUSolver()
        res = tpu.solve(make_snapshot(pods))
        assert tpu.last_backend == "ffd-fallback"

    def test_hostname_asymmetric_also_falls_back(self):
        sel = {"matchLabels": {"app": "other"}}
        pods = [make_pod(cpu="1", labels={"app": "me"}, anti_affinity=[anti(sel, wk.HOSTNAME_LABEL_KEY)])] + [
            make_pod(cpu="1", labels={"app": "other"})
        ]
        reasons = check_capability(make_snapshot(pods))
        assert any("asymmetric anti-affinity" in r for r in reasons), reasons


class TestNodeFilteredSpreadWindow:
    def test_zone_selector_with_zone_spread_stays_in_window(self):
        # the effective Honor filter only constrains the spread's own key:
        # the za mask IS the filter
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "w"},
                node_selector={wk.ZONE_LABEL_KEY: "test-zone-a"},
                tsc=[zone_spread(1, sel)],
            )
            for _ in range(3)
        ]
        assert check_capability(make_snapshot(pods)) == []

    def test_non_key_selector_with_default_honor_falls_back(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "w"},
                node_selector={wk.ARCH_LABEL_KEY: "amd64"},
                tsc=[zone_spread(1, sel)],
            )
        ]
        reasons = check_capability(make_snapshot(pods))
        assert any("node-filtered spread" in r for r in reasons), reasons

    def test_non_key_selector_with_explicit_ignore_stays_in_window(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(
                cpu="1",
                labels={"app": "w"},
                node_selector={wk.ARCH_LABEL_KEY: "amd64"},
                tsc=[spread(wk.ZONE_LABEL_KEY, 1, sel, node_affinity_policy="Ignore")],
            )
            for _ in range(3)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        res, _ = solve_both(pods)
        assert not res.pod_errors

    def test_taint_policy_honor_falls_back(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[spread(wk.ZONE_LABEL_KEY, 1, sel, node_taints_policy="Honor")])
        ]
        reasons = check_capability(make_snapshot(pods))
        assert any("taint policy" in r for r in reasons), reasons


@pytest.mark.heavy
class TestShardedDomainEquivalence:
    def test_capacity_type_workload_sharded_equivalent(self):
        import jax

        from karpenter_tpu.models.scheduler_model import make_tensors
        from karpenter_tpu.models.scheduler_model_grouped import build_items, make_item_tensors
        from karpenter_tpu.parallel.sharded import assert_sharded_equivalent, make_mesh

        sel_a = {"matchLabels": {"app": "a"}}
        sel_b = {"matchLabels": {"app": "b"}}
        pods = [make_pod(cpu="1", labels={"app": "a"}, tsc=[zone_spread(1, sel_a)]) for _ in range(9)] + [
            make_pod(cpu="500m", labels={"app": "b"}, tsc=[spread(wk.CAPACITY_TYPE_LABEL_KEY, 1, sel_b)])
            for _ in range(7)
        ]
        snap = make_snapshot(pods)
        enc = encode(snap)
        assert not enc.fallback_reasons
        t = make_tensors(enc, with_pods=False)
        item_arrays, _ = build_items(enc)
        items = make_item_tensors(item_arrays)
        mesh = make_mesh(jax.devices()[:4])
        assert_sharded_equivalent(t, items, mesh)  # raises on divergence
