"""Tensor-native consolidation parity suite (ISSUE 9).

The relaxed-LP repack (`solver/consolidation.propose_subsets_lp`) and the
masked sub-encode simulations (`solver/simulate.ConsolidationSimulator`) are
both RELAXATIONS riding exact hosts: every contract here pins that the fast
path can only cost optimality, never correctness —

  * every LP-proposed command the method emits passed exact host validation,
  * LP savings >= annealed savings on randomized fleets (both exact-validated),
  * masked-simulation placements bit-identical to from-scratch
    `simulate_scheduling` (incl. randomized batches),
  * the correctness-envelope guards route topology/anti-affinity fleets to
    the from-scratch path,
  * `KARPENTER_CONSOLIDATE_LP=0` restores binary-search behavior exactly,
  * repeated consolidation rounds record ZERO warm recompiles on the LP
    kernels (sentinel-verified).
"""

import random

import numpy as np
import pytest

from helpers import make_nodepool, make_pod, hostname_anti_affinity, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling
from karpenter_tpu.controllers.disruption.methods import (
    MultiNodeConsolidation,
    _command_savings_per_hour,
)
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.solver.simulate import ConsolidationSimulator

from test_consolidation_tpu import OD_ONLY, build_fleet


def canon_results(r):
    """Placement canon: existing-node assignments, claims as (pods, types),
    errors. Claim slot hostnames are transient (`tpu-slot-N`) and the slot
    numbering legitimately shifts between masked and from-scratch encodes,
    so they are deliberately NOT part of the canon."""
    ex = sorted(
        (en.state_node.name(), sorted(p.key() for p in en.pods))
        for en in r.existing_nodes
        if en.pods
    )
    claims = sorted(
        (
            tuple(sorted(p.key() for p in nc.pods)),
            tuple(sorted(it.name for it in nc.instance_type_options)),
        )
        for nc in r.new_node_claims
    )
    return (ex, claims, dict(r.pod_errors))


def consolidation_method(env):
    ctx = env.disruption.ctx
    ctx.round_candidates = env.disruption.get_candidates()
    ctx.node_pool_totals = None
    return MultiNodeConsolidation(ctx), ctx.round_candidates


def flip_consolidatable(env):
    env.clock.step(40)
    env.nodeclaim_disruption.reconcile()


class TestMaskedSimulationParity:
    @pytest.fixture(scope="class")
    def fleet(self):
        env = build_fleet(6, solver_backend="tpu")
        flip_consolidatable(env)
        return env

    def test_batches_bit_identical_to_scratch(self, fleet):
        cands = fleet.disruption.get_candidates()
        assert len(cands) == 6
        sim = ConsolidationSimulator(fleet.provisioner, fleet.cluster, fleet.clock, cands)
        rng = random.Random(7)
        batches = [cands[:2], cands[:4], cands, [cands[1], cands[3], cands[5]]]
        batches += [rng.sample(cands, rng.randrange(2, 6)) for _ in range(4)]
        for batch in batches:
            masked = sim.simulate(batch)
            assert sim.last_mode == "masked", sim.why_scratch
            scratch = simulate_scheduling(fleet.provisioner, fleet.cluster, batch, fleet.clock)
            assert canon_results(masked) == canon_results(scratch)
        assert sim.masked_probes == len(batches)

    def test_masked_results_never_reference_deleted_nodes(self, fleet):
        cands = fleet.disruption.get_candidates()
        sim = ConsolidationSimulator(fleet.provisioner, fleet.cluster, fleet.clock, cands)
        batch = cands[:3]
        names = {c.name() for c in batch}
        r = sim.simulate(batch)
        assert sim.last_mode == "masked"
        assert not any(en.state_node.name() in names for en in r.existing_nodes)

    def test_provisioning_warm_state_survives_a_round(self, fleet):
        """solve_prepared restores the resident carry + hybrid state: a
        consolidation round must not trash the live provisioning warm path."""
        solver = fleet.provisioner.solver
        before_resident = solver._resident
        cands = fleet.disruption.get_candidates()
        sim = ConsolidationSimulator(fleet.provisioner, fleet.cluster, fleet.clock, cands)
        r = sim.simulate(cands[:3])
        assert sim.last_mode == "masked"
        assert solver._resident is before_resident

    def test_encodecache_untouched_by_masked_probes(self, fleet):
        solver = fleet.provisioner.solver
        before = (solver.encode_cache.last_enc, solver.encode_cache.row_key)
        cands = fleet.disruption.get_candidates()
        sim = ConsolidationSimulator(fleet.provisioner, fleet.cluster, fleet.clock, cands)
        sim.simulate(cands[:2])
        assert sim.last_mode == "masked"
        assert (solver.encode_cache.last_enc, solver.encode_cache.row_key) == before


class TestSimulatorGuards:
    def test_spread_fleet_rides_masked_with_probe_counts(self):
        """Topology groups are probe-dependent (bound-pod counts differ per
        surviving set). The per-node count decomposition (ISSUE 16, paying
        PR 9's named debt) hands every probe the exact from-scratch group
        counts/registries, so spread fleets now ride the masked path —
        bit-identical to `simulate_scheduling`."""
        env = Environment(options=Options(solver_backend="tpu"))
        np_ = make_nodepool(requirements=OD_ONLY)
        np_.spec.disruption.consolidate_after = "30s"
        np_.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np_)
        sel = {"matchLabels": {"app": "x"}}
        for i in range(4):
            env.store.create(
                make_pod(cpu="500m", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        env.settle()
        for i in range(4):
            env.store.delete("Pod", f"s{i}")
        spread_sel = {"matchLabels": {"app": "w"}}
        for i in range(4):
            env.store.create(
                make_pod(cpu="250m", name=f"w{i}", labels={"app": "w"}, tsc=[zone_spread(selector=spread_sel)])
            )
        env.settle(rounds=4)
        flip_consolidatable(env)
        cands = env.disruption.get_candidates()
        # batches need at least one reschedulable pod (an all-empty batch has
        # nothing to simulate and correctly short-circuits to scratch), and
        # which candidates host the spread pods varies with interning order
        withpods = [c for c in cands if c.reschedulable_pods]
        empties = [c for c in cands if not c.reschedulable_pods]
        assert len(withpods) >= 2
        sim = ConsolidationSimulator(env.provisioner, env.cluster, env.clock, cands)
        batches = [withpods[:2], withpods[1:], cands]
        if empties:
            batches.append([withpods[0], empties[0]])
        for batch in batches:
            r = sim.simulate(batch)
            assert sim.last_mode == "masked", sim.why_scratch
            scratch = simulate_scheduling(env.provisioner, env.cluster, batch, env.clock)
            assert canon_results(r) == canon_results(scratch)
        assert sim.masked_probes == len(batches)

    def test_anti_affinity_candidate_pods_ride_masked(self):
        # the anti-affinity pods ARE the workload (no swap): evicting one
        # makes it a running inverse-anti blocker of another probe — the
        # per-candidate inverse-entry decomposition lowers exactly the
        # surviving candidates' blockers per probe
        env2 = Environment(options=Options(solver_backend="tpu"))
        np_ = make_nodepool(requirements=OD_ONLY)
        np_.spec.disruption.consolidate_after = "30s"
        np_.spec.disruption.budgets = [Budget(nodes="100%")]
        env2.store.create(np_)
        sel = {"matchLabels": {"app": "x"}}
        for i in range(4):
            env2.store.create(
                make_pod(cpu="250m", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        env2.settle()
        flip_consolidatable(env2)
        cands = env2.disruption.get_candidates()
        if len(cands) < 2:
            pytest.skip("anti-affinity fleet produced too few candidates")
        sim = ConsolidationSimulator(env2.provisioner, env2.cluster, env2.clock, cands)
        for batch in (cands[:2], [cands[0]], cands):
            r = sim.simulate(batch)
            assert sim.last_mode == "masked", sim.why_scratch
            scratch = simulate_scheduling(env2.provisioner, env2.cluster, batch, env2.clock)
            assert canon_results(r) == canon_results(scratch)

    def test_hostname_spread_routes_to_scratch(self):
        """The one topology family still outside the envelope: a blocked row
        is an extra zero-count hostname domain the from-scratch probe never
        sees, which skews the spread minimum — refuse, and the from-scratch
        path serves the probe identically either way."""
        env = Environment(options=Options(solver_backend="tpu"))
        np_ = make_nodepool(requirements=OD_ONLY)
        np_.spec.disruption.consolidate_after = "30s"
        np_.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np_)
        sel = {"matchLabels": {"app": "x"}}
        for i in range(4):
            env.store.create(
                make_pod(cpu="500m", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        env.settle()
        for i in range(4):
            env.store.delete("Pod", f"s{i}")
        host_tsc = zone_spread(selector={"matchLabels": {"app": "w"}})
        host_tsc.topology_key = wk.HOSTNAME_LABEL_KEY
        for i in range(4):
            env.store.create(make_pod(cpu="250m", name=f"w{i}", labels={"app": "w"}, tsc=[host_tsc]))
        env.settle(rounds=4)
        flip_consolidatable(env)
        cands = env.disruption.get_candidates()
        assert len(cands) >= 2
        sim = ConsolidationSimulator(env.provisioner, env.cluster, env.clock, cands)
        r = sim.simulate(cands[:2])
        assert sim.last_mode == "scratch"
        assert "hostname spread" in sim.why_scratch
        scratch = simulate_scheduling(env.provisioner, env.cluster, cands[:2], env.clock)
        assert canon_results(r) == canon_results(scratch)

    def test_ffd_backend_routes_to_scratch(self):
        env = build_fleet(4, solver_backend="ffd")
        flip_consolidatable(env)
        cands = env.disruption.get_candidates()
        sim = ConsolidationSimulator(env.provisioner, env.cluster, env.clock, cands)
        r = sim.simulate(cands[:2])
        assert sim.last_mode == "scratch"
        assert "tensor path" in sim.why_scratch
        assert r is not None


class TestLPCommands:
    def test_every_emitted_command_passed_exact_validation(self, monkeypatch):
        """The method's LP arm only returns a command compute_consolidation
        accepted — re-run the exact from-scratch simulation on the emitted
        candidate set and require the same verdict."""
        env = build_fleet(6, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        deadline = env.clock.now() + 60.0
        cmd = m._lp_option(cands, deadline)
        assert cmd.candidates, "LP found no command on an idle fleet"
        results = simulate_scheduling(env.provisioner, env.cluster, cmd.candidates, env.clock)
        from karpenter_tpu.controllers.disruption.helpers import all_non_pending_scheduled

        assert all_non_pending_scheduled(results, cmd.candidates)
        assert len(results.new_node_claims) <= 1
        assert _command_savings_per_hour(cmd) > 0

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_lp_savings_at_least_anneal_randomized(self, seed):
        """Randomized underutilized fleets: the LP's exact-validated best
        command must save at least what the annealed search's does."""
        rng = random.Random(seed)
        n = rng.randrange(4, 8)
        env = build_fleet(n, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        deadline = env.clock.now() + 60.0
        lp_cmd = m._lp_option(cands, deadline)
        anneal_cmd = m._annealed_option(cands, deadline)
        lp_savings = _command_savings_per_hour(lp_cmd)
        anneal_savings = _command_savings_per_hour(anneal_cmd)
        assert lp_savings >= anneal_savings - 1e-9, (n, lp_savings, anneal_savings)
        assert lp_savings > 0

    def test_escape_hatch_binary_search_parity(self, monkeypatch):
        """KARPENTER_CONSOLIDATE_LP=0: the method must run EXACTLY the
        reference's binary search — no LP, no anneal — and emit its verdict
        verbatim (on this fleet the prefix binary search legitimately finds
        nothing where the LP finds a command: the non-monotone validity the
        relaxation was built to escape)."""
        env = build_fleet(5, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        # the LP arm DOES find a command on this fleet
        assert m._lp_option(cands, env.clock.now() + 60.0).candidates
        eligible = m.sort_candidates([c for c in cands if m.should_disrupt(c)])
        reference = m._first_n_consolidation_option(list(eligible))

        captured = {}
        orig = MultiNodeConsolidation._first_n_consolidation_option

        def spy(self, candidates, deadline=None):
            cmd = orig(self, candidates, deadline)
            captured["cmd"] = cmd
            return cmd

        monkeypatch.setattr(MultiNodeConsolidation, "_first_n_consolidation_option", spy)
        monkeypatch.setattr(MultiNodeConsolidation, "_lp_option", None)  # must not be called
        monkeypatch.setattr(MultiNodeConsolidation, "_annealed_option", None)
        monkeypatch.setenv("KARPENTER_CONSOLIDATE_LP", "0")
        budgets = {env.store.list("NodePool")[0].metadata.name: 100}
        m2, cands2 = consolidation_method(env)
        cmds = m2.compute_commands(cands2, budgets)
        assert "cmd" in captured, "binary search did not run under the escape hatch"
        assert captured["cmd"].candidate_names() == reference.candidate_names()
        assert abs(_command_savings_per_hour(captured["cmd"]) - _command_savings_per_hour(reference)) < 1e-9
        if not reference.candidates:
            assert cmds == []

    def test_consolidation_metrics_emitted(self):
        from karpenter_tpu import metrics as mm

        env = build_fleet(4, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        cmd = m._lp_option(cands, env.clock.now() + 60.0)
        assert cmd.candidates
        reg = env.disruption.ctx.metrics
        assert reg.counter(mm.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).value(proposer="lp") > 0
        assert reg.counter(mm.SOLVER_CONSOLIDATION_VALIDATION_TOTAL).total() > 0
        assert reg.counter(mm.SOLVER_CONSOLIDATION_LP_ITERATIONS_TOTAL).total() > 0
        assert reg.gauge(mm.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).value(proposer="lp") > 0


class TestZeroWarmRecompiles:
    def test_repeated_rounds_record_zero_lp_recompiles(self):
        """Shape bucketing holds across rounds on a stable fleet: the second
        LP round must not grow any watched jit cache (sentinel-verified) —
        the churn loop's zero-steady-state-recompiles contract."""
        from karpenter_tpu.obs.trace import sentinel

        env = build_fleet(5, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        m._lp_option(cands, env.clock.now() + 60.0)  # warm: compiles allowed
        before = sentinel().snapshot()
        for _ in range(2):
            cmd = m._lp_option(cands, env.clock.now() + 60.0)
            assert cmd.candidates
        delta = sentinel().delta(before)
        assert not delta, f"warm consolidation rounds recompiled: {delta}"

    def test_consolidate_trace_records_phases(self):
        env = build_fleet(4, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        rec = env.provisioner.solver.recorder
        m._lp_option(cands, env.clock.now() + 60.0)
        traces = [t for t in rec.traces() if t.mode == "consolidate"]
        assert traces, "no consolidation flight record"
        t = traces[-1]
        assert t.backend == "lp"
        for phase in ("encode_candidates", "lp_repack", "round", "validate"):
            assert phase in t.phase_totals, (phase, t.phase_totals)
        assert t.attribution.get("sim_masked", 0) >= 1
