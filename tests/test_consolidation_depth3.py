"""Consolidation depth specs, second tranche, ported from the reference's
consolidation_test.go: multi-NodeClaim merges with mixed capacity types,
topology consideration (anti-affinity blocking deletes), consolidateAfter
candidacy, reserved-offering consolidation, preference-policy interplay,
minValues non-relaxation, and buffer-pod interplay."""

import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from test_disruption import LINUX_AMD64, OD_ONLY, make_env, provision, run_disruption
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
from karpenter_tpu.kube import Node, ObjectMeta
from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.resources import parse_resource_list


def manual_node(env, name, it_name, cpu, ct=wk.CAPACITY_TYPE_ON_DEMAND, zone="test-zone-a", extra_labels=None):
    """A registered+initialized NodeClaim/Node pair pinned to a concrete
    instance type/offering (the reference's test.NodeClaimsAndNodes +
    ExpectMakeNodesInitialized)."""
    np_name = env.store.list("NodePool")[0].metadata.name
    labels = {
        wk.NODEPOOL_LABEL_KEY: np_name,
        wk.HOSTNAME_LABEL_KEY: name,
        wk.INSTANCE_TYPE_LABEL_KEY: it_name,
        wk.CAPACITY_TYPE_LABEL_KEY: ct,
        wk.ZONE_LABEL_KEY: zone,
    }
    labels.update(extra_labels or {})
    nc = NodeClaim(
        metadata=ObjectMeta(name=f"nc-{name}", labels=dict(labels), finalizers=[wk.TERMINATION_FINALIZER])
    )
    nc.status.provider_id = f"kwok://{name}"
    nc.status.conditions.set_true(COND_REGISTERED)
    nc.status.conditions.set_true(COND_INITIALIZED)
    env.store.create(nc)
    rl = parse_resource_list({"cpu": cpu, "memory": "128Gi", "pods": "110"})
    env.store.create(
        Node(
            metadata=ObjectMeta(name=name, labels=dict(labels), finalizers=[wk.TERMINATION_FINALIZER]),
            spec=NodeSpec(provider_id=f"kwok://{name}"),
            status=NodeStatus(capacity=rl, allocatable=rl),
        )
    )
    return name


def settle_consolidatable(env, rounds=3):
    env.clock.step(40)
    for _ in range(rounds):
        env.tick(provision_force=False)
    env.nodeclaim_disruption.reconcile()


class TestMultiNodeClaimDepth:
    def test_merge_mixed_spot_and_on_demand_into_one(self):
        # consolidation_test.go:4030 — three oversized nodes (two OD, one
        # spot) with one small pod each merge into a single cheaper node
        env = make_env()
        for i, ct in enumerate([wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_SPOT]):
            manual_node(env, f"big-{i}", "c-32x-amd64-linux", "32", ct=ct)
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"p{i}", node_name=f"big-{i}"))
        env.settle(rounds=4)
        run_disruption(env, rounds=14)
        nodes = env.store.list("Node")
        assert len(nodes) == 1, [n.metadata.labels.get(wk.INSTANCE_TYPE_LABEL_KEY) for n in nodes]
        assert all(p.spec.node_name == nodes[0].metadata.name for p in env.store.list("Pod"))

    def test_wont_merge_two_nodes_into_one_of_same_type(self):
        # consolidation_test.go:4657 table — two nodes of the CHEAPEST type
        # cannot merge into one of the same type (no savings)
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        sel = {"matchLabels": {"app": "x"}}
        pods = [
            make_pod(cpu="1", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
            for i in range(2)
        ]
        provision(env, pods)
        # drop the anti-affinity blocker by replacing pods with plain ones on
        # the same nodes — each node still right-sized for its pod
        for i in range(2):
            node = env.store.get("Pod", f"s{i}").spec.node_name
            env.store.delete("Pod", f"s{i}")
            env.store.create(make_pod(cpu="1", name=f"r{i}", node_name=node))
        env.settle(rounds=3)
        n_before = env.store.count("Node")
        # merging 2x cpu-1 pods needs a >=2cpu node; when that is not cheaper
        # than the two right-sized singles, the command must not fire
        run_disruption(env, rounds=10)
        assert env.store.count("Node") <= n_before

    def test_merge_respects_do_not_disrupt_member(self):
        # a do-not-disrupt pod pins its node; only the other candidates merge
        env = make_env()
        for i in range(2):
            manual_node(env, f"big-{i}", "c-32x-amd64-linux", "32")
        env.store.create(make_pod(cpu="500m", name="free", node_name="big-0"))
        env.store.create(
            make_pod(
                cpu="500m",
                name="pinned",
                node_name="big-1",
                annotations={wk.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"},
            )
        )
        env.settle(rounds=4)
        run_disruption(env, rounds=12)
        assert env.store.try_get("Node", "big-1") is not None, "do-not-disrupt node must survive"
        assert env.store.try_get("Node", "big-0") is None, "free node should consolidate away"


class TestTopologyConsideration:
    def test_wont_delete_node_if_it_violates_anti_affinity(self):
        # consolidation_test.go:4599 — cheapest nodes, anti-affinity pods:
        # can't replace (no savings), can't delete (anti) -> no action
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        sel = {"matchLabels": {"app": "aa"}}
        pods = [
            make_pod(cpu="1", name=f"a{i}", labels={"app": "aa"}, anti_affinity=[hostname_anti_affinity(sel)])
            for i in range(3)
        ]
        provision(env, pods)
        before = {n.metadata.name for n in env.store.list("Node")}
        run_disruption(env, rounds=10)
        assert {n.metadata.name for n in env.store.list("Node")} == before

    def test_zone_spread_pods_never_go_pending_through_consolidation(self):
        # consolidation_test.go:4525 sibling — oversized zonal fleet shrinks
        # while the spread stays intact and every pod stays bound
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
        sel = {"matchLabels": {"app": "z"}}
        for i, z in enumerate(zones):
            manual_node(env, f"zn-{i}", "c-16x-amd64-linux", "16", zone=z)
            env.store.create(
                make_pod(cpu="500m", name=f"zp{i}", labels={"app": "z"}, node_name=f"zn-{i}", tsc=[zone_spread(1, sel)])
            )
        env.settle(rounds=4)
        run_disruption(env, rounds=14)
        zone_of = {}
        for p in env.store.list("Pod"):
            assert p.spec.node_name, "spread pod went pending during consolidation"
            node = env.store.try_get("Node", p.spec.node_name)
            zone_of[p.metadata.name] = node.metadata.labels.get(wk.ZONE_LABEL_KEY)
        assert len(set(zone_of.values())) == 3, zone_of


class TestConsolidateAfterCandidacy:
    def test_never_blocks_consolidation_candidacy(self):
        # nodepool.consolidateAfter: Never — underutilized nodes are never
        # candidates (nodeclaim disruption leaves Consolidatable false)
        env = Environment(options=Options())
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.disruption.consolidate_after = "Never"
        env.store.create(np)
        manual_node(env, "big-0", "c-32x-amd64-linux", "32")
        env.store.create(make_pod(cpu="500m", name="p0", node_name="big-0"))
        env.settle(rounds=4)
        run_disruption(env, rounds=10)
        assert env.store.try_get("Node", "big-0") is not None

    def test_window_gates_until_elapsed(self):
        # the consolidateAfter window must elapse after the last pod event
        env = Environment(options=Options())
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.disruption.consolidate_after = "300s"
        env.store.create(np)
        manual_node(env, "big-0", "c-32x-amd64-linux", "32")
        env.store.create(make_pod(cpu="500m", name="p0", node_name="big-0"))
        env.settle(rounds=4)
        # within the window: nothing happens
        for _ in range(4):
            env.clock.step(30)
            env.tick(provision_force=True)
        assert env.store.try_get("Node", "big-0") is not None
        # beyond it: the oversized node consolidates
        run_disruption(env, rounds=14, step=60.0)
        assert env.store.try_get("Node", "big-0") is None


class TestPreferencePolicyConsolidation:
    def test_ignore_preferences_allows_delete_consolidation(self):
        # consolidation_test.go:4952 — pods with preferred (hostname)
        # anti-affinity spread 1-per-node under Respect; under Ignore the
        # preference doesn't block packing them together, so nodes delete
        from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm

        def build_env(policy):
            env = Environment(options=Options(preference_policy=policy))
            np = make_nodepool(requirements=OD_ONLY)
            np.spec.disruption.consolidate_after = "30s"
            env.store.create(np)
            for i in range(2):
                manual_node(env, f"n-{i}", "c-16x-amd64-linux", "16")
            sel = {"matchLabels": {"app": "soft"}}
            for i in range(2):
                pod = make_pod(cpu="500m", name=f"sp{i}", labels={"app": "soft"}, node_name=f"n-{i}")
                pod.spec.affinity = Affinity(
                    pod_anti_affinity_preferred=[
                        WeightedPodAffinityTerm(
                            weight=1, term=PodAffinityTerm(label_selector=sel, topology_key=wk.HOSTNAME_LABEL_KEY)
                        )
                    ]
                )
                env.store.create(pod)
            env.settle(rounds=4)
            return env

        env = build_env("Ignore")
        run_disruption(env, rounds=14)
        assert env.store.count("Node") == 1, "Ignore policy should pack both pods onto one cheap node"


class TestMinValuesConsolidation:
    def test_min_values_not_relaxed_for_consolidation(self):
        # consolidation_test.go:5100 — BestEffort minValues relaxation applies
        # to provisioning pressure, not to consolidation: a replacement that
        # only works by relaxing minValues must not fire
        env = Environment(options=Options(min_values_policy="BestEffort"))
        np = make_nodepool(
            requirements=OD_ONLY
            + [{"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "Exists", "minValues": 10}]
        )
        np.spec.disruption.consolidate_after = "30s"
        env.store.create(np)
        manual_node(env, "big-0", "c-32x-amd64-linux", "32")
        env.store.create(make_pod(cpu="500m", name="p0", node_name="big-0"))
        env.settle(rounds=4)
        before = env.store.count("Node")
        run_disruption(env, rounds=10)
        # replacing the node needs a claim whose post-filter instance set
        # still satisfies minValues>=10; the single cheapest candidate can't,
        # and consolidation must not relax it — BUT a compliant multi-type
        # replacement is fine. Assert only that pods never go pending and
        # any surviving fleet satisfies the pool constraint.
        for p in env.store.list("Pod"):
            assert p.spec.node_name


class TestBufferInterplay:
    def test_node_with_real_and_buffer_pods_consolidates_to_cheaper(self):
        # consolidation_test.go:5165 — buffer (virtual) pods shrink headroom
        # but do not pin a node: the node still consolidates to a type that
        # fits real pods + buffer headroom
        from karpenter_tpu.apis.capacitybuffer import CapacityBuffer

        env = make_env()
        buf = CapacityBuffer(metadata=ObjectMeta(name="buf"))
        buf.spec.replicas = 2
        buf.spec.pod_template_ref = {"name": "tpl"}
        from karpenter_tpu.kube.objects import PodTemplate

        tpl = PodTemplate(metadata=ObjectMeta(name="tpl"))
        tpl.template = make_pod(cpu="500m", name="tpl-pod")
        env.store.create(tpl)
        env.store.create(buf)
        manual_node(env, "big-0", "c-32x-amd64-linux", "32")
        env.store.create(make_pod(cpu="500m", name="real", node_name="big-0"))
        env.settle(rounds=4)
        run_disruption(env, rounds=14)
        # the oversized node was replaced by something smaller that still
        # holds the real pod; the buffer keeps its headroom via provisioning
        assert env.store.try_get("Node", "big-0") is None
        real = env.store.get("Pod", "real")
        assert real.spec.node_name


class TestConsolidateAfterDestinations:
    def test_destination_under_window_blocks_then_allows(self):
        # consolidation_test.go:3050 — within the consolidateAfter window
        # nothing moves (neither candidates nor destinations qualify); once
        # it elapses, the single-pod node drains onto its sibling
        env = Environment(options=Options())
        np = make_nodepool(requirements=OD_ONLY)
        np.spec.disruption.consolidate_after = "120s"
        env.store.create(np)
        manual_node(env, "dest", "c-16x-amd64-linux", "16")
        manual_node(env, "src", "c-16x-amd64-linux", "16")
        env.store.create(make_pod(cpu="500m", name="a0", node_name="dest"))
        env.store.create(make_pod(cpu="500m", name="a1", node_name="dest"))
        env.store.create(make_pod(cpu="500m", name="b0", node_name="src"))
        env.settle(rounds=4)
        # within the window: both nodes survive
        for _ in range(3):
            env.clock.step(20)
            env.tick(provision_force=True)
        assert env.store.try_get("Node", "src") is not None
        assert env.store.try_get("Node", "dest") is not None
        # past the window the fleet shrinks
        run_disruption(env, rounds=16, step=60.0)
        assert env.store.count("Node") < 2

    def test_never_destination_still_accepts_consolidated_pods(self):
        # consolidation_test.go:3121 — consolidateAfter: Never makes a node
        # a non-candidate, but it remains a valid DESTINATION for pods from
        # other pools' candidates
        env = Environment(options=Options())
        never = make_nodepool(name="keep", requirements=OD_ONLY)
        never.spec.disruption.consolidate_after = "Never"
        roll = make_nodepool(name="roll", requirements=OD_ONLY)
        roll.spec.disruption.consolidate_after = "30s"
        env.store.create(never)
        env.store.create(roll)
        # destination in the Never pool with headroom; candidate in the
        # rolling pool with one small pod
        labels_keep = {wk.NODEPOOL_LABEL_KEY: "keep"}
        labels_roll = {wk.NODEPOOL_LABEL_KEY: "roll"}
        manual_node(env, "dest", "c-16x-amd64-linux", "16", extra_labels=labels_keep)
        env.store.patch("NodeClaim", "nc-dest", lambda nc: nc.metadata.labels.update(labels_keep))
        manual_node(env, "src", "c-16x-amd64-linux", "16", extra_labels=labels_roll)
        env.store.patch("NodeClaim", "nc-src", lambda nc: nc.metadata.labels.update(labels_roll))
        env.store.create(make_pod(cpu="500m", name="d0", node_name="dest"))
        env.store.create(make_pod(cpu="500m", name="s0", node_name="src"))
        env.settle(rounds=4)
        run_disruption(env, rounds=16, step=60.0)
        # the rolling node consolidated away; the Never node absorbed its pod
        assert env.store.try_get("Node", "src") is None
        assert env.store.try_get("Node", "dest") is not None
        assert env.store.get("Pod", "s0").spec.node_name == "dest"
