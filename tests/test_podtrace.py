"""podtrace (karpenter_tpu/obs/podtrace.py): end-to-end event-lifecycle
tracing for the fleet serving path (ISSUE 14).

Pins the subsystem's contracts:
- parity: bit-identical placements with tracing on vs off (the recorder may
  never influence a solve);
- cross-thread stamps: the threaded fleet loop under the runtime sanitizer
  stamps arrival (watch-delivery thread) / dispatch+solve (fleet loop) on
  the same records with ZERO racecheck violations;
- the additive decomposition: coalesce + sched_wait + solve == e2e exactly,
  per completed record;
- ring bounding + dropped counter, SLO burn accounting, wake-cause split;
- Perfetto export: three named thread tracks joined by flow arrows, round-
  tripping through JSON;
- surfaces: /debug/events (+ ?tenant= + ?n=), /debug/solves?tenant=,
  SolveTrace.explain()'s linked event-batch line, and the ChurnReport e2e
  columns the bench prints next to delta-hit.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from helpers import make_pod
from test_churn_loop import placement_shape, small_spec
from karpenter_tpu import metrics as m
from karpenter_tpu.obs import racecheck
from karpenter_tpu.obs.export import events_to_trace_events, parse_event_dump
from karpenter_tpu.obs.podtrace import (
    STAGES,
    EventRecord,
    PodTracer,
    register_tenant,
    reset_tenants,
    unregister_tenant,
)
from karpenter_tpu.serving import ChurnHarness, ChurnSpec


@pytest.fixture(autouse=True)
def _fresh_tenant_surfaces():
    reset_tenants()
    yield
    reset_tenants()


# -- synthetic record drivers --------------------------------------------------
class _Claim:
    def __init__(self, pods):
        self.pods = pods


class _Results:
    def __init__(self, pods):
        self.new_node_claims = [_Claim(pods)]
        self.existing_nodes = []


def _deliver(tracer: PodTracer, pod, event: str = "ADDED"):
    now = time.monotonic()
    tracer.on_delivery(event, pod, now, now)


def _complete(tracer: PodTracer, pods, solve_seq: int = 1):
    tracer.on_dispatch(pods)
    tracer.on_solved(_Results(pods), solve_seq=solve_seq)


class TestEventRecordLifecycle:
    def test_stages_are_additive_to_e2e(self):
        tracer = PodTracer(enabled=True)
        pod = make_pod(name="ev-add")
        pod.metadata.uid = "uid-ev-add"
        _deliver(tracer, pod)
        tracer.on_prestaged("uid-ev-add")
        _complete(tracer, [pod], solve_seq=7)
        recs = tracer.events()
        assert len(recs) == 1
        r = recs[0]
        assert r.outcome == "placed" and r.solve_seq == 7 and r.staged
        s = r.stage_seconds()
        assert s["e2e"] == pytest.approx(s["coalesce"] + s["sched_wait"] + s["solve"], abs=1e-9)
        assert set(s) == set(STAGES)

    def test_cancel_and_bind_paths(self):
        tracer = PodTracer(enabled=True)
        gone = make_pod(name="ev-gone")
        gone.metadata.uid = "uid-ev-gone"
        _deliver(tracer, gone)
        _deliver(tracer, gone, "DELETED")
        assert tracer.cancelled == 1 and tracer.events() == []
        # placed then bound: the MODIFIED event carrying node_name closes
        # the decode stage on the already-completed ring record
        pod = make_pod(name="ev-bind")
        pod.metadata.uid = "uid-ev-bind"
        _deliver(tracer, pod)
        _complete(tracer, [pod])
        pod.spec.node_name = "node-1"
        _deliver(tracer, pod, "MODIFIED")
        r = tracer.events()[0]
        assert r.outcome == "bound"
        assert r.stage_seconds()["decode"] >= 0.0 and r.t_bound >= r.t_solved

    def test_errored_and_absent_records_never_phantom_complete(self):
        tracer = PodTracer(enabled=True)
        pod = make_pod(name="ev-err")
        pod.metadata.uid = "uid-ev-err"
        _deliver(tracer, pod)
        # dispatched but ERRORED: the record must stay in flight
        tracer.on_dispatch([pod])
        res = _Results([])
        res.pod_errors = {"default/ev-err": "unschedulable"}
        tracer.on_solved(res, solve_seq=1)
        assert tracer.events() == []
        # the pod then leaves the pending set WITHOUT a watch event (e.g.
        # PVC turns invalid); a later pass solves a batch it is absent from
        # — completion-by-inversion must not phantom-place it
        tracer.on_dispatch([])
        tracer.on_solved(_Results([]), solve_seq=2)
        assert tracer.events() == [] and tracer.seq == 0
        # re-dispatched in a clean batch: completes normally
        tracer.on_dispatch([pod])
        tracer.on_solved(_Results([pod]), solve_seq=3)
        assert [r.solve_seq for r in tracer.events()] == [3]

    def test_disabled_tracer_records_nothing(self):
        tracer = PodTracer(enabled=False)
        pod = make_pod(name="ev-off")
        pod.metadata.uid = "uid-ev-off"
        _deliver(tracer, pod)
        _complete(tracer, [pod])
        assert tracer.events() == [] and tracer.deliveries == 0

    def test_non_pod_kinds_are_ignored(self):
        tracer = PodTracer(enabled=True)

        class _Node:
            kind = "Node"

        _deliver(tracer, _Node())
        assert tracer.deliveries == 0


class TestRingAndSlo:
    def test_ring_bounds_and_dropped_counter(self):
        tracer = PodTracer(enabled=True, capacity=4)
        for i in range(7):
            pod = make_pod(name=f"ev-ring-{i}")
            pod.metadata.uid = f"uid-ev-ring-{i}"
            _deliver(tracer, pod)
            _complete(tracer, [pod], solve_seq=i + 1)
        assert len(tracer.events()) == 4
        assert tracer.dropped == 3
        assert tracer.seq == 7
        # the ring keeps the NEWEST completions, oldest first
        assert [r.seq for r in tracer.events()] == [4, 5, 6, 7]
        assert tracer.events_since(6) == tracer.events()[-1:]

    def test_slo_burn_accounting_and_metric(self):
        registry = m.make_registry()
        tracer = PodTracer(enabled=True, slo_seconds=0.0, registry=registry)
        for i in range(3):
            pod = make_pod(name=f"ev-slo-{i}")
            pod.metadata.uid = f"uid-ev-slo-{i}"
            _deliver(tracer, pod)
            _complete(tracer, [pod])
        slo = tracer.slo.to_dict()
        assert slo["completed"] == 3 and slo["breaches"] == 3
        assert slo["burn_rate"] == 1.0 and slo["budget_remaining"] == 0.0
        assert registry.counter(m.SOLVER_EVENT_SLO_BREACH_TOTAL).value(tenant="") == 3
        # a generous target burns nothing
        ok = PodTracer(enabled=True, slo_seconds=60.0)
        pod = make_pod(name="ev-slo-ok")
        pod.metadata.uid = "uid-ev-slo-ok"
        _deliver(ok, pod)
        _complete(ok, [pod])
        assert ok.slo.breaches == 0 and ok.slo.to_dict()["budget_remaining"] == 1.0

    def test_wake_cause_and_sched_wait_plumbing(self):
        tracer = PodTracer(enabled=True)
        tracer.on_wake("watch-event")
        tracer.on_wake("poll-floor")
        tracer.on_wake("watch-event")
        pod = make_pod(name="ev-drr")
        pod.metadata.uid = "uid-ev-drr"
        _deliver(tracer, pod)
        tracer.note_sched_wait(0.5, drr_round=3, credit=2.0, cause="watch-event")
        _complete(tracer, [pod])
        r = tracer.events()[0]
        assert r.sched_wait == 0.5 and r.drr_round == 3 and r.drr_credit == 2.0
        assert r.stage_seconds()["sched_wait"] == 0.5
        # the episode's wake cause rides the dispatch onto the record
        assert r.wake_cause == "watch-event"
        assert r.to_dict()["wake_cause"] == "watch-event"
        dump = tracer.dump()
        assert dump["wake_causes"] == {"watch-event": 2, "poll-floor": 1}

    def test_selftime_meter_arms_and_disarms(self):
        tracer = PodTracer(enabled=True)
        tracer.start_selftime()
        pod = make_pod(name="ev-st")
        pod.metadata.uid = "uid-ev-st"
        _deliver(tracer, pod)
        _complete(tracer, [pod])
        cost = tracer.stop_selftime()
        assert cost > 0.0
        assert "on_delivery" not in tracer.__dict__  # wrappers removed
        # disarmed: further activity does not accumulate
        pod2 = make_pod(name="ev-st2")
        pod2.metadata.uid = "uid-ev-st2"
        _deliver(tracer, pod2)
        assert tracer.selftime == cost
        assert tracer.seq == 1 and len(tracer.events()) == 1

    def test_stats_cover_every_stage(self):
        tracer = PodTracer(enabled=True)
        pod = make_pod(name="ev-stats")
        pod.metadata.uid = "uid-ev-stats"
        _deliver(tracer, pod)
        _complete(tracer, [pod])
        stats = tracer.stats()
        assert set(stats) == set(STAGES)
        for qs in stats.values():
            assert qs["n"] == 1 and qs["p50"] <= qs["p99"]


class TestParityOnOff:
    def test_bit_identical_placements_tracing_on_vs_off(self, monkeypatch):
        shapes = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("KARPENTER_PODTRACE", flag)
            spec = small_spec()
            h = ChurnHarness(spec)
            try:
                h.run()
                shapes[flag] = placement_shape(h.env)
                tracer = h.env.podtracer
                if flag == "1":
                    assert tracer.enabled and tracer.seq > 0
                else:
                    assert not tracer.enabled and tracer.seq == 0
            finally:
                h.close()
        assert shapes["1"] == shapes["0"]

    def test_churn_report_e2e_columns(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_PODTRACE", "1")
        rep = None
        h = ChurnHarness(small_spec())
        try:
            rep = h.run()
        finally:
            h.close()
        assert rep.e2e_events > 0
        assert rep.e2e_p99_seconds >= rep.e2e_p50_seconds > 0.0
        assert rep.dominant_stage in ("coalesce", "sched_wait", "solve")
        assert set(rep.stage_p99_seconds) == set(STAGES) - {"e2e"}
        d = rep.as_dict()
        assert d["e2e_p99_seconds"] == round(rep.e2e_p99_seconds, 4)
        # solo harness: no DRR, so sched_wait must be exactly zero
        assert rep.stage_p99_seconds["sched_wait"] == 0.0

    def test_event_batch_linked_into_solvetrace_explain(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_PODTRACE", "1")
        h = ChurnHarness(small_spec())
        try:
            h.run()
            solver = h.env.provisioner.solver
            last = h.recorder.last()
            assert last is not None
            eb = last.attribution.get("event_batch") or next(
                (t.attribution.get("event_batch") for t in reversed(h.recorder.traces()) if t.attribution.get("event_batch")),
                None,
            )
            assert eb is not None and eb["count"] > 0 and "oldest_age_s" in eb
            traced = next(t for t in reversed(h.recorder.traces()) if t.attribution.get("event_batch"))
            assert "traced watch event" in traced.explain()
            # the ring's solve_seq values join back to recorded solve traces
            seqs = {t.seq for t in h.recorder.traces()}
            assert any(r.solve_seq in seqs for r in h.env.podtracer.events())
        finally:
            h.close()

    def test_record_replay_carries_arrival_offsets(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KARPENTER_PODTRACE", "1")
        path = tmp_path / "events.jsonl"
        spec = small_spec(record_path=str(path))
        h = ChurnHarness(spec)
        try:
            rep = h.run()
        finally:
            h.close()
        ops = [json.loads(line) for line in path.read_text().splitlines()]
        arrives = [op for op in ops if op["op"] == "arrive"]
        assert arrives and all("t" in op for op in ops)
        ts = [op["t"] for op in arrives]
        assert ts == sorted(ts), "arrival offsets must be monotone"
        # replay: same placements, and the replayed run re-measures a live
        # e2e distribution over the same event/solve composition
        replay = ChurnSpec.from_event_log(str(path))
        h2 = ChurnHarness(replay)
        try:
            rep2 = h2.run()
        finally:
            h2.close()
        assert rep2.e2e_events > 0
        assert rep2.solves == rep.solves
        assert rep2.dominant_stage in ("coalesce", "sched_wait", "solve")


class TestPerfettoExport:
    def _records(self, n=3):
        tracer = PodTracer(enabled=True)
        pods = []
        for i in range(n):
            pod = make_pod(name=f"ev-px-{i}")
            pod.metadata.uid = f"uid-ev-px-{i}"
            _deliver(tracer, pod)
            tracer.on_prestaged(pod.metadata.uid)
            pods.append(pod)
        _complete(tracer, pods, solve_seq=9)
        for pod in pods:
            pod.spec.node_name = "node-1"
            _deliver(tracer, pod, "MODIFIED")
        return tracer.events()

    def test_flow_arrows_round_trip(self):
        recs = self._records()
        doc = json.loads(json.dumps(events_to_trace_events(recs)))
        events = doc["traceEvents"]
        names = {e["args"]["name"] for e in events if e.get("ph") == "M"}
        assert names == {"watch-delivery", "serve-loop", "prestage-worker"}
        flows = [e for e in events if e.get("name") == "event-flow"]
        starts = {e["id"]: e["tid"] for e in flows if e["ph"] == "s"}
        finishes = {e["id"]: e["tid"] for e in flows if e["ph"] == "f"}
        steps = {e["id"]: e["tid"] for e in flows if e["ph"] == "t"}
        assert set(starts) == set(finishes) and len(starts) == len(recs)
        # the arrow crosses threads: watch-delivery -> serve-loop, stepping
        # through the prestage worker for staged events
        for fid, tid in starts.items():
            assert tid != finishes[fid]
        assert steps and all(fid in starts for fid in steps)
        slices = {e["name"].split(":")[0] for e in events if e.get("ph") == "X"}
        assert {"coalesce", "solve", "prestage", "decode"} <= slices

    def test_parse_event_dump_forms(self):
        recs = [r.to_dict() for r in self._records(2)]
        jsonl = "\n".join(json.dumps(r) for r in recs)
        assert parse_event_dump(jsonl) == recs
        assert parse_event_dump(json.dumps({"tenants": {"a": {"events": recs}}})) == recs
        assert parse_event_dump(json.dumps({"events": recs})) == recs
        assert parse_event_dump("") == []

    def test_cli_exports_event_tracks(self, tmp_path, capsys):
        from karpenter_tpu.obs.__main__ import main

        src = tmp_path / "events.jsonl"
        src.write_text("\n".join(json.dumps(r.to_dict()) for r in self._records(2)))
        out = tmp_path / "events.trace.json"
        assert main([str(src), "--events", "--out", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert any(e.get("name") == "event-flow" for e in doc["traceEvents"])


class TestOperatorSurfaces:
    def _get(self, port, path):
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:  # 4xx still carries a body
            return e.code, e.read().decode()

    def test_debug_events_and_tenant_filter(self, monkeypatch):
        from karpenter_tpu.operator.server import OperatorServer

        monkeypatch.setenv("KARPENTER_PODTRACE", "1")
        h = ChurnHarness(small_spec(n_base_pods=40, iterations=2))
        try:
            h.run()
            server = OperatorServer(h.env, port=0)
            port = server.start()
            try:
                code, body = self._get(port, "/debug/events")
                assert code == 200
                dump = json.loads(body)
                assert "default" in dump["tenants"]
                d = dump["tenants"]["default"]
                assert d["enabled"] and d["completed"] > 0 and d["events"]
                assert set(d["stats"]) == set(STAGES)
                assert "slo" in d and d["slo"]["completed"] > 0
                code, body = self._get(port, "/debug/events?n=1")
                assert code == 200
                assert len(json.loads(body)["tenants"]["default"]["events"]) == 1
                code, _ = self._get(port, "/debug/events?tenant=nope")
                assert code == 404
                # metrics: the stage-quantile family and SLO counter render
                code, body = self._get(port, "/metrics")
                assert code == 200
                assert m.SOLVER_EVENT_STAGE_QUANTILE_SECONDS in body
            finally:
                server.stop()
        finally:
            h.close()

    def test_debug_solves_tenant_filter(self):
        from karpenter_tpu.obs.trace import TraceRecorder
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.operator.server import OperatorServer

        env = Environment(options=Options())
        rec = TraceRecorder(capacity=8, enabled=True)
        tr = rec.begin(n_pods=1)
        tr.mode = "full"
        rec.commit(tr)
        register_tenant("team-a", rec, PodTracer(enabled=True, tenant="team-a"))
        server = OperatorServer(env, port=0)
        port = server.start()
        try:
            code, body = self._get(port, "/debug/solves?tenant=team-a")
            assert code == 200
            dump = json.loads(body)
            assert dump["recorded"] == 1 and dump["solves"]
            code, _ = self._get(port, "/debug/solves?tenant=ghost")
            assert code == 404
            code, body = self._get(port, "/debug/events?tenant=team-a")
            assert code == 200
            assert json.loads(body)["tenants"]["team-a"]["completed"] == 0
        finally:
            server.stop()
            unregister_tenant("team-a")


class TestThreadedFleetPodtrace:
    def test_cross_thread_stamps_under_sanitizer(self):
        """The wall-clock fleet loop + watch-delivery threads stamp the SAME
        records (arrival on the delivery thread, dispatch/solve on the fleet
        loop) with zero racecheck violations — the cross-thread contract."""
        from test_fleet import tenant_options
        from karpenter_tpu.serving.fleet import FleetFrontend, reset_tenant_labels
        from karpenter_tpu.utils.clock import Clock

        racecheck.reset()
        reset_tenant_labels()
        spec = small_spec(n_base_pods=0, batch_idle_seconds=0.05)
        fleet = FleetFrontend(poll_floor_seconds=0.05)
        try:
            sess = fleet.add_tenant("live", options=tenant_options(spec), clock=Clock())
            tracer = sess.env.podtracer
            assert tracer.enabled and tracer.tenant == "live"
            h = ChurnHarness(spec).attach(sess)
            fleet.start()
            for _ in range(10):
                h.apply_arrivals(5)
                time.sleep(0.03)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not tracer.events():
                time.sleep(0.05)
            fleet.stop()
            recs = tracer.events()
            assert recs, "fleet loop never completed a traced event"
            for r in recs:
                assert r.t_dispatch >= r.t_arrival and r.t_solved >= r.t_dispatch
                assert r.outcome in ("placed", "bound")
            # fleet dispatches carry the episode's wake cause per record
            from karpenter_tpu.obs.podtrace import WAKE_CAUSES as _WC

            assert any(r.wake_cause in _WC for r in recs), [r.wake_cause for r in recs]
            # the wake split carried a bounded cause end-to-end
            total_wakes = sum(tracer.wake_causes.values())
            assert total_wakes > 0
            from karpenter_tpu.obs.podtrace import WAKE_CAUSES

            assert set(tracer.wake_causes) <= set(WAKE_CAUSES)
            snap = racecheck.snapshot()
            assert snap["violations"] == [], snap["violations"]
        finally:
            fleet.close()
            racecheck.reset()
            reset_tenant_labels()
