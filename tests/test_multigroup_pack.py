"""Multi-group water-fill (lrapack): pods in MULTIPLE keyed-domain groups
keep their count>1 merge via `_waterfill_multi`'s joint fill.

Five contract families from the lrapack PR:
  1. randomized dense-graph parity — merged multi-group items vs the per-pod
     (count=1) reference expansion, spread+anti+required-affinity mixed with
     host ports and taints, compared canonically (placed set, per-slot
     composition multiset, final counts_zone state);
  2. demotion-reason attribution — every DEMOTION_REASONS value reachable
     and counted in build_items' with_info stats;
  3. delta-path chaining over a GROWN multi-group item (replicas of an
     already-merged shape arriving on the warm path);
  4. escape-hatch bit-parity — KARPENTER_SOLVER_MULTIGROUP=0 reproduces the
     seed's per-pod keys exactly (inline reference reimplementation);
  5. zero-recompile sentinel pin — identical resubmit and under-high-water
     shrink of a multi-group fleet must not retrace any watched kernel.
"""

from __future__ import annotations

import numpy as np
import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.scheduling.taints import Taint
from karpenter_tpu.solver.encode import encode
from karpenter_tpu.solver.tpu import TPUSolver
from test_domain_topology import LINUX_AMD64, anti, make_snapshot, spread

ZONE = wk.ZONE_LABEL_KEY
CT = wk.CAPACITY_TYPE_LABEL_KEY


def _sel(**kv):
    return {"matchLabels": kv}


def _merged_set(g, n, tier, cpu="500m", ports=False):
    """n replicas that are members of TWO zone-key spread groups (own app
    selector + shared tier selector): the merged multi-group shape."""
    labels = {"app": f"g{g}", "tier": tier}
    tsc = [spread(ZONE, 1, _sel(app=f"g{g}")), spread(ZONE, 2, _sel(tier=tier))]
    pods = [make_pod(cpu=cpu, name=f"g{g}-{i}", labels=labels, tsc=tsc) for i in range(n)]
    if ports:
        for p in pods:
            p.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]
    return pods


def _perpod_items(enc):
    """The reference expansion: EVERY pod its own count=1 item, in MERGED
    ITEM ORDER (all replicas of a shape consecutively, at the shape's first
    queue position). Grouping collapses an item's replicas to its first
    pod's scan position — the seed's count>1 merge already reorders
    interleaved queues this way — so the per-pod reference must process the
    same pod sequence for placement parity to be well-defined. Distinct
    item_axis so the reference arm never pollutes the production 'items'
    high-water mark."""
    from karpenter_tpu.models.scheduler_model import sig_restrict_of
    from karpenter_tpu.models.scheduler_model_grouped import (
        ITEM_AXIS_BUCKET,
        build_items,
        pad_item_arrays,
    )

    _, merged_pods = build_items(enc)
    order = np.concatenate([p for p in merged_pods if p.size]).astype(np.int64)
    P = enc.n_pods
    S = enc.n_sigs
    G = enc.sig_member.shape[1] if enc.sig_member.size else 0
    sig_member = enc.sig_member if G else np.zeros((max(S, 1), 1), bool)
    sig_owner = enc.sig_owner if G else np.zeros((max(S, 1), 1), bool)
    sig = np.asarray(enc.sig_of_pod, dtype=np.int64)[order]
    arrays = dict(
        item_req=enc.sig_req[sig],
        item_mask=enc.sig_mask[sig],
        item_taint_ok=enc.sig_taint_ok[sig],
        item_dom_allowed=enc.sig_dom_allowed[sig],
        item_restrict=sig_restrict_of(enc)[sig],
        item_member=sig_member[sig],
        item_owner=sig_owner[sig],
        item_count=np.ones(P, np.int32),
        item_port_any=enc.sig_port_any[sig],
        item_port_wild=enc.sig_port_wild[sig],
        item_port_spec=enc.sig_port_spec[sig],
        item_host_blocked=enc.sig_host_blocked[sig],
    )
    arrays = pad_item_arrays(arrays, ITEM_AXIS_BUCKET, item_axis="ref_items")
    item_pods = [np.array([i], np.int64) for i in order]
    item_pods += [np.zeros(0, np.int64)] * (len(arrays["item_count"]) - P)
    return arrays, item_pods


def _pack(enc, arrays, item_pods):
    from karpenter_tpu.models.scheduler_model import make_tensors
    from karpenter_tpu.models.scheduler_model_grouped import (
        assignment_from_triples,
        greedy_pack_grouped_compressed,
        make_item_tensors,
    )

    items = make_item_tensors(arrays)
    t = make_tensors(enc, n_slots=enc.n_existing + min(enc.n_pods, 4096), with_pods=False)
    out = greedy_pack_grouped_compressed(t, items, enc.n_pods)
    assignment = assignment_from_triples(out["nz_item"], out["nz_slot"], out["nz_count"], item_pods, enc.n_pods)
    return out, assignment


def _canonical(enc, out, assignment):
    """Placement up to fresh-slot index order AND within-item pod identity:
    (placed pod set, multiset of per-slot (basis, sig-composition), final
    counts_zone). Pods inside one item are interchangeable, so WHICH replica
    carries WHICH name on a slot is not part of the contract; the slot's
    shape composition and the group-count state are — exactly."""
    sig = np.asarray(enc.sig_of_pod)
    placed = np.nonzero(assignment >= 0)[0]
    slots: dict[int, list[int]] = {}
    for p in placed:
        slots.setdefault(int(assignment[p]), []).append(int(sig[p]))
    comp = sorted((int(out["slot_basis"][s]), tuple(sorted(v))) for s, v in slots.items())
    return set(placed.tolist()), comp, np.asarray(out["state"][4])


def _mg_zone_counts(enc, out, assignment):
    """Per-(multi-group sig, slot zoneset) pod counts — the joint fill's
    OWN placements must match per-pod sequential placement exactly (not just
    in aggregate): same zones, same per-zone counts."""
    from karpenter_tpu.models.scheduler_model_grouped import (
        KIND_DOM_AFF,
        KIND_DOM_ANTI,
        KIND_DOM_SPREAD,
    )

    kinds = np.asarray(enc.group_kind)
    zone_groups = (kinds == KIND_DOM_SPREAD) | (kinds == KIND_DOM_ANTI) | (kinds == KIND_DOM_AFF)
    zm = (enc.sig_member & zone_groups[None, :]).sum(axis=1)
    sig = np.asarray(enc.sig_of_pod)
    zs = np.asarray(out["slot_zoneset"])
    counts: dict[tuple, int] = {}
    for p in np.nonzero(assignment >= 0)[0]:
        s_ = int(sig[p])
        if zm[s_] <= 1:
            continue
        z = tuple(np.nonzero(zs[int(assignment[p])])[0].tolist())
        counts[(s_, z)] = counts.get((s_, z), 0) + 1
    return counts


def _lra_fleet(rng, n_sets=7):
    """Dense LRA-style cross-membership: every set spreads over its own app
    selector, rolls extra zone/tier/capacity-type constraints, hostname
    anti-affinity, required zone affinity, ports, and taints."""
    tiers = ("gold", "silver")
    pods, tolerating = [], []
    for g in range(n_sets):
        tier = tiers[g % 2]
        n = int(rng.integers(2, 6))
        cpu = ["300m", "500m", "700m", "1"][int(rng.integers(0, 4))]
        labels = {"app": f"g{g}", "tier": tier}
        tsc = [spread(ZONE, 1, _sel(app=f"g{g}"))]
        anti_aff = None
        pod_aff = None
        roll = int(rng.integers(0, 6))
        if roll in (0, 1):
            # merged multi-group: second zone-key spread over the shared
            # `mg=<tier>` label. Carried ONLY by sets that also declare the
            # constraint, so membership stays symmetric (every matched pod
            # declares it) while still crossing replica-set boundaries.
            labels["mg"] = tier
            tsc.append(spread(ZONE, 2, _sel(mg=tier)))
            if roll == 1:  # plus hostname spread (hostname key is in-window)
                tsc.append(spread(wk.HOSTNAME_LABEL_KEY, 1, _sel(app=f"g{g}")))
        elif roll == 2:  # zone spread + hostname anti (anti_path, count>1)
            anti_aff = [hostname_anti_affinity(_sel(app=f"g{g}"))]
        elif roll == 3:  # required zone co-location only (dom_aff_path)
            tsc = []
            pod_aff = [anti(_sel(app=f"g{g}"), ZONE)]
        # roll 4/5: plain single-group spread
        for i in range(n):
            p = make_pod(
                cpu=cpu,
                name=f"g{g}-{i}",
                labels=labels,
                tsc=list(tsc),
                anti_affinity=anti_aff,
                pod_affinity=pod_aff,
                tolerations=[{"key": "dedicated", "operator": "Equal", "value": "lra", "effect": "NoSchedule"}]
                if g % 3 == 0
                else None,
            )
            if roll == 2 and int(rng.integers(0, 2)):
                p.spec.containers[0].ports = [{"containerPort": 9000, "hostPort": 9000 + g, "protocol": "TCP"}]
            pods.append(p)
            if g % 3 == 0:
                tolerating.append(p.metadata.name)
    return pods


def _pools():
    return [
        make_nodepool(name="default-pool", requirements=LINUX_AMD64),
        make_nodepool(
            name="tainted-pool",
            requirements=LINUX_AMD64,
            taints=[Taint(key="dedicated", value="lra", effect="NoSchedule")],
        ),
    ]


class TestMultiGroupKernelParity:
    @pytest.mark.parametrize("seed", [1, 7, 23, 61])
    def test_randomized_dense_graph_matches_perpod_reference(self, seed):
        """Merged multi-group items place bit-identically (up to fresh-slot
        index order) to the per-pod count=1 reference expansion."""
        from karpenter_tpu.models.scheduler_model_grouped import build_items
        from karpenter_tpu.solver.check import fast_validate

        rng = np.random.default_rng(seed)
        snap = make_snapshot(_lra_fleet(rng), node_pools=_pools())
        enc = encode(snap)
        assert enc.fallback_reasons == []

        merged_arrays, merged_pods = build_items(enc)
        ref_arrays, ref_pods = _perpod_items(enc)
        out_m, asg_m = _pack(enc, merged_arrays, merged_pods)
        out_r, asg_r = _pack(enc, ref_arrays, ref_pods)
        assert fast_validate(enc, asg_m, out_m["slot_basis"], out_m["slot_zoneset"]) == []
        assert fast_validate(enc, asg_r, out_r["slot_basis"], out_r["slot_zoneset"]) == []

        placed_m, comp_m, cz_m = _canonical(enc, out_m, asg_m)
        placed_r, comp_r, cz_r = _canonical(enc, out_r, asg_r)
        assert placed_m == placed_r
        assert comp_m == comp_r
        np.testing.assert_array_equal(cz_m, cz_r)
        assert _mg_zone_counts(enc, out_m, asg_m) == _mg_zone_counts(enc, out_r, asg_r)

    def test_merged_items_compress_multi_group_replicas(self):
        from karpenter_tpu.models.scheduler_model_grouped import build_items

        pods = _merged_set(0, 12, "gold") + _merged_set(1, 12, "gold")
        enc = encode(make_snapshot(pods))
        assert enc.fallback_reasons == []
        _, _, info = build_items(enc, with_info=True)
        assert info["n_pods"] == 24
        assert info["demotions"] == {}
        # 24 pods in 2 shapes -> 2 items: the whole point of the merge
        assert info["n_items"] == 2

    def test_merged_ports_fleet_matches_reference(self):
        """hostPort forces one-per-host inside a merged multi-group item."""
        from karpenter_tpu.models.scheduler_model_grouped import build_items

        pods = _merged_set(0, 5, "gold", ports=True) + _merged_set(1, 4, "silver", cpu="300m")
        enc = encode(make_snapshot(pods))
        assert enc.fallback_reasons == []
        out_m, asg_m = _pack(enc, *build_items(enc))
        out_r, asg_r = _pack(enc, *_perpod_items(enc))
        placed_m, comp_m, cz_m = _canonical(enc, out_m, asg_m)
        placed_r, comp_r, cz_r = _canonical(enc, out_r, asg_r)
        assert placed_m == placed_r
        assert comp_m == comp_r
        np.testing.assert_array_equal(cz_m, cz_r)
        assert _mg_zone_counts(enc, out_m, asg_m) == _mg_zone_counts(enc, out_r, asg_r)


class TestDemotionAttribution:
    def test_multi_key_demotes_with_reason(self):
        from karpenter_tpu.models.scheduler_model_grouped import build_items

        labels = {"app": "mk"}
        tsc = [spread(ZONE, 1, _sel(app="mk")), spread(CT, 1, _sel(app="mk"))]
        pods = [make_pod(cpu="500m", name=f"mk-{i}", labels=labels, tsc=tsc) for i in range(6)]
        enc = encode(make_snapshot(pods))
        _, _, info = build_items(enc, with_info=True)
        assert info["demotions"] == {"multi-key": 6}
        assert info["n_items"] == 6  # every pod its own item

    def test_aff_pin_conflict_demotes_with_reason(self):
        from karpenter_tpu.models.scheduler_model_grouped import build_items

        labels = {"a": "1", "b": "1"}
        pod_aff = [anti(_sel(a="1"), ZONE), anti(_sel(b="1"), ZONE)]
        pods = [make_pod(cpu="500m", name=f"ap-{i}", labels=labels, pod_affinity=pod_aff) for i in range(4)]
        enc = encode(make_snapshot(pods))
        _, _, info = build_items(enc, with_info=True)
        assert info["demotions"] == {"aff-pin-conflict": 4}

    def test_hatch_off_demotes_mergeable_shapes(self, monkeypatch):
        from karpenter_tpu.models.scheduler_model_grouped import build_items

        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "0")
        enc = encode(make_snapshot(_merged_set(0, 5, "gold")))
        _, _, info = build_items(enc, with_info=True)
        assert info["multigroup"] is False
        assert info["demotions"] == {"hatch-off": 5}
        assert info["n_items"] == 5

    def test_demotion_label_is_bounded(self):
        from karpenter_tpu.models.scheduler_model_grouped import DEMOTION_REASONS, demotion_label

        for r in DEMOTION_REASONS:
            assert demotion_label(r) == r
        assert demotion_label("surprise-new-reason") == "other"

    def test_solver_emits_demotion_metrics(self, monkeypatch):
        from karpenter_tpu.metrics import (
            SOLVER_PACK_ITEM_COMPRESSION,
            SOLVER_PACK_ITEM_DEMOTIONS_TOTAL,
            make_registry,
        )

        # hatch off: the merged shape demotes per-pod and the counter/gauge
        # record it (in-window shapes never demote with the hatch on)
        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "0")
        reg = make_registry()
        solver = TPUSolver(force=True, registry=reg)
        solver.solve(make_snapshot(_merged_set(0, 5, "gold")))
        assert reg.counter(SOLVER_PACK_ITEM_DEMOTIONS_TOTAL).value(reason="hatch-off") == 5
        assert reg.gauge(SOLVER_PACK_ITEM_COMPRESSION).value() == 1.0  # 5 pods / 5 items

        # hatch on: same fleet merges to ONE item, no demotions
        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "1")
        reg2 = make_registry()
        solver2 = TPUSolver(force=True, registry=reg2)
        solver2.solve(make_snapshot(_merged_set(0, 5, "gold")))
        assert reg2.counter(SOLVER_PACK_ITEM_DEMOTIONS_TOTAL).total() == 0
        assert reg2.gauge(SOLVER_PACK_ITEM_COMPRESSION).value() == 5.0


class TestDeltaChaining:
    def test_grown_multi_group_item_stays_delta_and_matches_full(self):
        """Replicas of an already-merged multi-group shape arriving on the
        warm path must ride the delta kernel (the merged item GROWS), chain
        across batches, and land where a fresh full solve lands them."""
        pods = _merged_set(0, 6, "gold") + _merged_set(1, 4, "gold", cpu="300m")
        snap = make_snapshot(list(pods))
        solver = TPUSolver(force=True)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert not r.pod_errors

        for batch in range(2):
            snap.pods.extend(_merged_set(0, 2, "gold")[:2])
            for i, p in enumerate(snap.pods[-2:]):
                p.metadata.name = f"grow{batch}-{i}"
            r = solver.solve(snap)
            assert solver.last_solve_mode == "delta", (
                solver.last_solve_mode,
                solver.encode_cache.last_delta_reject,
            )
            assert not r.pod_errors

        from test_delta_compose import _claims, _placed_pod_names

        fresh = TPUSolver(force=True)
        full = fresh.solve(make_snapshot(list(snap.pods)))
        assert not full.pod_errors
        assert _placed_pod_names(r) == _placed_pod_names(full)
        assert len(_claims(r)) <= len(_claims(full)) + 1

    def test_delta_demotes_same_shapes_as_full(self, monkeypatch):
        """A demoted shape arriving as a delta add must split per-pod exactly
        like the full path (shared sig_demotions oracle): hatch off, new
        replicas of a multi-group shape stay count=1 on the delta path."""
        from karpenter_tpu.obs.trace import TraceRecorder

        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "0")
        pods = _merged_set(0, 5, "gold")
        snap = make_snapshot(list(pods))
        solver = TPUSolver(force=True, recorder=TraceRecorder(enabled=True))
        solver.solve(snap)
        assert solver.last_solve_mode == "full"
        grow = _merged_set(0, 2, "gold")
        for i, p in enumerate(grow):
            p.metadata.name = f"late-{i}"
        snap.pods.extend(grow)
        r = solver.solve(snap)
        assert not r.pod_errors
        if solver.last_solve_mode == "delta":
            assert solver._trace.attribution.get("delta_demoted") == 2


class TestEscapeHatch:
    def test_hatch_off_bit_parity_with_seed_reference(self, monkeypatch):
        """MULTIGROUP=0 must reproduce the seed's item keys EXACTLY: per-pod
        keys for every multi-zone-membership shape, merge for the rest."""
        from karpenter_tpu.models.scheduler_model_grouped import build_items
        from karpenter_tpu.models.scheduler_model_grouped import KIND_DOM_AFF, KIND_DOM_ANTI, KIND_DOM_SPREAD

        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "0")
        rng = np.random.default_rng(5)
        snap = make_snapshot(_lra_fleet(rng), node_pools=_pools())
        enc = encode(snap)
        assert enc.fallback_reasons == []
        arrays, item_pods = build_items(enc)

        # inline seed reference: per-pod keys for ALL multi-zone sigs
        kinds = np.asarray(enc.group_kind)
        zone_groups = (kinds == KIND_DOM_SPREAD) | (kinds == KIND_DOM_ANTI) | (kinds == KIND_DOM_AFF)
        multi_zone = (enc.sig_member & zone_groups[None, :]).sum(axis=1) > 1
        sig = np.asarray(enc.sig_of_pod, dtype=np.int64)
        P = enc.n_pods
        key = np.where(multi_zone[sig], enc.n_sigs + np.arange(P, dtype=np.int64), sig)
        _, first_idx, inverse, counts = np.unique(key, return_index=True, return_inverse=True, return_counts=True)
        order = np.argsort(first_idx, kind="stable")
        np.testing.assert_array_equal(
            arrays["item_count"][: len(order)], counts[order].astype(np.int32)
        )
        rep_sig = sig[first_idx[order]]
        np.testing.assert_array_equal(arrays["item_req"][: len(order)], enc.sig_req[rep_sig])
        rank = np.empty_like(order)
        rank[order] = np.arange(order.size)
        item_of_pod = rank[inverse]
        for w in range(len(order)):
            np.testing.assert_array_equal(item_pods[w], np.nonzero(item_of_pod == w)[0])

    def test_hatch_arms_place_equivalently(self, monkeypatch):
        """Solver-level: MULTIGROUP on/off schedule the same pods onto the
        same claim shapes (composition multiset), differing only in item
        compression."""

        def run():
            solver = TPUSolver(force=True)
            res = solver.solve(make_snapshot(_merged_set(0, 8, "gold") + _merged_set(1, 6, "silver", cpu="300m")))
            assert not res.pod_errors
            comp = sorted(
                tuple(sorted(p.metadata.labels["app"] for p in nc.pods)) for nc in res.new_node_claims if nc.pods
            )
            names = {p.metadata.name for nc in res.new_node_claims for p in nc.pods}
            names |= {p.metadata.name for en in res.existing_nodes for p in en.pods}
            return comp, names

        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "1")
        comp_on, names_on = run()
        monkeypatch.setenv("KARPENTER_SOLVER_MULTIGROUP", "0")
        comp_off, names_off = run()
        assert names_on == names_off
        assert comp_on == comp_off


class TestRecompilePin:
    def test_warm_multigroup_resubmit_zero_recompiles(self):
        """Identical resubmit AND an under-high-water shrink of a merged
        multi-group fleet must not retrace any watched kernel: item counts
        are traced data, never static shape."""
        from karpenter_tpu.obs.trace import TraceRecorder

        pods = _merged_set(0, 10, "gold") + _merged_set(1, 8, "silver", cpu="300m")
        solver = TPUSolver(force=True, recorder=TraceRecorder(enabled=True))
        solver.solve(make_snapshot(list(pods)))
        # identical resubmit: zero
        solver.solve(make_snapshot(list(pods)))
        assert solver._trace.recompiles == {}, solver._trace.recompiles
        # shrink below the high-water mark (same shapes, fewer replicas): zero
        solver.solve(make_snapshot(list(pods[:-3])))
        assert solver._trace.recompiles == {}, solver._trace.recompiles
