"""Steady-state churn serving loop (karpenter_tpu/serving/).

Pins the three serving-mode mechanisms and their contracts:
- wake-up coalescing: N triggers during an in-flight solve cost exactly ONE
  batched follow-up solve that sees all N pods (Batcher begin/end bracket);
- double-buffering: the prestager's clone-identity cache changes scheduling
  of host work, never results — placements are bit-identical to serial
  execution with KARPENTER_SOLVER_DOUBLEBUF=0;
- shape stability: with KARPENTER_SOLVER_BUCKET=1 (high-water bucketing) a
  sustained churn run records ZERO recompiles after warmup, and delta solves
  actually serve the live provisioner (the clone-identity + node_generation
  machinery).
"""

from __future__ import annotations

import pytest

from helpers import make_pod
from karpenter_tpu import metrics as m
from karpenter_tpu.controllers.provisioning.batcher import Batcher
from karpenter_tpu.serving import ChurnHarness, ChurnSpec, PendingPrestager
from karpenter_tpu.utils.clock import FakeClock


def small_spec(**kw) -> ChurnSpec:
    base = dict(
        n_base_pods=160,
        n_types=12,
        arrivals=40,
        cancels=30,
        departures=40,
        bind_every=2,
        iterations=4,
        warmup_cycles=1,
        concurrent_seconds=0.0,
    )
    base.update(kw)
    return ChurnSpec(**base)


def placement_shape(env) -> list:
    """Node-name-free placement structure: one (instance-type, zone,
    frozenset of pod names) per node — random claim-name suffixes must not
    enter the parity comparison."""
    from karpenter_tpu.apis import labels as wk

    nodes = {n.metadata.name: n for n in env.store.list("Node")}
    groups: dict[str, set] = {}
    for p in env.store.list("Pod"):
        if p.spec.node_name:
            groups.setdefault(p.spec.node_name, set()).add(p.metadata.name)
    out = []
    for name, pods in groups.items():
        labels = nodes[name].metadata.labels if name in nodes else {}
        out.append((labels.get(wk.INSTANCE_TYPE_LABEL_KEY), labels.get(wk.ZONE_LABEL_KEY), frozenset(pods)))
    return sorted(out, key=lambda t: (t[0] or "", t[1] or "", sorted(t[2])))


class TestBatcherCoalescing:
    def test_reference_windows_without_solve_bracket(self):
        clock = FakeClock()
        b = Batcher(clock, idle_seconds=1.0, max_seconds=10.0)
        assert not b.ready()
        b.trigger("a")
        assert not b.ready()
        clock.step(1.5)
        assert b.ready()
        b.reset()
        assert not b.ready()

    def test_triggers_during_solve_arm_the_drain(self):
        clock = FakeClock()
        b = Batcher(clock, idle_seconds=1.0, max_seconds=10.0)
        b.begin_solve()
        for i in range(5):
            b.trigger(str(i))
        assert b.end_solve() == 5
        # no clock advance: the in-flight solve WAS the window
        assert b.ready()
        assert b.pending() == 5
        b.reset()
        assert not b.ready()

    def test_no_triggers_during_solve_means_no_drain(self):
        clock = FakeClock()
        b = Batcher(clock, idle_seconds=1.0, max_seconds=10.0)
        b.begin_solve()
        assert b.end_solve() == 0
        b.trigger("after")
        assert not b.ready()  # the idle window applies as before

    def test_n_triggers_during_inflight_solve_one_followup_sees_all(self):
        """The integration pin: pods created DURING a solve coalesce into
        exactly one follow-up solve whose batch contains all of them."""
        h = ChurnHarness(small_spec(n_base_pods=0)).build()
        env = h.env
        prov = env.provisioner
        solver = prov.solver
        seen_batches: list[int] = []
        injected = {"done": False}
        orig_solve = solver.solve

        def spying_solve(snap):
            seen_batches.append(len(snap.pods))
            if not injected["done"]:
                injected["done"] = True
                # mid-solve burst: 7 pods arrive while this solve is in flight
                h.apply_arrivals(7)
            return orig_solve(snap)

        solver.solve = spying_solve
        h.apply_arrivals(3)
        env.clock.step(1.0)
        assert prov.reconcile() is not None  # solve #1: the 3 pre-solve pods
        assert seen_batches == [3]
        # the 7 in-flight triggers armed the drain: ready NOW, no idle wait
        assert prov.batcher.ready()
        assert prov.reconcile() is not None  # ONE follow-up
        assert len(seen_batches) == 2
        assert seen_batches[1] == 10  # all 7 (plus the still-pending 3)
        assert env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).total() == 7
        assert not prov.batcher.ready()
        h.close()


class TestPrestager:
    def test_clone_identity_while_rv_unchanged(self):
        ps = PendingPrestager()
        pod = make_pod(cpu="1")
        c1 = ps.take(pod)
        assert c1 is not None and c1 is not pod
        c2 = ps.take(pod)
        assert c2 is c1, "same (uid, rv) must hand out the SAME clone object"
        assert ps.reused == 1

    def test_rv_bump_invalidates(self):
        ps = PendingPrestager()
        pod = make_pod(cpu="1")
        c1 = ps.take(pod)
        pod.metadata.resource_version = 99
        c2 = ps.take(pod)
        assert c2 is not c1

    def test_clone_is_stamped_and_content_equal(self):
        from karpenter_tpu.solver.encode import pod_signature

        ps = PendingPrestager()
        pod = make_pod(cpu="500m", memory="1Gi", labels={"a": "b"})
        clone = ps.take(pod)
        st = getattr(clone, "_sig_stamp", None)
        assert st is not None and st.rv == pod.metadata.resource_version
        assert st.sig == pod_signature(pod)

    def test_pvc_pods_bypass(self):
        ps = PendingPrestager()
        pod = make_pod(cpu="1", volumes=[{"name": "d", "persistentVolumeClaim": {"claimName": "x"}}])
        assert ps.take(pod) is None

    def test_store_events_evict(self):
        from karpenter_tpu.kube import Store

        store = Store()
        ps = PendingPrestager()
        ps.attach(store)
        store.create(make_pod(cpu="1", name="ev"))
        ps.pump()
        assert len(ps) == 1
        # binding makes it non-provisionable: evicted
        store.patch("Pod", "ev", lambda p: setattr(p.spec, "node_name", "n1"))
        ps.pump()
        assert len(ps) == 0

    def test_doublebuf_escape_hatch_disables(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
        h = ChurnHarness(small_spec(n_base_pods=0)).build()
        assert h.loop.prestager is None
        assert h.env.provisioner.prestager is None
        h.close()


class TestClusterGenerationSplit:
    def test_pending_pod_events_do_not_bump_node_generation(self):
        from karpenter_tpu.kube import Store
        from karpenter_tpu.state import Cluster
        from karpenter_tpu.state.informer import start_informers

        store, clock = Store(), FakeClock()
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        ng0 = cluster.node_generation
        store.create(make_pod(cpu="1", name="pend"))
        store.try_delete("Pod", "pend")
        assert cluster.generation > 0
        assert cluster.node_generation == ng0, "pending-pod create/delete must be rows-neutral"

    def test_bound_pod_events_bump_node_generation(self):
        from karpenter_tpu.kube import Store
        from karpenter_tpu.state import Cluster
        from karpenter_tpu.state.informer import start_informers

        store, clock = Store(), FakeClock()
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        ng0 = cluster.node_generation
        store.create(make_pod(cpu="1", name="bnd", node_name="node-1"))
        assert cluster.node_generation > ng0

    def test_anti_affinity_membership_bumps(self):
        from helpers import hostname_anti_affinity
        from karpenter_tpu.kube import Store
        from karpenter_tpu.state import Cluster
        from karpenter_tpu.state.informer import start_informers

        store, clock = Store(), FakeClock()
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        ng0 = cluster.node_generation
        store.create(make_pod(cpu="1", name="anti", anti_affinity=[hostname_anti_affinity({"matchLabels": {"a": "b"}})]))
        assert cluster.node_generation > ng0, "inverse-anti entries read the membership set"


class TestHighWaterBuckets:
    def test_monotone_and_resettable(self, monkeypatch):
        from karpenter_tpu.models.scheduler_model import bucket_hw, cap_hw, reset_bucket_highwater

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "1")
        reset_bucket_highwater()
        try:
            assert bucket_hw("t_axis", 5, 16) == 16
            assert bucket_hw("t_axis", 40, 16) == 48
            # oscillating back down: the mark holds
            assert bucket_hw("t_axis", 5, 16) == 48
            assert cap_hw("t_nnz", 1024) == 1024
            assert cap_hw("t_nnz", 256) == 1024
            reset_bucket_highwater()
            assert bucket_hw("t_axis", 5, 16) == 16
        finally:
            reset_bucket_highwater()

    def test_escape_hatch_restores_plain_bucketing(self, monkeypatch):
        from karpenter_tpu.models.scheduler_model import bucket, bucket_hw, reset_bucket_highwater

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "0")
        reset_bucket_highwater()
        assert bucket_hw("t_axis2", 40, 16) == bucket(40, 16)
        assert bucket_hw("t_axis2", 5, 16) == bucket(5, 16) == 16  # shrinks again

    def test_delta_pads_to_resident_tensor_axes(self):
        """item_pad_targets must mirror make_tensors' axes so a delta padded
        against an older resident carry always shape-matches it."""
        import numpy as np

        from karpenter_tpu.models.scheduler_model import make_tensors
        from karpenter_tpu.models.scheduler_model_grouped import item_pad_targets
        from karpenter_tpu.solver.encode import encode
        from test_solver import make_snapshot

        snap = make_snapshot([make_pod(cpu="1") for _ in range(4)])
        enc = encode(snap)
        t = make_tensors(enc, with_pods=False)
        tg = item_pad_targets(t)
        assert tg["res"] == int(t.pod_req.shape[1])
        assert tg["keys"] == int(t.pod_mask.shape[1])
        assert tg["words"] == int(t.pod_mask.shape[2])
        assert tg["groups"] == int(t.member.shape[1])
        assert tg["exist"] == int(t.existing_domset.shape[0])
        assert int(np.asarray(t.row_port_any).shape[1]) == tg["ports1"]


class TestStateNodeIncrementalTotals:
    def test_patch_total_matches_fresh_merge(self):
        from karpenter_tpu.state.statenode import StateNode
        from karpenter_tpu.utils import resources as res

        sn = StateNode()
        pods = [make_pod(cpu=f"{100 * (i + 1)}m", memory="256Mi", name=f"p{i}") for i in range(6)]
        for p in pods:
            sn.update_for_pod(p)
        assert sn.total_pod_requests() == res.merge(*sn.pod_requests.values())
        # removal keeps the incremental total exact
        sn.cleanup_for_pod(pods[2].key())
        assert sn.total_pod_requests() == res.merge(*sn.pod_requests.values())
        # re-adding an existing pod (rebind replay) must not double-count
        sn.update_for_pod(pods[0])
        assert sn.total_pod_requests() == res.merge(*sn.pod_requests.values())
        # shallow copies share (and keep) the memo without aliasing writes
        c = sn.shallow_copy()
        c.update_for_pod(make_pod(cpu="1", name="extra"))
        assert sn.total_pod_requests() == res.merge(*sn.pod_requests.values())


class TestChurnLoop:
    def test_doublebuffer_bit_parity_vs_serial(self, monkeypatch):
        """Identical scripted event sequences through the serving loop with
        the double buffer ON vs the KARPENTER_SOLVER_DOUBLEBUF=0 serial arm:
        the final placement structure must be identical — the prestager and
        delta path change scheduling of work, never results."""
        shapes = []
        for arm_on in (True, False):
            if arm_on:
                monkeypatch.delenv("KARPENTER_SOLVER_DOUBLEBUF", raising=False)
            else:
                monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
            h = ChurnHarness(small_spec()).build()
            h.provision_base_fleet()
            h.apply_departures(40)
            h.bind_flush()
            for _ in range(3):
                h.run_cycle()
            shapes.append(placement_shape(h.env))
            if arm_on:
                assert h.loop.prestager is not None
            else:
                assert h.loop.prestager is None
            h.close()
        assert shapes[0] == shapes[1]

    def test_zero_recompiles_under_sustained_churn(self, monkeypatch):
        """The sentinel pin: with high-water bucketing ON, the steady phase
        records ZERO recompiles (cold compiles land in warmup), and the
        delta path actually serves the live provisioner."""
        from karpenter_tpu.models.scheduler_model import reset_bucket_highwater

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "1")
        reset_bucket_highwater()
        try:
            h = ChurnHarness(small_spec(iterations=6, warmup_cycles=2))
            rep = h.run()
            h.close()
        finally:
            reset_bucket_highwater()
        assert rep.steady_recompiles == 0, rep.recompiles
        assert rep.solves > 0
        assert rep.modes.get("delta", 0) + rep.modes.get("hybrid-delta", 0) > 0, rep.modes
        assert rep.delta_hit_rate > 0.3
        assert rep.events > 0 and rep.events_per_sec > 0
        # re-solve latency quantiles come from the same machinery
        assert rep.p99_solve_seconds >= rep.p50_solve_seconds > 0

    def test_churn_metrics_families(self):
        h = ChurnHarness(small_spec(iterations=2, warmup_cycles=1))
        rep = h.run()
        reg = h.env.registry
        assert reg.counter(m.SOLVER_CHURN_EVENTS_TOTAL).value(event="arrival") > 0
        assert reg.counter(m.SOLVER_CHURN_EVENTS_TOTAL).value(event="departure") > 0
        hist = reg.histogram(m.SOLVER_CHURN_EVENTS_PER_SOLVE)
        assert hist.count() > 0
        # gauge exists and holds the post-solve queue depth (>= 0)
        assert reg.gauge(m.SOLVER_CHURN_QUEUE_DEPTH).value() >= 0
        assert rep.events > 0
        h.close()

    @pytest.mark.slow
    def test_worker_thread_liveness_and_results(self):
        """The threaded prestager (real-TPU mode) must stage asynchronously
        and leave results placement-valid."""
        h = ChurnHarness(small_spec(worker=True, iterations=2, warmup_cycles=1))
        rep = h.run()
        assert h.loop.prestager is not None
        assert h.loop.prestager.staged > 0
        assert rep.solves > 0
        h.close()
