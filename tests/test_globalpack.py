"""globalpack acceptance suite (ISSUE 16).

The joint provisioning+consolidation convex solve (`models/globalpack`) is a
RELAXATION riding the same exact hosts as the two-phase LP: every contract
here pins that turning the global mode on can only improve the objective,
never correctness —

  * the global solve's exact-validated best command saves at least what the
    two-phase LP ladder's does (randomized fleets),
  * every emitted command passed `compute_consolidation` exact validation
    (no proposal becomes a command without a simulation verdict),
  * `KARPENTER_SOLVER_GLOBALPACK` off (the default) preserves bit-identical
    two-phase behavior — `_globalpack_option` is never entered,
  * repeated global rounds record ZERO warm recompiles (sentinel-verified),
    including when two-phase and global rounds interleave (shared jit cache
    via the zero-pending delegation),
  * the bounded karpenter_solver_globalpack_* family and the
    proposer="globalpack" enum value are published,
  * the second customers work: `FleetFrontend.rebalance` (hatch-gated probe)
    and faultline's revocation path (`ChurnHarness.repack_savings`).
"""

import random

import pytest

from helpers import make_pod
from karpenter_tpu.controllers.disruption.methods import (
    MultiNodeConsolidation,
    _command_savings_per_hour,
)

from test_consolidation_lp import consolidation_method, flip_consolidatable
from test_consolidation_tpu import build_fleet


class TestGlobalObjective:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_global_savings_at_least_two_phase_randomized(self, seed):
        """Randomized underutilized fleets: the global solve's first
        exact-validated command must save at least what the two-phase LP
        ladder's does on the same fleet."""
        rng = random.Random(seed)
        n = rng.randrange(4, 8)
        env = build_fleet(n, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        deadline = env.clock.now() + 60.0
        global_cmd = m._globalpack_option(cands, deadline)
        lp_cmd = m._lp_option(cands, deadline)
        global_savings = _command_savings_per_hour(global_cmd)
        lp_savings = _command_savings_per_hour(lp_cmd)
        assert global_savings >= lp_savings - 1e-9, (n, global_savings, lp_savings)
        assert global_savings > 0

    def test_pending_pods_enter_the_joint_solve(self):
        """With pending pods in the cluster the global round still emits a
        validated command, and the proposer's encode saw the pending axis
        (trace span attribution n_pending > 0)."""
        env = build_fleet(6, solver_backend="tpu")
        flip_consolidatable(env)
        for i in range(3):
            env.store.create(make_pod(cpu="300m", name=f"gp-pend-{i}"))
        m, cands = consolidation_method(env)
        rec = env.provisioner.solver.recorder
        cmd = m._globalpack_option(cands, env.clock.now() + 60.0)
        assert cmd.candidates
        traces = [t for t in rec.traces() if t.backend == "globalpack"]
        assert traces, "no globalpack flight record"
        t = traces[-1]
        for phase in ("encode_candidates", "globalpack", "round", "validate"):
            assert phase in t.phase_totals, (phase, t.phase_totals)
        assert t.attribution.get("globalpack_proposals", 0) >= 1


class TestEveryProposalValidated:
    def test_emitted_command_is_a_validated_verdict(self, monkeypatch):
        """The global arm may only return what compute_consolidation
        produced: spy every exact-validation probe and require the emitted
        command to be one of the spy's verdicts, candidate-set included."""
        env = build_fleet(6, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        validated = []
        orig = MultiNodeConsolidation.compute_consolidation

        def spy(self, candidates, reuse=None):
            cmd = orig(self, candidates, reuse=reuse)
            validated.append(cmd)
            return cmd

        monkeypatch.setattr(MultiNodeConsolidation, "compute_consolidation", spy)
        cmd = m._globalpack_option(cands, env.clock.now() + 60.0)
        assert cmd.candidates, "global repack found no command on an idle fleet"
        assert validated, "command emitted without any exact-validation probe"
        assert any(v is cmd for v in validated), "emitted command bypassed validation"
        from karpenter_tpu.controllers.disruption.helpers import (
            all_non_pending_scheduled,
            simulate_scheduling,
        )

        results = simulate_scheduling(env.provisioner, env.cluster, cmd.candidates, env.clock)
        assert all_non_pending_scheduled(results, cmd.candidates)


class TestEscapeHatch:
    def test_hatch_off_is_bit_identical_two_phase(self, monkeypatch):
        """Default (hatch off): compute_commands must run EXACTLY the
        two-phase LP ladder — `_globalpack_option` is never entered — and
        emit its verdict verbatim."""
        monkeypatch.delenv("KARPENTER_SOLVER_GLOBALPACK", raising=False)
        env = build_fleet(5, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        reference = m._lp_option(cands, env.clock.now() + 60.0)
        assert reference.candidates

        monkeypatch.setattr(MultiNodeConsolidation, "_globalpack_option_iter", None)  # must not be called
        captured = {}
        orig = MultiNodeConsolidation._lp_option_iter

        def spy(self, candidates, deadline):
            for cmd in orig(self, candidates, deadline):
                captured.setdefault("cmd", cmd)
                yield cmd

        monkeypatch.setattr(MultiNodeConsolidation, "_lp_option_iter", spy)
        budgets = {env.store.list("NodePool")[0].metadata.name: 100}
        m2, cands2 = consolidation_method(env)
        m2.compute_commands(cands2, budgets)
        assert "cmd" in captured, "two-phase LP did not run with the hatch off"
        assert captured["cmd"].candidate_names() == reference.candidate_names()
        assert abs(_command_savings_per_hour(captured["cmd"]) - _command_savings_per_hour(reference)) < 1e-9

    def test_hatch_on_routes_through_globalpack(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_GLOBALPACK", "1")
        env = build_fleet(5, solver_backend="tpu")
        flip_consolidatable(env)
        captured = {}
        orig = MultiNodeConsolidation._globalpack_option_iter

        def spy(self, candidates, deadline):
            for cmd in orig(self, candidates, deadline):
                captured.setdefault("cmd", cmd)
                yield cmd

        monkeypatch.setattr(MultiNodeConsolidation, "_globalpack_option_iter", spy)
        budgets = {env.store.list("NodePool")[0].metadata.name: 100}
        m, cands = consolidation_method(env)
        m.compute_commands(cands, budgets)
        assert "cmd" in captured, "hatch on did not route through the global arm"
        assert captured["cmd"].candidates


class TestZeroWarmRecompiles:
    def test_repeated_global_rounds_record_zero_recompiles(self):
        """Shape bucketing holds across global rounds on a stable fleet —
        AND across interleaved two-phase rounds, because the zero-pending
        delegation shares one jit cache with the global kernels."""
        from karpenter_tpu.obs.trace import sentinel

        env = build_fleet(5, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        deadline = env.clock.now() + 60.0
        m._globalpack_option(cands, deadline)  # cold: compiles allowed
        m._lp_option(cands, deadline)
        before = sentinel().snapshot()
        for _ in range(2):
            cmd = m._globalpack_option(cands, deadline)
            assert cmd.candidates
            m._lp_option(cands, deadline)
        delta = sentinel().delta(before)
        assert not delta, f"warm global rounds recompiled: {delta}"


class TestGlobalpackMetrics:
    def test_bounded_family_and_proposer_enum_published(self):
        from karpenter_tpu import metrics as mm

        env = build_fleet(4, solver_backend="tpu")
        flip_consolidatable(env)
        m, cands = consolidation_method(env)
        cmd = m._globalpack_option(cands, env.clock.now() + 60.0)
        assert cmd.candidates
        reg = env.disruption.ctx.metrics
        assert reg.counter(mm.SOLVER_GLOBALPACK_ROUNDS_TOTAL).total() > 0
        assert reg.counter(mm.SOLVER_GLOBALPACK_ITERATIONS_TOTAL).total() > 0
        assert reg.gauge(mm.SOLVER_GLOBALPACK_OBJECTIVE_IMPROVEMENT).value() >= 0.0
        assert reg.counter(mm.SOLVER_CONSOLIDATION_PROPOSALS_TOTAL).value(proposer="globalpack") > 0
        assert reg.gauge(mm.SOLVER_CONSOLIDATION_SAVINGS_PER_HOUR).value(proposer="globalpack") > 0


class TestSecondCustomers:
    def test_fleet_rebalance_hatch_gated(self, monkeypatch):
        """FleetFrontend.rebalance: {} with the hatch off; a plan summary
        (proposals/objective_improvement/rounded) with it on — computed via
        TPUSolver.global_repack_plan, nothing executed."""
        from karpenter_tpu.serving import ChurnSpec
        from karpenter_tpu.serving.fleet import FleetFrontend, reset_tenant_labels

        from test_fleet import add_churn_tenant

        reset_tenant_labels()
        fleet = FleetFrontend()
        try:
            h = add_churn_tenant(fleet, "t-gp", ChurnSpec(n_base_pods=12, n_types=6, concurrent_seconds=0.0))
            h.provision_base_fleet()
            h.env.clock.step(40)
            h.env.nodeclaim_disruption.reconcile()
            monkeypatch.delenv("KARPENTER_SOLVER_GLOBALPACK", raising=False)
            assert fleet.rebalance("t-gp") == {}
            monkeypatch.setenv("KARPENTER_SOLVER_GLOBALPACK", "1")
            assert fleet.rebalance("no-such-tenant") == {}
            out = fleet.rebalance("t-gp")
            assert set(out) >= {"proposals", "objective_improvement", "rounded"}
        finally:
            fleet.close()
            reset_tenant_labels()

    def test_revocation_repack_recovers_at_least_two_phase(self):
        """faultline's revocation path: after a spot reclaim the global
        solve's exact-validated recovery must match or beat the greedy
        two-phase ladder on the shrunken fleet."""
        from karpenter_tpu.serving import ChurnHarness, ChurnSpec

        h = ChurnHarness(ChurnSpec(n_base_pods=24, n_types=8, seed=11, concurrent_seconds=0.0)).build()
        try:
            h.provision_base_fleet()
            h.apply_departures(12)
            names = sorted(nd.metadata.name for nd in h.env.store.borrow_list("Node"))
            assert names
            h.revoke_node(names[0])
            two = h.repack_savings(mode="two-phase")
            glob = h.repack_savings(mode="global")
        finally:
            h.close()
        assert glob >= two - 1e-9, (glob, two)
