"""Consolidation compute caps + cross-round fairness depth specs.

Reference: multinodeconsolidation.go:35,117-191 (1-min binary-search budget),
singlenodeconsolidation.go:33-176 (3-min budget, PreviouslyUnseenNodePools
interweave carry-over, CanPassThreshold pre-filter, ConsolidationTimeoutsTotal).
"""

from types import SimpleNamespace

from karpenter_tpu import metrics as m
from karpenter_tpu.apis.nodepool import BALANCED
from karpenter_tpu.controllers.disruption.balanced import NodePoolTotals
from karpenter_tpu.controllers.disruption.controller import _Ctx
from karpenter_tpu.controllers.disruption.methods import (
    MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS,
    SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.types import Command
from karpenter_tpu.metrics import make_registry
from karpenter_tpu.utils.clock import FakeClock


def make_candidate(pool_name: str, cost: float = 1.0, price: float = 1.0, policy: str = "WhenEmptyOrUnderutilized"):
    node_pool = SimpleNamespace(
        metadata=SimpleNamespace(name=pool_name),
        spec=SimpleNamespace(disruption=SimpleNamespace(consolidation_policy=policy)),
    )
    c = SimpleNamespace(
        node_pool=node_pool,
        disruption_cost=cost,
        reschedule_disruption_cost=1.0,
        price=price,
        name=lambda: pool_name,
    )
    c.savings_ratio = lambda: c.price / c.reschedule_disruption_cost
    return c


def make_ctx(clock=None, registry=None):
    clock = clock or FakeClock()
    ctx = _Ctx(
        store=None,
        cluster=None,
        provisioner=None,
        clock=clock,
        options=SimpleNamespace(solver_backend="ffd", feature_gates=SimpleNamespace(spot_to_spot_consolidation=False)),
        metrics=registry if registry is not None else make_registry(),
    )
    return ctx


class TestSingleNodeTimeout:
    def _method(self, ctx):
        method = SingleNodeConsolidation(ctx)
        method.should_disrupt = lambda c: True
        return method

    def test_timeout_aborts_and_carries_unseen_pools(self):
        # singlenodeconsolidation.go:61-74: on timeout the round returns
        # nothing, counts the timeout, and saves the not-yet-seen pools
        ctx = make_ctx()
        method = self._method(ctx)
        # every simulation "costs" 100s on the deterministic clock
        method.compute_consolidation = lambda cs: (ctx.clock.step(100.0), Command())[1]
        cands = [make_candidate(p, cost=i) for p in ("pa", "pb", "pc") for i in range(2)]
        budgets = {"pa": 10, "pb": 10, "pc": 10}
        out = method.compute_commands(cands, budgets)
        assert out == []
        # interweave order is pa0, pb0, pc0, ...: after 100s+100s candidates
        # pa0/pb0 evaluated; the pc check at t=200 > 180 aborts before pc
        assert method.previously_unseen_node_pools == {"pc"}
        assert (
            ctx.metrics.counter(m.DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL).value(consolidation_type="single") == 1
        )

    def test_unseen_pools_go_first_next_round(self):
        # shuffleCandidates (singlenodeconsolidation.go:143-176): pools unseen
        # after a timeout lead the next round's interweave
        ctx = make_ctx()
        method = self._method(ctx)
        method.previously_unseen_node_pools = {"pc"}
        cands = [make_candidate(p, cost=i) for p in ("pa", "pb", "pc") for i in range(2)]
        ordered = method.sort_candidates(cands)
        assert ordered[0].node_pool.metadata.name == "pc"
        # round-robin across pools, unseen-first within each wave
        wave1 = [c.node_pool.metadata.name for c in ordered[:3]]
        assert wave1 == ["pc", "pa", "pb"]

    def test_interweave_prevents_one_pool_starvation(self):
        # the plain cost sort would put all of pool-big first; the interweave
        # alternates pools so each wave touches every pool once
        ctx = make_ctx()
        method = self._method(ctx)
        cands = [make_candidate("big", cost=i) for i in range(5)]
        cands += [make_candidate("small", cost=100 + i) for i in range(2)]
        ordered = method.sort_candidates(cands)
        names = [c.node_pool.metadata.name for c in ordered]
        assert names[:4] == ["big", "small", "big", "small"]

    def test_no_timeout_clears_unseen(self):
        ctx = make_ctx()
        method = self._method(ctx)
        method.previously_unseen_node_pools = {"stale"}
        method.compute_consolidation = lambda cs: Command()
        cands = [make_candidate("pa"), make_candidate("pb")]
        method.compute_commands(cands, {"pa": 1, "pb": 1})
        assert method.previously_unseen_node_pools == set()

    def test_can_pass_threshold_skips_simulation(self):
        # singlenodeconsolidation.go:88-90 + balanced.go:285-299: a Balanced
        # candidate whose best-case (full delete) score fails 1/k is skipped
        # without paying for the scheduling simulation
        ctx = make_ctx()
        ctx.node_pool_totals = {"bal": NodePoolTotals(total_cost=1e9, total_disruption_cost=1.0)}
        method = self._method(ctx)
        calls = []
        method.compute_consolidation = lambda cs: (calls.append(cs), Command())[1]
        bad = make_candidate("bal", price=1.0, policy=BALANCED)
        method.compute_commands([bad], {"bal": 1})
        assert calls == []  # pre-filter rejected before simulation

    def test_can_pass_threshold_lets_good_candidates_through(self):
        ctx = make_ctx()
        ctx.node_pool_totals = {"bal": NodePoolTotals(total_cost=10.0, total_disruption_cost=100.0)}
        method = self._method(ctx)
        calls = []
        method.compute_consolidation = lambda cs: (calls.append(cs), Command())[1]
        good = make_candidate("bal", price=5.0, policy=BALANCED)  # delete score >> 1/k
        method.compute_commands([good], {"bal": 1})
        assert len(calls) == 1

    def test_non_balanced_pools_always_pass_prefilter(self):
        ctx = make_ctx()
        method = self._method(ctx)
        calls = []
        method.compute_consolidation = lambda cs: (calls.append(cs), Command())[1]
        method.compute_commands([make_candidate("plain", price=0.0)], {"plain": 1})
        assert len(calls) == 1


class TestMultiNodeTimeout:
    def test_timeout_returns_last_valid_command(self):
        # multinodeconsolidation.go:139-152: binary search aborts on deadline
        # and returns the last batch that validated
        ctx = make_ctx()
        method = MultiNodeConsolidation(ctx)
        cands = [make_candidate(f"p{i}") for i in range(8)]
        saved = Command(reason="underutilized", candidates=cands[:4])

        def slow_probe(cs):
            ctx.clock.step(70.0)  # one probe blows the 60s budget
            return saved

        method.compute_consolidation = slow_probe
        out = method._first_n_consolidation_option(cands)
        assert out is saved
        assert (
            ctx.metrics.counter(m.DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL).value(consolidation_type="multi") == 1
        )

    def test_timeout_with_no_valid_command_returns_empty(self):
        ctx = make_ctx()
        method = MultiNodeConsolidation(ctx)
        cands = [make_candidate(f"p{i}") for i in range(8)]

        def slow_failing_probe(cs):
            ctx.clock.step(70.0)
            return Command()

        method.compute_consolidation = slow_failing_probe
        out = method._first_n_consolidation_option(cands)
        assert not out.candidates

    def test_fast_search_unaffected_by_budget(self):
        ctx = make_ctx()
        method = MultiNodeConsolidation(ctx)
        cands = [make_candidate(f"p{i}") for i in range(8)]
        probes = []

        def fast_probe(cs):
            probes.append(len(cs))
            return Command(reason="underutilized", candidates=list(cs))

        method.compute_consolidation = fast_probe
        out = method._first_n_consolidation_option(cands)
        assert len(out.candidates) == 8  # full batch found
        assert ctx.metrics.counter(m.DISRUPTION_CONSOLIDATION_TIMEOUTS_TOTAL).total() == 0

    def test_budget_constants_match_reference(self):
        assert MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS == 60.0  # multinodeconsolidation.go:35
        assert SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS == 180.0  # singlenodeconsolidation.go:33
