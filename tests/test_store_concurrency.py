"""kube.Store concurrency stress — the race-detector analogue.

Reference: the Go suites run under `go test -race` (Makefile:104-111) and the
state layer is mutex/atomic-based (cluster.go:60-100). Python has no race
detector, so these specs hammer the store from many threads and assert the
invariants the informer stack depends on: monotonic resourceVersions,
optimistic-concurrency conflict detection, watch delivery in commit order
(ADDED < MODIFIED < DELETED per object), and no lost updates.
"""

import threading

import pytest

from karpenter_tpu.kube import ObjectMeta, Pod, Store
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

N_THREADS = 8
N_OBJECTS = 40


class TestStoreConcurrency:
    def test_concurrent_creates_unique_resource_versions(self):
        store = Store()
        errors = []

        def create(worker):
            try:
                for i in range(N_OBJECTS):
                    store.create(Pod(metadata=ObjectMeta(name=f"w{worker}-p{i}")))
            except Exception as e:  # pragma: no cover - failure reporting
                errors.append(e)

        threads = [threading.Thread(target=create, args=(w,)) for w in range(N_THREADS)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errors
        pods = store.list("Pod")
        assert len(pods) == N_THREADS * N_OBJECTS
        rvs = [p.metadata.resource_version for p in pods]
        assert len(set(rvs)) == len(rvs), "resourceVersions must be unique per commit"

    def test_concurrent_patches_lose_no_increments(self):
        # patch() is read-modify-write under the store lock: N_THREADS x K
        # increments on one annotation must all land
        store = Store()
        store.create(Pod(metadata=ObjectMeta(name="ctr", annotations={"n": "0"})))
        K = 50

        def bump():
            for _ in range(K):
                store.patch("Pod", "ctr", lambda p: p.metadata.annotations.update(n=str(int(p.metadata.annotations["n"]) + 1)))

        threads = [threading.Thread(target=bump) for _ in range(N_THREADS)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert int(store.get("Pod", "ctr").metadata.annotations["n"]) == N_THREADS * K

    def test_stale_update_conflicts(self):
        # two writers racing update() on one snapshot: exactly one wins, the
        # loser gets a resourceVersion conflict
        store = Store()
        store.create(Pod(metadata=ObjectMeta(name="race")))
        snap_a = store.get("Pod", "race")
        snap_b = store.get("Pod", "race")
        snap_a.metadata.annotations["who"] = "a"
        snap_b.metadata.annotations["who"] = "b"
        store.update(snap_a)
        with pytest.raises(Exception):
            store.update(snap_b)

    def test_watch_order_per_object(self):
        # watchers must observe each object's events in commit order even with
        # concurrent writers: ADDED first, MODIFIED rvs strictly increasing,
        # DELETED last
        store = Store()
        log: dict[str, list] = {}
        lock = threading.Lock()

        def watch(event, obj):
            with lock:
                log.setdefault(obj.metadata.name, []).append((event, obj.metadata.resource_version))

        store.watch("Pod", watch)

        def churn(worker):
            for i in range(N_OBJECTS):
                name = f"w{worker}-p{i}"
                store.create(Pod(metadata=ObjectMeta(name=name)))
                store.patch("Pod", name, lambda p: p.metadata.annotations.update(x="1"))
                store.patch("Pod", name, lambda p: p.metadata.annotations.update(x="2"))
                store.delete("Pod", name, grace=False)

        threads = [threading.Thread(target=churn, args=(w,)) for w in range(N_THREADS)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert len(log) == N_THREADS * N_OBJECTS
        for name, events in log.items():
            kinds = [e for e, _ in events]
            assert kinds[0] == "ADDED", f"{name}: {kinds}"
            assert kinds[-1] == "DELETED", f"{name}: {kinds}"
            assert kinds.count("ADDED") == 1 and kinds.count("DELETED") == 1
            rvs = [rv for _, rv in events]
            assert rvs == sorted(rvs), f"{name}: out-of-order resourceVersions {rvs}"

    def test_cluster_state_consistent_under_churn(self):
        # informers driven from many threads: the cluster mirror must end
        # exactly consistent with the store
        store, clock = Store(), FakeClock()
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        from karpenter_tpu.kube import Node
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        def churn(worker):
            for i in range(20):
                name = f"n{worker}-{i}"
                store.create(
                    Node(
                        metadata=ObjectMeta(name=name),
                        spec=NodeSpec(provider_id=f"kwok://{name}"),
                        status=NodeStatus(
                            capacity=parse_resource_list({"cpu": "4"}),
                            allocatable=parse_resource_list({"cpu": "4"}),
                        ),
                    )
                )
                if i % 3 == 0:
                    store.delete("Node", name, grace=False)

        threads = [threading.Thread(target=churn, args=(w,)) for w in range(N_THREADS)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        live = {n.metadata.name for n in store.list("Node")}
        mirrored = {sn.name() for sn in cluster.nodes()}
        assert mirrored == live
        assert cluster.generation > 0
