"""Volume topology + volume-limit behavior specs.

Modeled on the reference's provisioning/scheduling volumetopology_test.go and
the VolumeUsage coverage in suite_test.go.
"""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.controllers.provisioning.scheduling import Scheduler
from karpenter_tpu.controllers.provisioning.scheduling.volumetopology import VolumeTopology
from karpenter_tpu.kube import (
    CSINode,
    CSINodeDriver,
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    Store,
)
from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
from karpenter_tpu.scheduling.volumeusage import BIND_COMPLETED_ANNOTATION, VolumeUsage, get_volumes
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.resources import parse_resource_list

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]

CSI = "csi.test.io"


def build_env():
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    np = make_nodepool(requirements=LINUX_AMD64)
    store.create(np)
    return store, clock, cluster, [np], catalog.construct_instance_types()


def make_scheduler(store, clock, cluster, pools, types):
    return Scheduler(store, cluster, pools, {np.metadata.name: types for np in pools}, cluster.nodes(), [], clock)


def bound_pvc(store, name, zone=None, driver=CSI, local=False, hostname_term=False, ns="default"):
    """A PVC bound to a PV, optionally carrying zone node affinity."""
    terms = []
    if zone is not None:
        terms.append([{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": [zone]}])
    if hostname_term:
        terms.append([{"key": wk.HOSTNAME_LABEL_KEY, "operator": "In", "values": ["old-node"]}])
    pv = PersistentVolume(metadata=ObjectMeta(name=f"pv-{name}"), csi_driver=driver, node_affinity_required=terms, local=local)
    store.create(pv)
    pvc = PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace=ns, annotations={BIND_COMPLETED_ANNOTATION: "yes"}),
        volume_name=f"pv-{name}",
        phase="Bound",
    )
    store.create(pvc)
    return pvc


def pod_with_pvcs(*claim_names, **kw):
    pod = make_pod(**kw)
    pod.spec.volumes = [{"name": f"v{i}", "persistentVolumeClaim": {"claimName": c}} for i, c in enumerate(claim_names)]
    return pod


class TestVolumeTopology:
    def test_bound_pv_zone_pins_nodeclaim(self):
        store, clock, cluster, pools, types = build_env()
        bound_pvc(store, "claim-a", zone="test-zone-b")
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("claim-a")])
        assert results.all_pods_scheduled()
        req = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert req.values_list() == ["test-zone-b"]

    def test_storage_class_allowed_topologies(self):
        store, clock, cluster, pools, types = build_env()
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="wait-sc"),
                provisioner=CSI,
                volume_binding_mode="WaitForFirstConsumer",
                allowed_topologies=[[{"key": wk.ZONE_LABEL_KEY, "values": ["test-zone-c"]}]],
            )
        )
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="unbound"), storage_class_name="wait-sc"))
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("unbound")])
        assert results.all_pods_scheduled()
        req = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert req.values_list() == ["test-zone-c"]

    def test_multiple_allowed_topology_terms_are_alternatives(self):
        # SC allows zones a OR b; the pod's selector pins b — the b alternative
        # must be chosen rather than failing on the first term
        store, clock, cluster, pools, types = build_env()
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="multi-sc"),
                provisioner=CSI,
                volume_binding_mode="WaitForFirstConsumer",
                allowed_topologies=[
                    [{"key": wk.ZONE_LABEL_KEY, "values": ["test-zone-a"]}],
                    [{"key": wk.ZONE_LABEL_KEY, "values": ["test-zone-b"]}],
                ],
            )
        )
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="unbound"), storage_class_name="multi-sc"))
        s = make_scheduler(store, clock, cluster, pools, types)
        pod = pod_with_pvcs("unbound", node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})
        results = s.solve([pod])
        assert results.all_pods_scheduled()
        req = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert req.values_list() == ["test-zone-b"]

    def test_conflicting_volume_zones_unschedulable(self):
        store, clock, cluster, pools, types = build_env()
        bound_pvc(store, "in-a", zone="test-zone-a")
        bound_pvc(store, "in-b", zone="test-zone-b")
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("in-a", "in-b")])
        assert not results.all_pods_scheduled()

    def test_local_volume_hostname_affinity_ignored(self):
        store, clock, cluster, pools, types = build_env()
        bound_pvc(store, "local-claim", local=True, hostname_term=True)
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("local-claim")])
        # hostname-only terms on local PVs are unconstrained alternatives
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1

    def test_get_requirements_empty_without_volumes(self):
        store, *_ = build_env()
        vt = VolumeTopology(store)
        assert vt.get_requirements(make_pod()) == []


class TestPVCValidation:
    def _validate(self, store, pod):
        return VolumeTopology(store).validate_persistent_volume_claims(pod)

    def test_missing_pvc_rejected(self):
        store, *_ = build_env()
        assert "not found" in self._validate(store, pod_with_pvcs("ghost"))

    def test_unbound_immediate_rejected(self):
        store, *_ = build_env()
        store.create(StorageClass(metadata=ObjectMeta(name="imm"), provisioner=CSI, volume_binding_mode="Immediate"))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="c"), storage_class_name="imm"))
        assert "immediate" in self._validate(store, pod_with_pvcs("c"))

    def test_unbound_wait_for_first_consumer_ok(self):
        store, *_ = build_env()
        store.create(StorageClass(metadata=ObjectMeta(name="w"), provisioner=CSI, volume_binding_mode="WaitForFirstConsumer"))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="c"), storage_class_name="w"))
        assert self._validate(store, pod_with_pvcs("c")) is None

    def test_bound_without_bind_annotation_rejected(self):
        store, *_ = build_env()
        store.create(PersistentVolume(metadata=ObjectMeta(name="pv-x"), csi_driver=CSI))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="c"), volume_name="pv-x"))
        assert BIND_COMPLETED_ANNOTATION in self._validate(store, pod_with_pvcs("c"))

    def test_bound_valid_ok(self):
        store, *_ = build_env()
        bound_pvc(store, "good", zone="test-zone-a")
        assert self._validate(store, pod_with_pvcs("good")) is None

    def test_lost_pvc_rejected(self):
        store, *_ = build_env()
        pvc = PersistentVolumeClaim(metadata=ObjectMeta(name="lost"), volume_name="gone", phase="Lost")
        store.create(pvc)
        assert "non-existent" in self._validate(store, pod_with_pvcs("lost"))

    def test_provisioner_skips_invalid_pvc_pods(self):
        from karpenter_tpu.cloudprovider.kwok import KWOKCloudProvider
        from karpenter_tpu.controllers.provisioning.provisioner import Provisioner

        store, clock, cluster, pools, types = build_env()
        prov = Provisioner(store, cluster, KWOKCloudProvider(store, types, clock), clock)
        store.create(pod_with_pvcs("ghost", name="bad-pod"))
        store.create(make_pod(name="good-pod"))
        pending = prov.get_pending_pods()
        assert [p.metadata.name for p in pending] == ["good-pod"]


class TestVolumeLimits:
    def test_volume_usage_limits(self):
        u = VolumeUsage()
        u.add_limit(CSI, 2)
        u.add("p1", {CSI: {"default/a"}})
        assert u.exceeds_limits({CSI: {"default/b"}}) is None
        u.add("p2", {CSI: {"default/b"}})
        assert u.exceeds_limits({CSI: {"default/c"}}) is not None
        # duplicate PVC on another pod does not double count
        assert u.exceeds_limits({CSI: {"default/a"}}) is None
        u.remove("p2")
        assert u.exceeds_limits({CSI: {"default/c"}}) is None

    def test_existing_node_respects_csinode_limit(self):
        store, clock, cluster, pools, types = build_env()
        for c in ("c1", "c2", "c3"):
            bound_pvc(store, c)
        nc = NodeClaim(metadata=ObjectMeta(name="claim-1", labels={wk.NODEPOOL_LABEL_KEY: "default-pool"}))
        nc.status.provider_id = "kwok://n1"
        nc.status.conditions.set_true(COND_REGISTERED)
        nc.status.conditions.set_true(COND_INITIALIZED)
        store.create(nc)
        store.create(CSINode(metadata=ObjectMeta(name="n1"), drivers=[CSINodeDriver(name=CSI, allocatable_count=2)]))
        store.create(
            Node(
                metadata=ObjectMeta(
                    name="n1",
                    labels={
                        wk.NODEPOOL_LABEL_KEY: "default-pool",
                        wk.HOSTNAME_LABEL_KEY: "n1",
                        wk.ZONE_LABEL_KEY: "test-zone-a",
                        wk.ARCH_LABEL_KEY: "amd64",
                        wk.OS_LABEL_KEY: "linux",
                    },
                ),
                spec=NodeSpec(provider_id="kwok://n1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "16", "memory": "32Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "16", "memory": "32Gi", "pods": "110"}),
                ),
            )
        )
        s = make_scheduler(store, clock, cluster, pools, types)
        pods = [pod_with_pvcs(c, name=f"pod-{c}", cpu="100m") for c in ("c1", "c2", "c3")]
        results = s.solve(pods)
        assert results.all_pods_scheduled()
        # only two volume-bearing pods fit the node's CSI attach limit
        assert results.node_pod_count().get("n1") == 2
        assert len(results.new_node_claims) == 1

    def test_state_node_tracks_bound_pod_volumes(self):
        store, clock, cluster, pools, types = build_env()
        bound_pvc(store, "c1")
        store.create(
            Node(
                metadata=ObjectMeta(name="n1", labels={wk.HOSTNAME_LABEL_KEY: "n1"}),
                spec=NodeSpec(provider_id="kwok://n1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "4", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "4", "pods": "110"}),
                ),
            )
        )
        pod = pod_with_pvcs("c1", name="bound-pod", node_name="n1")
        store.create(pod)
        sn = cluster.node_for_name("n1")
        assert sn.volume_usage.exceeds_limits({}) is None
        sn.volume_usage.add_limit(CSI, 1)
        assert sn.volume_usage.exceeds_limits({CSI: {"default/other"}}) is not None

    def test_get_volumes_resolves_drivers(self):
        store, *_ = build_env()
        bound_pvc(store, "c1")
        store.create(StorageClass(metadata=ObjectMeta(name="w"), provisioner="other.csi"))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="c2"), storage_class_name="w"))
        pod = pod_with_pvcs("c1", "c2")
        vols = get_volumes(store, pod)
        assert vols == {CSI: {"default/c1"}, "other.csi": {"default/c2"}}

    def test_ephemeral_volume_resolves_pod_scoped_claim(self):
        store, *_ = build_env()
        pod = make_pod(name="web-0")
        pod.spec.volumes = [{"name": "scratch", "ephemeral": {}}]
        bound_pvc(store, "web-0-scratch")
        vols = get_volumes(store, pod)
        assert vols == {CSI: {"default/web-0-scratch"}}

    def test_ephemeral_template_constrains_before_pvc_exists(self):
        # the ephemeral controller hasn't created the PVC yet: the
        # volumeClaimTemplate's StorageClass topology must still apply
        store, clock, cluster, pools, types = build_env()
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="zonal"),
                provisioner=CSI,
                volume_binding_mode="WaitForFirstConsumer",
                allowed_topologies=[[{"key": wk.ZONE_LABEL_KEY, "values": ["test-zone-d"]}]],
            )
        )
        pod = make_pod(name="eph-0")
        pod.spec.volumes = [
            {"name": "scratch", "ephemeral": {"volumeClaimTemplate": {"spec": {"storageClassName": "zonal"}}}}
        ]
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod])
        assert results.all_pods_scheduled()
        req = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert req.values_list() == ["test-zone-d"]

    def test_default_storage_class_applies(self):
        from karpenter_tpu.scheduling.volumeusage import DEFAULT_STORAGE_CLASS_ANNOTATION

        store, clock, cluster, pools, types = build_env()
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="standard", annotations={DEFAULT_STORAGE_CLASS_ANNOTATION: "true"}),
                provisioner=CSI,
                volume_binding_mode="WaitForFirstConsumer",
                allowed_topologies=[[{"key": wk.ZONE_LABEL_KEY, "values": ["test-zone-b"]}]],
            )
        )
        # PVC with storageClassName omitted relies on the cluster default
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="dflt"), storage_class_name=None))
        assert VolumeTopology(store).validate_persistent_volume_claims(pod_with_pvcs("dflt")) is None
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("dflt")])
        assert results.all_pods_scheduled()
        req = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert req.values_list() == ["test-zone-b"]
        assert get_volumes(store, pod_with_pvcs("dflt")) == {CSI: {"default/dflt"}}

    def test_csinode_arriving_after_node_applies_limits(self):
        store, clock, cluster, pools, types = build_env()
        store.create(
            Node(
                metadata=ObjectMeta(name="n1", labels={wk.HOSTNAME_LABEL_KEY: "n1"}),
                spec=NodeSpec(provider_id="kwok://n1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "4", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "4", "pods": "110"}),
                ),
            )
        )
        # CSINode lands AFTER the node (the real-world ordering)
        store.create(CSINode(metadata=ObjectMeta(name="n1"), drivers=[CSINodeDriver(name=CSI, allocatable_count=1)]))
        sn = cluster.node_for_name("n1")
        assert sn.volume_usage.exceeds_limits({CSI: {"default/a", "default/b"}}) is not None


class TestCSIMigration:
    """In-tree volume plugins resolve to their CSI driver names for limit
    tracking (suite_test.go:3896-4058 "CSIMigration";
    volumeusage.go:155-181 driverFromSC/driverFromVolume via
    csi-translation-lib)."""

    EBS_IN_TREE = "kubernetes.io/aws-ebs"
    EBS_CSI = "ebs.csi.aws.com"

    def _node_with_limit(self, store, limit=1):
        store.create(CSINode(metadata=ObjectMeta(name="n1"), drivers=[CSINodeDriver(name=self.EBS_CSI, allocatable_count=limit)]))
        store.create(
            Node(
                metadata=ObjectMeta(
                    name="n1",
                    labels={
                        wk.NODEPOOL_LABEL_KEY: "default-pool",
                        wk.HOSTNAME_LABEL_KEY: "n1",
                        wk.ZONE_LABEL_KEY: "test-zone-a",
                        wk.ARCH_LABEL_KEY: "amd64",
                        wk.OS_LABEL_KEY: "linux",
                    },
                ),
                spec=NodeSpec(provider_id="kwok://n1"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": "16", "memory": "32Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": "16", "memory": "32Gi", "pods": "110"}),
                ),
            )
        )

    def _in_tree_pvc(self, store, name, ns="default"):
        """PVC bound to a legacy in-tree EBS PV (no spec.csi)."""
        pv = PersistentVolume(metadata=ObjectMeta(name=f"pv-{name}"), in_tree_source=self.EBS_IN_TREE)
        store.create(pv)
        store.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name=name, namespace=ns, annotations={BIND_COMPLETED_ANNOTATION: "yes"}),
                volume_name=f"pv-{name}",
            )
        )

    def test_resolve_driver_translates_in_tree_pv(self):
        store, *_ = build_env()
        self._in_tree_pvc(store, "legacy")
        pvc = store.get("PersistentVolumeClaim", "legacy", namespace="default")
        from karpenter_tpu.scheduling.volumeusage import resolve_driver

        assert resolve_driver(store, pvc) == self.EBS_CSI

    def test_resolve_driver_translates_in_tree_sc_provisioner(self):
        store, *_ = build_env()
        store.create(StorageClass(metadata=ObjectMeta(name="in-tree-sc"), provisioner=self.EBS_IN_TREE, volume_binding_mode="WaitForFirstConsumer"))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="unbound"), storage_class_name="in-tree-sc"))
        pvc = store.get("PersistentVolumeClaim", "unbound", namespace="default")
        from karpenter_tpu.scheduling.volumeusage import resolve_driver

        assert resolve_driver(store, pvc) == self.EBS_CSI

    def test_migrated_pv_counts_against_csi_limit(self):
        # suite_test.go:3897 — in-tree PVC/PV volumes count against the CSI
        # driver's CSINode limit, so the second pod launches a new node
        store, clock, cluster, pools, types = build_env()
        self._in_tree_pvc(store, "c1")
        self._in_tree_pvc(store, "c2")
        self._node_with_limit(store, limit=1)
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([pod_with_pvcs("c1", name="pod-1", cpu="100m"), pod_with_pvcs("c2", name="pod-2", cpu="100m")])
        assert results.all_pods_scheduled()
        assert results.node_pod_count().get("n1") == 1
        assert len(results.new_node_claims) == 1

    def test_migrated_sc_ephemeral_counts_against_csi_limit(self):
        # suite_test.go:3958 — ephemeral volumes through an in-tree SC count
        # against the same CSI limit
        store, clock, cluster, pools, types = build_env()
        store.create(StorageClass(metadata=ObjectMeta(name="in-tree-sc"), provisioner=self.EBS_IN_TREE, volume_binding_mode="WaitForFirstConsumer"))
        self._node_with_limit(store, limit=1)
        pods = []
        for i in range(2):
            p = make_pod(name=f"eph-{i}", cpu="100m")
            p.spec.volumes = [{"name": "v0", "ephemeral": {"volumeClaimTemplate": {"spec": {"storageClassName": "in-tree-sc"}}}}]
            pods.append(p)
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve(pods)
        assert results.all_pods_scheduled()
        assert results.node_pod_count().get("n1") == 1
        assert len(results.new_node_claims) == 1

    def test_mixed_in_tree_and_csi_share_one_limit(self):
        # one in-tree volume + one native CSI volume on the same driver name
        # consume the same budget
        store, clock, cluster, pools, types = build_env()
        self._in_tree_pvc(store, "legacy")
        pv = PersistentVolume(metadata=ObjectMeta(name="pv-native"), csi_driver=self.EBS_CSI)
        store.create(pv)
        store.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="native", namespace="default", annotations={BIND_COMPLETED_ANNOTATION: "yes"}),
                volume_name="pv-native",
            )
        )
        self._in_tree_pvc(store, "legacy2")
        self._node_with_limit(store, limit=2)
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([
            pod_with_pvcs("legacy", name="pod-l", cpu="100m"),
            pod_with_pvcs("native", name="pod-n", cpu="100m"),
            # the third volume-bearing pod exceeds the SHARED limit of 2 —
            # if in-tree and native CSI were tracked under separate driver
            # keys it would fit on n1 and this assertion would fail
            pod_with_pvcs("legacy2", name="pod-l2", cpu="100m"),
        ])
        assert results.all_pods_scheduled()
        assert results.node_pod_count().get("n1") == 2
        assert len(results.new_node_claims) == 1
