"""Node termination: drain -> VolumeAttachment detach wait -> instance delete.

Reference: node/termination/controller.go awaitVolumeDetachment (:235-280) and
filterVolumeAttachments (:309-355).
"""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube import ObjectMeta, VolumeAttachment
from karpenter_tpu.kube.objects import PersistentVolumeClaim
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def env_with_node(pod=None):
    env = Environment(options=Options())
    np = make_nodepool(requirements=LINUX_AMD64)
    env.store.create(np)
    env.store.create(pod or make_pod(cpu="1", name="w0"))
    env.settle(rounds=6)
    nodes = env.store.list("Node")
    assert len(nodes) == 1 and all(p.spec.node_name for p in env.store.list("Pod"))
    return env, nodes[0]


def attach(env, node, pv_name="pv-1", name="va-1"):
    env.store.create(
        VolumeAttachment(
            metadata=ObjectMeta(name=name),
            attacher="csi.test",
            node_name=node.metadata.name,
            persistent_volume_name=pv_name,
        )
    )


class TestVolumeAttachmentWait:
    def test_lingering_attachment_delays_deletion(self):
        env, node = env_with_node()
        attach(env, node)
        env.store.delete("Node", node.metadata.name)
        for _ in range(4):
            env.clock.step(5)
            env.tick(provision_force=False)
        # drained, but the instance must NOT be deleted while the attachment
        # of a drain-able pod lingers
        assert env.store.try_get("Node", node.metadata.name) is not None
        # the CSI controller detaches -> deletion completes
        env.store.delete("VolumeAttachment", "va-1", grace=False)
        for _ in range(3):
            env.clock.step(5)
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_undrainable_pod_attachment_does_not_block(self):
        # a daemonset-owned pod rides the node down; its volume detaches with
        # the instance and must not block termination
        from karpenter_tpu.kube.objects import OwnerReference

        daemon_pod = make_pod(cpu="100m", name="ds-pod", owner_refs=[OwnerReference(kind="DaemonSet", name="ds", uid="u-ds")])
        daemon_pod.spec.volumes = [{"persistentVolumeClaim": {"claimName": "ds-pvc"}}]
        env, node = env_with_node()
        daemon_pod.spec.node_name = node.metadata.name
        env.store.create(daemon_pod)
        env.store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="ds-pvc"), volume_name="ds-pv", phase="Bound"))
        attach(env, node, pv_name="ds-pv", name="va-ds")
        env.store.delete("Node", node.metadata.name)
        for _ in range(4):
            env.clock.step(5)
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_grace_period_expiry_skips_wait(self):
        env, node = env_with_node()
        attach(env, node)

        def stamp(n):
            n.metadata.annotations[wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY] = str(env.clock.now() + 10)

        env.store.patch("Node", node.metadata.name, stamp)
        env.store.delete("Node", node.metadata.name)
        env.clock.step(30)  # grace period elapses
        for _ in range(3):
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None
