"""Node termination: drain -> VolumeAttachment detach wait -> instance delete.

Reference: node/termination/controller.go awaitVolumeDetachment (:235-280) and
filterVolumeAttachments (:309-355).
"""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube import ObjectMeta, VolumeAttachment
from karpenter_tpu.kube.objects import PersistentVolumeClaim
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def env_with_node(pod=None):
    env = Environment(options=Options())
    np = make_nodepool(requirements=LINUX_AMD64)
    env.store.create(np)
    env.store.create(pod or make_pod(cpu="1", name="w0"))
    env.settle(rounds=6)
    nodes = env.store.list("Node")
    assert len(nodes) == 1 and all(p.spec.node_name for p in env.store.list("Pod"))
    return env, nodes[0]


def attach(env, node, pv_name="pv-1", name="va-1"):
    env.store.create(
        VolumeAttachment(
            metadata=ObjectMeta(name=name),
            attacher="csi.test",
            node_name=node.metadata.name,
            persistent_volume_name=pv_name,
        )
    )


class TestVolumeAttachmentWait:
    def test_lingering_attachment_delays_deletion(self):
        env, node = env_with_node()
        attach(env, node)
        env.store.delete("Node", node.metadata.name)
        for _ in range(4):
            env.clock.step(5)
            env.tick(provision_force=False)
        # drained, but the instance must NOT be deleted while the attachment
        # of a drain-able pod lingers
        assert env.store.try_get("Node", node.metadata.name) is not None
        # the CSI controller detaches -> deletion completes
        env.store.delete("VolumeAttachment", "va-1", grace=False)
        for _ in range(3):
            env.clock.step(5)
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_undrainable_pod_attachment_does_not_block(self):
        # a daemonset-owned pod rides the node down; its volume detaches with
        # the instance and must not block termination
        from karpenter_tpu.kube.objects import OwnerReference

        daemon_pod = make_pod(cpu="100m", name="ds-pod", owner_refs=[OwnerReference(kind="DaemonSet", name="ds", uid="u-ds")])
        daemon_pod.spec.volumes = [{"persistentVolumeClaim": {"claimName": "ds-pvc"}}]
        env, node = env_with_node()
        daemon_pod.spec.node_name = node.metadata.name
        env.store.create(daemon_pod)
        env.store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="ds-pvc"), volume_name="ds-pv", phase="Bound"))
        attach(env, node, pv_name="ds-pv", name="va-ds")
        env.store.delete("Node", node.metadata.name)
        for _ in range(4):
            env.clock.step(5)
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_grace_period_expiry_skips_wait(self):
        env, node = env_with_node()
        attach(env, node)

        def stamp(n):
            n.metadata.annotations[wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY] = str(env.clock.now() + 10)

        env.store.patch("Node", node.metadata.name, stamp)
        env.store.delete("Node", node.metadata.name)
        env.clock.step(30)  # grace period elapses
        for _ in range(3):
            env.tick(provision_force=False)
        assert env.store.try_get("Node", node.metadata.name) is None


def drain_rounds(env, rounds=10):
    for _ in range(rounds):
        env.termination.reconcile()
        env.clock.step(2.0)


class TestDrainDepth:
    """Drain-order specs ported from node/termination/suite_test.go:112-563."""

    def test_deletes_node_and_claim(self):
        # :112/:152
        env, node = env_with_node()
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env)
        env.settle(rounds=4)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_disruption_taint_toleration_equal_not_evicted(self):
        # :225 — pods tolerating the karpenter disrupted taint (Equal) ride
        # the node down: never reset to pending, deleted with the instance
        pod = make_pod(
            cpu="1",
            name="rider",
            tolerations=[{"key": wk.DISRUPTED_TAINT_KEY, "operator": "Equal", "value": "", "effect": "NoSchedule"}],
        )
        env, node = env_with_node(pod)
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env)
        # pod was never evicted-to-pending; it vanished with the node
        assert env.store.try_get("Node", node.metadata.name) is None
        assert env.store.try_get("Pod", "rider") is None

    def test_disruption_taint_toleration_exists_not_evicted(self):
        # :256 — Exists operator tolerates too
        pod = make_pod(
            cpu="1",
            name="rider2",
            tolerations=[{"key": wk.DISRUPTED_TAINT_KEY, "operator": "Exists"}],
        )
        env, node = env_with_node(pod)
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env)
        assert env.store.try_get("Node", node.metadata.name) is None
        assert env.store.try_get("Pod", "rider2") is None

    def test_unschedulable_toleration_still_evicted(self):
        # :289 — tolerating node.kubernetes.io/unschedulable does NOT opt a
        # pod out of drain
        pod = make_pod(
            cpu="1",
            name="w1",
            tolerations=[{"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"}],
        )
        env, node = env_with_node(pod)
        env.store.delete("Node", node.metadata.name)
        env.termination.reconcile()
        p = env.store.get("Pod", "w1")
        assert p.spec.node_name == "" and p.status.phase == "Pending"

    def test_evicts_lower_priority_groups_first(self):
        # :485 — non-critical pods drain before high-priority ones
        env = Environment(options=Options())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        env.store.create(make_pod(cpu="500m", name="low", priority=0))
        env.store.create(make_pod(cpu="500m", name="high", priority=1000))
        env.settle(rounds=6)
        nodes = env.store.list("Node")
        assert len(nodes) == 1
        env.store.delete("Node", nodes[0].metadata.name)
        env.termination.reconcile()
        low, high = env.store.get("Pod", "low"), env.store.get("Pod", "high")
        assert low.spec.node_name == "", "low priority evicts in the first pass"
        assert high.spec.node_name != "", "high priority drains in a later pass"
        env.termination.reconcile()
        assert env.store.get("Pod", "high").spec.node_name == ""

    def test_static_node_owned_pods_not_evicted(self):
        # :523 — static (node-owned) pods are never evicted; they go down
        # with the node
        from karpenter_tpu.kube.objects import OwnerReference

        env, node = env_with_node()
        static = make_pod(cpu="100m", name="static-pod", node_name=node.metadata.name)
        static.metadata.owner_references = [OwnerReference(kind="Node", name=node.metadata.name, uid="u-node")]
        env.store.create(static)
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env)
        assert env.store.try_get("Node", node.metadata.name) is None
        assert env.store.try_get("Pod", "static-pod") is None  # deleted with node, never pending

    def test_terminal_pods_do_not_block(self):
        # :348 — Succeeded/Failed pods don't hold the drain open
        env, node = env_with_node()

        def finish(p):
            p.status.phase = "Succeeded"

        env.store.patch("Pod", "w0", finish)
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env, rounds=4)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_node_survives_until_drain_completes(self):
        # :564 — with a blocking PDB the node lingers; unblocked it goes
        from karpenter_tpu.kube.objects import PodDisruptionBudget

        sel = {"matchLabels": {"app": "guarded"}}
        pod = make_pod(cpu="1", name="guarded", labels={"app": "guarded"})
        env, node = env_with_node(pod)
        env.store.create(
            PodDisruptionBudget(metadata=ObjectMeta(name="pdb"), selector=sel, max_unavailable=0)
        )
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env, rounds=4)
        assert env.store.try_get("Node", node.metadata.name) is not None, "PDB blocks the drain"
        env.store.delete("PodDisruptionBudget", "pdb")
        drain_rounds(env, rounds=6)
        assert env.store.try_get("Node", node.metadata.name) is None

    def test_termination_metrics_fire(self):
        # :975/:989
        from karpenter_tpu import metrics as m

        env, node = env_with_node()
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env)
        assert env.registry.counter(m.NODES_TERMINATED_TOTAL).total() >= 1


class TestLoadBalancerExclusion:
    def test_terminating_node_labeled_out_of_load_balancers(self):
        # suite_test.go:202-224 — the exclusion label lands with the taint,
        # BEFORE draining, so connections stop before the instance dies
        from karpenter_tpu.controllers.node.termination import EXCLUDE_BALANCERS_LABEL_KEY
        from karpenter_tpu.kube.objects import PodDisruptionBudget

        pod = make_pod(cpu="100m", name="held", labels={"app": "held"})
        env, node = env_with_node(pod)
        # fully blocking PDB keeps the node alive long enough to observe
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="block", namespace="default"),
            selector={"matchLabels": {"app": "held"}},
            max_unavailable=0,
        ))
        env.store.delete("Node", node.metadata.name)
        drain_rounds(env, rounds=1)
        cur = env.store.try_get("Node", node.metadata.name)
        assert cur is not None
        assert cur.metadata.labels.get(EXCLUDE_BALANCERS_LABEL_KEY) == "karpenter"
