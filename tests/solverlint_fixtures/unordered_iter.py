"""solverlint fixture: unordered-iteration-escape. Never imported — parsed only.

Seeds the hash-order escape routes: a for-loop over a set, a list()
materialization, a comprehension freezing set order, set.pop(), an
id()-keyed sort, and *-unpacking. The sorted()/order-insensitive twins and
the pragma'd twin must NOT be flagged.
"""


def bad_for_loop(enc):
    pending = set(enc.pending)
    order = []
    for p in pending:
        order.append(p)
    return order


def bad_list_materialize(enc):
    sigs = frozenset(enc.sigs)
    return list(sigs)


def bad_comprehension(enc):
    domains = set(enc.domains)
    return [d.name for d in domains]


def bad_set_pop(enc):
    pending = set(enc.pending)
    return pending.pop()


def bad_id_key(rows):
    return sorted(rows, key=id)


def bad_star_unpack(enc):
    sigs = set(enc.sigs)
    return [*sigs]


def bad_aliased_union(enc):
    # set-typedness flows through the | operator and name copies
    a = set(enc.a)
    b = a | set(enc.b)
    for x in b:
        yield x


def bad_self_attr(enc):
    class Walker:
        def __init__(self):
            self._groups = set()

        def emit(self):
            return list(self._groups)

    return Walker


def ok_sorted(enc):
    pending = set(enc.pending)
    order = []
    for p in sorted(pending):
        order.append(p)
    return order


def ok_order_insensitive(enc):
    pending = set(enc.pending)
    # membership, len, min/max and order-insensitive folds never leak order
    total = len(pending) + min(pending) + max(pending)
    covered = all(p in pending for p in enc.required)
    return total, covered, frozenset(pending)


def ok_literal_display(enc):
    # a literal display is the author's explicit enumeration — exempt
    for kind in {"cpu", "tpu"}:
        enc.note(kind)


def ok_pragma(enc):
    pending = set(enc.pending)
    for p in pending:  # solverlint: ok(unordered-iteration-escape): fixture — proves the pragma form suppresses
        enc.note(p)
