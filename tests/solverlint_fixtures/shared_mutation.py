"""solverlint fixture: shared-array-mutation. Never imported — parsed only.

`bad_*` functions each seed exactly one violation; `ok_*` functions repeat
the violation under a justified pragma and must be suppressed.
"""


def bad_subscript_store(enc):
    enc.sig_req[0] = 1.0


def bad_augassign(enc):
    enc.counts_dom_init += 1


def bad_fill(enc):
    enc.sig_dom_allowed.fill(True)


def bad_alias_store(enc):
    alias = enc.row_alloc
    alias[3] = 0.0


def ok_pragma(enc):
    enc.sig_req[0] = 1.0  # solverlint: ok(shared-array-mutation): fixture — proves the pragma form suppresses

def ok_local_copy(enc):
    local = enc.sig_req.copy()
    local[0] = 1.0  # a copy is not shared: must NOT be flagged


def bad_mutation_inside_lambda(enc, xs):
    # lambdas are not a lint blind spot either
    xs.sort(key=lambda x: enc.group_registered.fill(False))
    return xs
