"""solverlint fixture: stale-pragma. Never imported — parsed only.

Seeds dead suppressions: a pragma whose finding no longer exists, and a
pragma naming a rule that was never registered. The load-bearing pragma
(one that suppresses a live finding) must NOT be reported.
"""


def stale_suppression(enc):
    # the mutation this pragma once excused was refactored away; the pragma
    # rotted in place — exactly what the rule reports
    x = enc.read_only_view()  # solverlint: ok(shared-array-mutation): nothing left to suppress here
    return x


def unknown_rule(enc):
    return enc.x  # solverlint: ok(rule-that-never-existed): names a rule that is not registered


def live_suppression(enc):
    enc.sig_req[0] = 1.0  # solverlint: ok(shared-array-mutation): load-bearing — suppresses a real finding, must not be reported
    return enc
