"""solverlint fixture: host-sync-in-hot-path. Never imported — parsed only."""

import numpy as np


def bad_float(t, items):
    takes = greedy_pack_grouped_sharded(t, items)  # noqa: F821 — fixture, parsed only
    return float(takes)


def bad_item(t, items):
    leftovers = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    return leftovers.sum().item()


def bad_asarray(t, items):
    out = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    return np.asarray(out)


def ok_pragma(t, items):
    takes = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    return float(takes)  # solverlint: ok(host-sync-in-hot-path): fixture — proves the pragma form suppresses


def ok_shape_read(t, items):
    takes = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    return int(takes.shape[0])  # static metadata, not a sync: must NOT be flagged


def bad_sync_mixed_with_shape_read(t, items):
    # the .shape read exempts only ITS subtree — takes.sum() still syncs
    takes = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    return float(takes.sum() / takes.shape[0])


def bad_sync_inside_lambda(t, items, xs):
    # lambdas are not a lint blind spot: the sync in the sort key is flagged
    takes = greedy_pack_grouped_sharded(t, items)  # noqa: F821
    xs.sort(key=lambda x: float(takes))
    return xs
