"""solverlint fixture: python-loop-over-pod-axis. Never imported — parsed only."""


def bad_loop(enc):
    total = 0
    for p in enc.pods:
        total += p.weight
    return total


def ok_pragma(enc):
    total = 0
    for p in enc.pods:  # solverlint: ok(python-loop-over-pod-axis): fixture — proves the pragma form suppresses
        total += p.weight
    return total


def ok_comprehension(enc):
    # comprehensions doing O(1) attribute reads are the sanctioned cheap
    # pass: must NOT be flagged
    return [p.key for p in enc.pods]


def ok_signature_scale(rep_pods):
    # per-signature (unique pod shape) loops are the whole point: not flagged
    out = []
    for pod in rep_pods:
        out.append(pod)
    return out


def bad_multigroup_items(enc, demote):
    # seeded multi-group item-builder violation: deciding each pod's merge
    # key with a Python loop over the pod axis — the O(pods) host work the
    # vectorized sig_demotions/np.unique path exists to avoid
    keys = []
    for i, p in enumerate(enc.pods):
        keys.append(enc.n_sigs + i if demote[p.sig] else p.sig)
    return keys


def ok_multigroup_items(np, enc, demote, sig):
    # the sanctioned form: pure np.unique/segment work, items scale with
    # unique shapes — never with pods
    key = np.where(demote[sig], enc.n_sigs + np.arange(sig.shape[0]), sig)
    return np.unique(key, return_index=True, return_inverse=True, return_counts=True)


def bad_decode_loop(enc, assignment):
    # seeded decode violation: materializing per-slot membership by walking
    # the pod axis in Python — the O(pods) host tail the decode-delta memo
    # and the columnar gather exist to kill
    slots = {}
    for i, p in enumerate(enc.pods):
        slots.setdefault(assignment[i], []).append(p)
    return slots


def ok_decode_columnar(np, enc, assignment, dirty):
    # the sanctioned columnar decode: one vectorized gather over the dirty
    # rows only — per-slot grouping comes from the sorted assignment column,
    # never from a per-pod Python walk
    valid = np.nonzero(dirty[assignment])[0]
    order = np.argsort(assignment[valid], kind="stable")
    return valid[order], np.bincount(assignment[valid], minlength=enc.n_slots)
