"""solverlint fixture: python-loop-over-pod-axis. Never imported — parsed only."""


def bad_loop(enc):
    total = 0
    for p in enc.pods:
        total += p.weight
    return total


def ok_pragma(enc):
    total = 0
    for p in enc.pods:  # solverlint: ok(python-loop-over-pod-axis): fixture — proves the pragma form suppresses
        total += p.weight
    return total


def ok_comprehension(enc):
    # comprehensions doing O(1) attribute reads are the sanctioned cheap
    # pass: must NOT be flagged
    return [p.key for p in enc.pods]


def ok_signature_scale(rep_pods):
    # per-signature (unique pod shape) loops are the whole point: not flagged
    out = []
    for pod in rep_pods:
        out.append(pod)
    return out
