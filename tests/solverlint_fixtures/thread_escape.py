"""solverlint fixture: thread-escape. Never imported — parsed only.

Seeds three violations: an unregistered Thread target, an unregistered
store-watch callback, and a lambda callback (invisible capture is flagged
outright). The pragma'd twin must be suppressed.
"""

import threading
from threading import Thread as _SneakyThread


class FixtureEscapee:
    def bad_from_import_thread(self):
        # a renamed from-import must not evade the registry check
        t = _SneakyThread(target=self._other)  # solverlint: ok(bare-thread-primitive): fixture — the escape is the point, not the construction
        t.start()

    def bad_thread(self):
        self._t = threading.Thread(target=self._run, daemon=True)  # solverlint: ok(bare-thread-primitive): fixture — the escape is the point, not the construction
        self._t.start()

    def bad_watch(self, store):
        store.watch("Pod", self._on_pod)

    def bad_lambda(self, store):
        store.watch("Node", lambda e, n: self.mark(n))

    def ok_pragma(self, store):
        store.watch("Pod", self._on_pod)  # solverlint: ok(thread-escape): fixture — proves the pragma form suppresses
