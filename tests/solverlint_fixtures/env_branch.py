"""solverlint fixture: env-dependent-branch. Never imported — parsed only.

Seeds unregistered os.environ reads through every access shape (get,
subscript, getenv, membership, bulk read) and the alias import pattern.
Registered KARPENTER_* knobs and the pragma'd twin must NOT be flagged.
"""

import os
import os as sneaky_os
from os import environ, getenv


def bad_unregistered_get():
    return os.environ.get("KARPENTER_SOLVER_SECRET", "")


def bad_aliased_module():
    # a renamed module import must not evade the knob table
    return sneaky_os.environ.get("SOLVER_EXPERIMENT", "")


def bad_from_import_environ():
    return environ["SOLVER_FORK_BEHAVIOR"]


def bad_from_import_getenv():
    return getenv("SOLVER_TUNING")


def bad_subscript():
    return os.environ["UNREVIEWED_KNOB"]


def bad_membership():
    return "SOLVER_FAST_PATH" in os.environ


def bad_dynamic_key(name):
    return os.environ.get(f"KARPENTER_{name}")


def bad_bulk_read():
    return dict(os.environ.items())


def ok_registered():
    a = os.environ.get("KARPENTER_SOLVER_MESH", "")
    b = os.getenv("KARPENTER_SOLVER_BUCKET")
    c = "KARPENTER_SOLVER_DETCHECK" in os.environ
    return a, b, c


def ok_pragma():
    return os.environ.get("KARPENTER_SOLVER_SECRET", "")  # solverlint: ok(env-dependent-branch): fixture — proves the pragma form suppresses
