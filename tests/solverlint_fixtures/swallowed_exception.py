"""solverlint fixture: swallowed-exception. Never imported — parsed only."""


def bad_silent_pass(store, nc):
    try:
        store.update(nc)
    except Exception:
        pass


def bad_bare_except(store, nc):
    try:
        store.update(nc)
    except:  # noqa: E722 — fixture, parsed only
        return None


def bad_base_exception_continue(store, items):
    for nc in items:
        try:
            store.update(nc)
        except BaseException:
            continue


def bad_tuple_broad(store, nc):
    # parenthesizing the broad type must not evade the rule
    try:
        store.update(nc)
    except (Exception, OSError):
        pass


def ok_reraise(store, nc):
    try:
        store.update(nc)
    except Exception:
        raise


def ok_event_emission(store, nc, recorder):
    try:
        store.update(nc)
    except Exception as e:
        recorder.publish(nc, "ReconcileError", str(e), type_="Warning")


def ok_metric_emission(store, nc, registry):
    try:
        store.update(nc)
    except Exception:
        registry.counter("m").inc(reason="update-failed")


def ok_narrowed(store, nc):
    try:
        store.update(nc)
    except (ValueError, KeyError):
        pass


def ok_pragma(store, nc):
    try:
        store.update(nc)
    except Exception:  # solverlint: ok(swallowed-exception): fixture — proves the pragma form suppresses
        pass
