"""solverlint fixture: metric-label-cardinality. Never imported — parsed only."""


def bad_fstring(registry, pod):
    registry.counter("m").inc(reason=f"pod {pod.key()}")


def bad_unbounded_name(registry, why):
    registry.counter("m").inc(reason=why)


def bad_splat(registry, labels):
    registry.counter("m").inc(**labels)


def ok_literal(registry):
    registry.counter("m").inc(reason="bounded")


def ok_producer(registry, r):
    registry.counter("m").inc(reason=reason_family(r))  # noqa: F821 — fixture, parsed only


def ok_ternary(registry, cmd):
    decision = "replace" if cmd.replacements else "delete"
    registry.counter("m").inc(decision=decision)


def ok_local_dict_splat(registry, node):
    labels = dict(reason="unhealthy", nodepool=node.pool)
    registry.counter("m").inc(**labels)


def ok_pragma(registry, why):
    registry.counter("m").inc(reason=why)  # solverlint: ok(metric-label-cardinality): fixture — proves the pragma form suppresses


def ok_identity_label(registry, node):
    # nodepool is an identity label, not in bounded-labels: must NOT be flagged
    registry.counter("m").inc(nodepool=node.pool)


def bad_tenant_raw_id(registry, session):
    # the fleet cardinality leak: a raw tenant id as the tenant label
    registry.counter("karpenter_solver_solve_total").inc(backend="tpu", tenant=session.tenant_id)


def ok_tenant_producer(registry, session):
    # tenant_label is the bounded fleet producer (serving.fleet)
    registry.counter("karpenter_solver_solve_total").inc(backend="tpu", tenant=tenant_label(session.tenant_id))  # noqa: F821 — fixture, parsed only


def bad_breaker_state_runtime(registry, breaker):
    # the faultline cardinality leak: the breaker-transitions counter's
    # `state` label fed a runtime breaker attribute instead of a literal
    # from the static serving.faults.TENANT_STATES enum
    registry.counter("karpenter_solver_breaker_transitions_total").inc(tenant=tenant_label(breaker.tenant_id), state=breaker.state)  # noqa: F821 — fixture, parsed only


def ok_breaker_state_enum(registry, breaker):
    # the sanctioned form: a literal/ternary over the static state enum
    state = "quarantined" if breaker.open else "healthy"
    registry.counter("karpenter_solver_breaker_transitions_total").inc(tenant=tenant_label(breaker.tenant_id), state=state)  # noqa: F821 — fixture, parsed only


def bad_stage_runtime_name(registry, rec):
    # the podtrace cardinality leak: a runtime-computed span name as the
    # stage label instead of iterating the static obs.podtrace.STAGES enum
    for stage, dur in rec.stamps.items():
        registry.histogram("karpenter_solver_event_stage_seconds").observe(dur, stage=stage)


def ok_stage_static_enum(registry, rec):
    # the sanctioned form: stage iterates the static stage tuple
    for stage in ("coalesce", "sched_wait", "prestage", "solve", "decode", "e2e"):
        registry.histogram("karpenter_solver_event_stage_seconds").observe(rec.stages[stage], stage=stage)


def bad_proposer_runtime(registry, trace):
    # the globalpack cardinality leak: the proposals counter's `proposer`
    # label fed a runtime trace attribute instead of a literal from the
    # static proposer enum (lp | anneal | binary-search | globalpack)
    registry.counter("karpenter_solver_consolidation_proposals_total").inc(8, proposer=trace.backend)


def ok_proposer_enum(registry, trace):
    # the sanctioned form: a literal/ternary over the static proposer enum
    proposer = "globalpack" if trace.backend == "globalpack" else "lp"
    registry.counter("karpenter_solver_consolidation_proposals_total").inc(8, proposer=proposer)
