"""solverlint fixture: guarded-field-access. Never imported — parsed only.

`bad_*` methods seed violations (a write AND a read outside the declared
lock — reads race too); `ok_*` show the three sanctioned forms: the lock
held (including nested withs), a line pragma, and the method-level
caller-holds contract (pragma on the `def` line).
"""


class FixtureStats:
    GUARDED_FIELDS = {"hits": "_lock", "misses": "_lock"}

    def __init__(self):
        self._lock = make_lock("fixture-stats")  # noqa: F821 — fixture, parsed only
        self.hits = 0
        self.misses = 0

    def bad_bump(self):
        self.hits += 1

    def bad_read(self):
        return self.misses

    def ok_locked(self):
        with self._lock:
            self.hits += 1
            if self.hits > 10:
                self.misses = 0  # still inside the with: must NOT be flagged

    def ok_pragma(self):
        self.hits += 1  # solverlint: ok(guarded-field-access): fixture — proves the pragma form suppresses

    def _ok_caller_holds(self):  # solverlint: ok(guarded-field-access): fixture — caller-holds method contract, every call site verified
        self.hits += 1
        self.misses -= 1
