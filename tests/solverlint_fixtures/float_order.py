"""solverlint fixture: float-reduction-order. Never imported — parsed only.

Seeds order-sensitive float folds: builtin sum() over device-derived values
and over set hash order. The canonical-order twins (math.fsum,
stable_host_sum, sum(sorted(...))) and the pragma'd twin must NOT be
flagged.
"""

import math


def bad_device_fold(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821 — fixture, parsed only
    return sum(takes)


def bad_device_fold_via_copy(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821
    parts = takes
    return sum(parts)


def bad_set_order_fold(costs):
    pool = set(costs)
    return sum(pool)


def bad_genexp_over_set(rows):
    pool = set(rows)
    return sum(r.cost for r in pool)


def ok_fsum(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821
    return math.fsum(takes)


def ok_canonical_helper(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821
    return stable_host_sum(takes)  # noqa: F821


def ok_sorted_fold(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821
    return sum(sorted(takes))


def ok_host_only_fold(weights):
    # a plain host list in its given order is deterministic — not flagged
    return sum(weights)


def ok_pragma(ts, items):
    takes = greedy_pack_grouped_sharded(ts, items)  # noqa: F821
    return sum(takes)  # solverlint: ok(float-reduction-order): fixture — proves the pragma form suppresses
