"""solverlint fixture: reason-family-tiers. Never imported — parsed only.

Seeds three violations: `fam-untiered` lacks a FAMILY_TIERS entry,
`fam-global-bare` is GLOBAL without a justification comment, and
`fam-stale` has a tier but no REASON_FAMILIES needle.
"""

GLOBAL = "global"
POD_LOCAL = "pod-local"

REASON_FAMILIES = (
    ("needle one", "fam-untiered"),
    ("needle two", "fam-global-bare"),
    ("needle three", "fam-ok"),
)

FAMILY_TIERS = {
    "fam-global-bare": GLOBAL,
    # attribution covers the whole membership set
    "fam-ok": POD_LOCAL,
    "fam-stale": POD_LOCAL,
    "other": GLOBAL,  # unattributable reasons take the conservative path
}
