"""solverlint fixture: bare-thread-primitive. Never imported — parsed only.

Seeds three violations (a raw Lock, a raw Event, and a from-import-aliased
Lock — renames resolve through the import table instead of evading the
rule); the pragma'd twin is suppressed, and `threading.local()` is
deliberately exempt (thread-LOCAL state is the opposite of shared state).
"""

import threading
from threading import Lock as _SneakyLock


def bad_lock():
    return threading.Lock()


def bad_from_import_alias():
    # a rename must not evade the rule: resolved through the import table
    return _SneakyLock()


def bad_event():
    return threading.Event()


def ok_pragma():
    return threading.Lock()  # solverlint: ok(bare-thread-primitive): fixture — proves the pragma form suppresses


def ok_thread_local():
    return threading.local()  # exempt: must NOT be flagged
