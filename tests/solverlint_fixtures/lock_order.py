"""solverlint fixture: lock-order. Never imported — parsed only.

Seeds two violations: `forward`+`backward` acquire the same pair of locks in
both orders (one cycle finding, reported once at finalize), and
`bad_blocking` runs a solve while holding a lock. `ok_pragma_edge` shows the
edge-level pragma that excludes a reviewed acquisition from the graph.
"""

import threading


class FixtureInverted:
    def __init__(self):
        self._a = threading.Lock()  # solverlint: ok(bare-thread-primitive): fixture — raw locks keep this file self-contained
        self._b = threading.Lock()  # solverlint: ok(bare-thread-primitive): fixture — raw locks keep this file self-contained

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        # the combined multi-item form acquires sequentially — it orders
        # b before a exactly like nested withs and must close the cycle
        with self._b, self._a:
            pass

    def bad_blocking(self, solver, snapshot):
        with self._a:
            return solver.solve(snapshot)

    def ok_pragma_edge(self):
        with self._b:
            with self._a:  # solverlint: ok(lock-order): fixture — proves the edge-level pragma excludes a reviewed acquisition
                pass
