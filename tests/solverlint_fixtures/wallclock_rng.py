"""solverlint fixture: wallclock-and-rng-in-solve-path. Never imported — parsed only.

Seeds wallclock reads and unseeded randomness through every import shape
the rule resolves (the PR 11 `import threading as t` alias pattern applied
to time/random/numpy.random/uuid). Seeded constructors and the jax.random
key-passing API must NOT be flagged.
"""

import random as rnd
import time as clk
import uuid
from random import shuffle as sneaky_shuffle
from time import perf_counter

import jax.random as jr
import numpy as np


def bad_wallclock():
    return clk.time()


def bad_from_import_wallclock():
    return perf_counter()


def bad_module_rng(order):
    rnd.shuffle(order)
    return order


def bad_from_import_rng(order):
    # a renamed from-import must not evade the solve-path RNG check
    sneaky_shuffle(order)
    return order


def bad_unseeded_random_ctor():
    return rnd.Random()


def bad_numpy_global_rng(n):
    return np.random.rand(n)


def bad_numpy_unseeded_default_rng():
    return np.random.default_rng()


def bad_uuid(claim):
    return f"{claim}-{uuid.uuid4()}"


def ok_seeded(order, seed):
    rng = rnd.Random(seed)
    rng.shuffle(order)
    gen = np.random.default_rng(seed)
    return gen.random()


def ok_jax_keyed(seed):
    # jax.random is deterministic by construction: randomness flows from an
    # explicit key, never ambient state (the seeded-rng registry entry)
    key = jr.PRNGKey(seed)
    return jr.uniform(key)


def ok_pragma():
    return clk.time()  # solverlint: ok(wallclock-and-rng-in-solve-path): fixture — proves the pragma form suppresses
