"""End-to-end slice (SURVEY.md §7 stage 6): pending pods -> batcher -> solve
-> NodeClaim create -> KWOK node Ready -> pods bound.

Modeled on the reference's provisioning suite + ExpectProvisioned harness.
"""

import pytest

from helpers import make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env(**kw):
    env = Environment(options=Options(**kw))
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env


class TestEndToEnd:
    def test_single_pod_provisions_and_binds(self):
        env = make_env()
        env.store.create(make_pod(cpu="1"))
        env.settle()
        assert env.store.count("NodeClaim") == 1
        assert env.store.count("Node") == 1
        pod = env.store.list("Pod")[0]
        assert pod.spec.node_name != ""
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_launched() and nc.is_registered() and nc.is_initialized()
        node = env.store.list("Node")[0]
        assert wk.UNREGISTERED_TAINT_KEY not in [t.key for t in node.spec.taints]
        assert node.metadata.labels[wk.NODE_REGISTERED_LABEL_KEY] == "true"

    def test_batch_packs_pods_onto_one_node(self):
        env = make_env()
        for _ in range(5):
            env.store.create(make_pod(cpu="1"))
        env.settle()
        assert env.store.count("NodeClaim") == 1
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_batcher_windows(self):
        env = make_env()
        env.store.create(make_pod(cpu="1"))
        # idle window (1s default) has not elapsed -> no provisioning
        env.tick()
        assert env.store.count("NodeClaim") == 0
        env.clock.step(1.5)
        env.tick()
        assert env.store.count("NodeClaim") == 1

    def test_second_batch_reuses_inflight_capacity(self):
        env = make_env()
        env.store.create(make_pod(cpu="1"))
        env.settle(rounds=3)
        assert env.store.count("NodeClaim") == 1
        # another small pod fits on the existing node
        env.store.create(make_pod(cpu="500m"))
        env.settle(rounds=3)
        assert env.store.count("NodeClaim") == 1
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_no_nodepool_no_claims(self):
        env = Environment()
        env.store.create(make_pod(cpu="1"))
        env.settle(rounds=3)
        assert env.store.count("NodeClaim") == 0

    def test_registration_delay(self):
        env = make_env()
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 5.0
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="1"))
        env.clock.step(1.5)
        env.tick()
        assert env.store.count("NodeClaim") == 1
        assert env.store.count("Node") == 0  # not registered yet
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_launched() and not nc.is_registered()
        env.clock.step(6)
        env.tick()
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_registered()

    def test_liveness_kills_unregistered_claims(self):
        env = make_env()
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 10**9  # never registers
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="1"))
        env.clock.step(1.5)
        env.tick()
        assert env.store.count("NodeClaim") == 1
        env.clock.step(16 * 60)
        env.tick()
        assert env.store.count("NodeClaim") == 0

    def test_zone_spread_e2e(self):
        env = make_env()
        sel = {"matchLabels": {"app": "web"}}
        for _ in range(4):
            env.store.create(make_pod(labels={"app": "web"}, tsc=[zone_spread(selector=sel)]))
        env.settle()
        nodes = {n.metadata.name: n for n in env.store.list("Node")}
        pods = env.store.list("Pod")
        assert all(p.spec.node_name for p in pods)
        # 4 pods / maxSkew 1: every pod must land in a distinct zone
        pod_zones = [nodes[p.spec.node_name].metadata.labels[wk.ZONE_LABEL_KEY] for p in pods]
        assert sorted(pod_zones) == sorted({z for z in pod_zones}), pod_zones
        assert len(set(pod_zones)) == 4

    def test_tpu_backend_e2e(self):
        env = Environment(options=Options(solver_backend="tpu"))
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        for _ in range(6):
            env.store.create(make_pod(cpu="1"))
        env.settle()
        assert all(p.spec.node_name for p in env.store.list("Pod"))
        assert env.provisioner.solver.last_backend == "tpu"

    def test_nodepool_limits_cap_fleet(self):
        np = make_nodepool(requirements=LINUX_AMD64, limits={"cpu": "4"})
        env = Environment()
        env.store.create(np)
        for _ in range(40):
            env.store.create(make_pod(cpu="1"))
        env.settle()
        total_cpu = sum(n.status.capacity["cpu"].value for n in env.store.list("Node"))
        assert total_cpu <= 4


class TestDaemonSetRunner:
    """The substrate's DaemonSet controller stand-in (kube/daemonsets.py):
    daemon pods materialize on registered matching nodes so port/resource
    accounting matches a real cluster."""

    def test_daemon_pods_materialize_and_hold_ports(self):
        from karpenter_tpu.kube import Container, ObjectMeta, PodSpec
        from karpenter_tpu.kube.objects import DaemonSet
        from karpenter_tpu.utils.resources import parse_resource_list

        env = Environment(options=Options(solver_backend="tpu"))
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        env.store.create(
            DaemonSet(
                metadata=ObjectMeta(name="proxy"),
                template_spec=PodSpec(
                    containers=[
                        Container(
                            resources={"requests": parse_resource_list({"cpu": "200m"})},
                            ports=[{"containerPort": 8080, "hostPort": 8080}],
                        )
                    ]
                ),
            )
        )
        clash = make_pod(cpu="1", name="clash")
        clash.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080}]
        plain = make_pod(cpu="1", name="plain")
        env.store.create(clash)
        env.store.create(plain)
        env.settle(rounds=12)
        assert env.store.get("Pod", "plain").spec.node_name
        # suite_test.go:955 end-to-end: the daemon owns 8080 on every node —
        # fresh at solve time, materialized once registered
        assert not env.store.get("Pod", "clash").spec.node_name
        daemon_pods = [
            p for p in env.store.list("Pod") if any(o.kind == "DaemonSet" for o in p.metadata.owner_references)
        ]
        assert len(daemon_pods) == env.store.count("Node") == 1
        assert daemon_pods[0].spec.node_name

    def test_daemon_pods_follow_node_lifecycle(self):
        from karpenter_tpu.kube import Container, ObjectMeta, PodSpec
        from karpenter_tpu.kube.objects import DaemonSet
        from karpenter_tpu.utils.resources import parse_resource_list

        env = Environment(options=Options())
        env.store.create(make_nodepool(requirements=LINUX_AMD64))
        env.store.create(
            DaemonSet(
                metadata=ObjectMeta(name="agent"),
                template_spec=PodSpec(
                    containers=[Container(resources={"requests": parse_resource_list({"cpu": "100m"})})]
                ),
            )
        )
        env.store.create(make_pod(cpu="1", name="w"))
        env.settle(rounds=10)
        assert any(o.kind == "DaemonSet" for p in env.store.list("Pod") for o in p.metadata.owner_references)
        # deleting the DaemonSet reaps its pods
        env.store.delete("DaemonSet", "agent")
        env.settle(rounds=4)
        assert not any(
            o.kind == "DaemonSet" for p in env.store.list("Pod") for o in p.metadata.owner_references
        )
