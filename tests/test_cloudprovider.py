"""InstanceType/Offering model + KWOK provider behavior specs."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.kwoknodeclass import KWOKNodeClass
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.errors import InsufficientCapacityError, NodeClaimNotFoundError
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types_assorted
from karpenter_tpu.cloudprovider.kwok import KWOKCloudProvider
from karpenter_tpu.cloudprovider.types import (
    cheapest,
    compatible_instance_types,
    offerings_compatible,
    order_by_price,
    satisfies_min_values,
    worst_launch_price,
)
from karpenter_tpu.kube import Store
from karpenter_tpu.scheduling.requirements import Requirement, Requirements
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def types():
    return catalog.construct_instance_types()


class TestCatalog:
    def test_cardinality(self, types):
        assert len(types) == 144
        names = {t.name for t in types}
        assert "c-1x-amd64-linux" in names and "m-256x-arm64-windows" in names

    def test_capacity_shape(self, types):
        it = next(t for t in types if t.name == "s-4x-amd64-linux")
        assert it.capacity["cpu"].value == 4
        assert it.capacity["memory"].value == 16 * 1024**3
        assert it.capacity["pods"].value == 64
        # allocatable < capacity due to overhead
        assert it.allocatable()["cpu"].milli == 3900

    def test_offerings(self, types):
        it = types[0]
        assert len(it.offerings) == 8  # 4 zones x {spot, on-demand}
        spot = [o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_SPOT]
        od = [o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_ON_DEMAND]
        assert len(spot) == 4 and len(od) == 4
        assert spot[0].price < od[0].price

    def test_price_monotone_in_size(self, types):
        c1 = next(t for t in types if t.name == "c-1x-amd64-linux")
        c4 = next(t for t in types if t.name == "c-4x-amd64-linux")
        assert cheapest(c1.offerings).price < cheapest(c4.offerings).price


class TestInstanceTypeOps:
    def test_order_by_price(self, types):
        reqs = Requirements(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_ON_DEMAND]))
        ordered = order_by_price(types, reqs)
        prices = []
        for it in ordered[:10]:
            compat = [o for o in it.offerings if reqs.intersects(o.requirements) is None]
            prices.append(min(o.price for o in compat))
        assert prices == sorted(prices)

    def test_compatible_filters_arch(self, types):
        reqs = Requirements(Requirement(wk.ARCH_LABEL_KEY, "In", [wk.ARCH_ARM64]))
        out = compatible_instance_types(types, reqs)
        assert out and all("arm64" in it.name for it in out)

    def test_worst_launch_price_prefers_reserved_then_spot(self):
        it = catalog.make_instance_type("c", 4, include_reserved=True)
        all_reqs = Requirements()
        # with all capacity types present, reserved wins the precedence
        p = worst_launch_price(it.offerings, all_reqs)
        reserved = [o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_RESERVED]
        assert p == max(o.price for o in reserved)
        # restrict to on-demand
        od_reqs = Requirements(Requirement(wk.CAPACITY_TYPE_LABEL_KEY, "In", [wk.CAPACITY_TYPE_ON_DEMAND]))
        od = [o for o in it.offerings if o.capacity_type() == wk.CAPACITY_TYPE_ON_DEMAND]
        assert worst_launch_price(it.offerings, od_reqs) == max(o.price for o in od)

    def test_min_values(self, types):
        reqs = Requirements(
            Requirement(wk.INSTANCE_TYPE_LABEL_KEY, "Exists", min_values=3),
        )
        needed, unsat = satisfies_min_values(types[:5], reqs)
        assert unsat is None and needed == 3
        needed, unsat = satisfies_min_values(types[:2], reqs)
        assert unsat == {wk.INSTANCE_TYPE_LABEL_KEY: 2}


def mkclaim(instance_types, extra_reqs=()):
    nc = NodeClaim()
    nc.metadata.name = "test-claim"
    nc.spec.requirements = [
        {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": instance_types},
        *extra_reqs,
    ]
    return nc


class TestKWOKProvider:
    def setup_method(self):
        self.store = Store()
        self.store.create(KWOKNodeClass())
        self.clock = FakeClock()
        self.cp = KWOKCloudProvider(self.store, catalog.construct_instance_types(), clock=self.clock)

    def test_create_picks_cheapest_offering(self):
        out = self.cp.create(mkclaim(["c-4x-amd64-linux", "c-2x-amd64-linux"]))
        # cheaper of the two is c-2x; cheapest capacity type is spot
        assert out.metadata.labels[wk.INSTANCE_TYPE_LABEL_KEY] == "c-2x-amd64-linux"
        assert out.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == wk.CAPACITY_TYPE_SPOT
        nodes = self.store.list("Node")
        assert len(nodes) == 1
        assert nodes[0].spec.provider_id.startswith("kwok://")
        assert any(t.key == wk.UNREGISTERED_TAINT_KEY for t in nodes[0].spec.taints)

    def test_create_respects_capacity_type_requirement(self):
        out = self.cp.create(
            mkclaim(
                ["c-2x-amd64-linux"],
                extra_reqs=[{"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]}],
            )
        )
        assert out.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == wk.CAPACITY_TYPE_ON_DEMAND

    def test_create_unknown_type_fails(self):
        with pytest.raises(InsufficientCapacityError):
            self.cp.create(mkclaim(["no-such-type"]))

    def test_registration_delay(self):
        nodeclass = self.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 30.0
        self.store.update(nodeclass)
        self.cp.create(mkclaim(["c-2x-amd64-linux"]))
        assert self.store.count("Node") == 0
        self.clock.step(31)
        assert self.cp.flush_pending() == 1
        assert self.store.count("Node") == 1

    def test_get_delete_roundtrip(self):
        out = self.cp.create(mkclaim(["c-2x-amd64-linux"]))
        pid = self.store.list("Node")[0].spec.provider_id
        got = self.cp.get(pid)
        assert got.status.provider_id == pid
        self.cp.delete(got)
        with pytest.raises(NodeClaimNotFoundError):
            self.cp.get(pid)

    def test_list(self):
        self.cp.create(mkclaim(["c-2x-amd64-linux"]))
        self.cp.create(mkclaim(["m-8x-amd64-linux"]))
        assert len(self.cp.list()) == 2


class TestFakeProvider:
    def test_scripted_error(self):
        fp = FakeCloudProvider()
        fp.next_create_err = RuntimeError("boom")
        with pytest.raises(RuntimeError):
            fp.create(mkclaim([fp.instance_types[0].name]))
        # next call succeeds and records
        fp.create(NodeClaim())
        assert len(fp.create_calls) == 2

    def test_assorted_generator(self):
        its = instance_types_assorted(400)
        assert len(its) == 400
        assert len({it.name for it in its}) == 400
