"""Pins for the round-5 advisor fixes.

References: singlenodeconsolidation.go:61-115 (unseen-pool persistence),
scheduling/taints.go KnownEphemeralTaintKeyPrefixes, Go stdlib flag parsing
(space-separated negative values), dra allocator totalRequirements release.
"""

from types import SimpleNamespace

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_REGISTERED, NodeClaim
from karpenter_tpu.controllers.disruption.types import Command
from karpenter_tpu.kube import Node, ObjectMeta
from karpenter_tpu.kube.objects import NodeSpec
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.taints import Taint
from karpenter_tpu.state.statenode import StateNode

from test_consolidation_timeouts import make_candidate, make_ctx


class TestUnseenPoolPersistence:
    """SingleNodeConsolidation persists unseenNodePools only on timeout and on
    full-pass completion; returning a command or failing validation leaves the
    previous round's set untouched (singlenodeconsolidation.go:61-74)."""

    def _method(self, ctx):
        from karpenter_tpu.controllers.disruption.methods import SingleNodeConsolidation

        method = SingleNodeConsolidation(ctx)
        method.should_disrupt = lambda c: True
        return method

    def test_command_return_leaves_unseen_untouched(self, monkeypatch):
        import karpenter_tpu.controllers.disruption.validation as validation

        ctx = make_ctx()
        method = self._method(ctx)
        method.previously_unseen_node_pools = {"carried"}
        cmd = Command()
        cmd.candidates = [make_candidate("pa")]
        method.compute_consolidation = lambda cs: cmd
        method._passes_balanced = lambda c: True
        monkeypatch.setattr(
            validation, "Validator", lambda *a, **k: SimpleNamespace(validate=lambda c: None)
        )
        out = method.compute_commands([make_candidate("pa"), make_candidate("pb")], {"pa": 1, "pb": 1})
        assert out == [cmd]
        # pb was never reached, but a successful command is not a timeout:
        # the carried set stays exactly as the previous round left it
        assert method.previously_unseen_node_pools == {"carried"}

    def test_validation_failure_leaves_unseen_untouched(self, monkeypatch):
        import karpenter_tpu.controllers.disruption.validation as validation

        ctx = make_ctx()
        method = self._method(ctx)
        method.previously_unseen_node_pools = {"carried"}
        cmd = Command()
        cmd.candidates = [make_candidate("pa")]
        method.compute_consolidation = lambda cs: cmd
        method._passes_balanced = lambda c: True

        def _raise(c):
            raise validation.ValidationError("churn", "changed")

        monkeypatch.setattr(validation, "Validator", lambda *a, **k: SimpleNamespace(validate=_raise))
        out = method.compute_commands([make_candidate("pa")], {"pa": 1})
        assert out == []
        assert method.previously_unseen_node_pools == {"carried"}


class TestReadinessPrefixTaints:
    """readiness.k8s.io/-prefixed taints on managed-but-uninitialized nodes are
    ephemeral (taints.go KnownEphemeralTaintKeyPrefixes): scheduling must
    assume they lift, or startup readiness gates cause over-provisioning."""

    def _node_with(self, *taints):
        node = Node(
            metadata=ObjectMeta(name="n1", labels={wk.HOSTNAME_LABEL_KEY: "n1"}),
            spec=NodeSpec(taints=list(taints)),
        )
        claim = NodeClaim(metadata=ObjectMeta(name="c1"))
        claim.status.conditions.set_true(COND_REGISTERED)
        return StateNode(node=node, node_claim=claim)

    def test_prefix_filtered_while_uninitialized(self):
        sn = self._node_with(
            Taint(key="readiness.k8s.io/some-gate", value="", effect="NoSchedule"),
            Taint(key="user.example.com/dedicated", value="x", effect="NoSchedule"),
        )
        keys = [t.key for t in sn.taints()]
        assert "readiness.k8s.io/some-gate" not in keys
        assert "user.example.com/dedicated" in keys

    def test_prefix_kept_once_initialized(self):
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED

        sn = self._node_with(Taint(key="readiness.k8s.io/some-gate", value="", effect="NoSchedule"))
        sn.node_claim.status.conditions.set_true(COND_INITIALIZED)
        assert [t.key for t in sn.taints()] == ["readiness.k8s.io/some-gate"]


class TestNegativeFlagValues:
    """Go's flag package accepts `--flag -100` (space-separated negative
    value); the single-dash normalization must not rewrite the value token."""

    def test_space_separated_negative_value(self):
        o = Options.from_args(["--cpu-requests", "-100"])
        assert o.cpu_requests == -100

    def test_single_dash_flags_still_normalized(self):
        o = Options.from_args(["-metrics-port", "7001"])
        assert o.metrics_port == 7001

    def test_stray_dash_digit_token_fails_closed(self):
        # a value whose flag was forgotten must not be silently dropped
        # (Go: 'flag provided but not defined: -100')
        with pytest.raises(ValueError):
            Options.from_args(["-100"])
        with pytest.raises(ValueError):
            Options.from_args(["--metrics-port", "7001", "-100"])


class TestSuperpositionReleaseOnCommit:
    """Instance types pruned between the DRA superposition filter and the
    final updated_instance_types of the same can_add must release their
    contributions for the just-committed claims too (allocator.go
    totalRequirements 'updated each time instance types are released')."""

    def test_commit_releases_pruned_instance_types(self):
        from karpenter_tpu.scheduling.dynamicresources.allocator import Allocator

        alloc = Allocator.__new__(Allocator)
        alloc.claim_allocation_metadata = {}
        released = []
        alloc.release_instance_types = lambda ck, names: released.append((ck, set(names)))
        alloc.commit_template_metadata = lambda metas: alloc.claim_allocation_metadata.update(metas)

        from karpenter_tpu.controllers.provisioning.scheduling import nodeclaim as nc_mod

        claim = nc_mod.SchedulingNodeClaim.__new__(nc_mod.SchedulingNodeClaim)
        claim.pods = []
        claim.allocator = alloc
        claim._dra_claim_keys = set()
        claim.dra_trackers = {}
        claim._pending_dra = {}
        meta = SimpleNamespace(contributed={"it-a": 1, "it-b": 1}, devices={}, recompute_total=lambda: None)
        claim._pending_dra_meta = {"ns/claim": meta}
        claim.reservation_manager = None
        claim.instance_type_options = [SimpleNamespace(name="it-a"), SimpleNamespace(name="it-b")]
        claim.spec_requests = {}
        claim.daemon_overhead_groups = []
        claim.topology = SimpleNamespace(record=lambda *a, **k: None)
        claim.template = SimpleNamespace(taints=[])
        claim.requirements = None

        pod = SimpleNamespace(
            key=lambda: "default/p",
            spec=SimpleNamespace(containers=[], init_containers=[], host_network=False),
        )
        pod_data = SimpleNamespace(requests={})
        kept = [SimpleNamespace(name="it-a")]
        claim.add(pod, pod_data, updated_requirements=None, updated_instance_types=kept)
        assert released == [("ns/claim", {"it-b"})]


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
