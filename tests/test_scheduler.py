"""Scheduler behavior specs, modeled on the reference's
scheduling/suite_test.go + topology_test.go + instance_selection_test.go.
"""

import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.controllers.provisioning.scheduling import Scheduler
from karpenter_tpu.kube import Store
from karpenter_tpu.scheduling.taints import Taint
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def build_env(node_pools=None, types=None):
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    node_pools = node_pools if node_pools is not None else [make_nodepool(requirements=LINUX_AMD64)]
    for np in node_pools:
        store.create(np)
    types = types if types is not None else catalog.construct_instance_types()
    return store, clock, cluster, node_pools, types


def make_scheduler(store, clock, cluster, node_pools, types, daemons=(), **kw):
    return Scheduler(
        store,
        cluster,
        node_pools,
        {np.metadata.name: types for np in node_pools},
        cluster.nodes(),
        list(daemons),
        clock,
        **kw,
    )


class TestBasicScheduling:
    def test_single_pod_new_nodeclaim(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="1")])
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1
        nc = results.new_node_claims[0]
        assert len(nc.pods) == 1
        # instance types should all fit the pod and be linux/amd64
        assert all("amd64-linux" in it.name for it in nc.instance_type_options)

    def test_pods_pack_onto_one_inflight_node(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="1") for _ in range(4)])
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1
        assert len(results.new_node_claims[0].pods) == 4

    def test_huge_pod_unschedulable(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="10000")])
        assert not results.all_pods_scheduled()
        assert len(results.new_node_claims) == 0

    def test_node_selector_pins_zone(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"})])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert nc.requirements.get(wk.ZONE_LABEL_KEY).values == {"test-zone-b"}

    def test_impossible_zone_fails(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={wk.ZONE_LABEL_KEY: "mars"})])
        assert not results.all_pods_scheduled()

    def test_incompatible_custom_label_fails(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={"team": "infra"})])
        assert not results.all_pods_scheduled()

    def test_custom_nodepool_label_schedules(self):
        np = make_nodepool(requirements=LINUX_AMD64, labels={"team": "infra"})
        env = build_env([np])
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={"team": "infra"})])
        assert results.all_pods_scheduled()


class TestTaints:
    def test_untolerated_taint_fails(self):
        np = make_nodepool(requirements=LINUX_AMD64, taints=[Taint(key="dedicated", value="gpu")])
        env = build_env([np])
        s = make_scheduler(*env)
        assert not s.solve([make_pod()]).all_pods_scheduled()

    def test_tolerated_taint_schedules(self):
        np = make_nodepool(requirements=LINUX_AMD64, taints=[Taint(key="dedicated", value="gpu")])
        env = build_env([np])
        s = make_scheduler(*env)
        pod = make_pod(tolerations=[{"key": "dedicated", "operator": "Equal", "value": "gpu"}])
        assert s.solve([pod]).all_pods_scheduled()


class TestExistingNodes:
    def test_existing_capacity_used(self):
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        store, clock, cluster, pools, types = build_env()
        nc = NodeClaim(metadata=ObjectMeta(name="c1", labels={wk.NODEPOOL_LABEL_KEY: "default-pool"}))
        nc.status.provider_id = "kwok://n1"
        nc.status.conditions.set_true(COND_REGISTERED)
        nc.status.conditions.set_true(COND_INITIALIZED)
        store.create(nc)
        node = Node(
            metadata=ObjectMeta(
                name="n1",
                labels={
                    wk.NODEPOOL_LABEL_KEY: "default-pool",
                    wk.HOSTNAME_LABEL_KEY: "n1",
                    wk.ZONE_LABEL_KEY: "test-zone-a",
                },
            ),
            spec=NodeSpec(provider_id="kwok://n1"),
            status=NodeStatus(
                capacity=parse_resource_list({"cpu": "4", "memory": "8Gi", "pods": "110"}),
                allocatable=parse_resource_list({"cpu": "4", "memory": "8Gi", "pods": "110"}),
            ),
        )
        store.create(node)
        s = make_scheduler(store, clock, cluster, pools, types)
        results = s.solve([make_pod(cpu="2")])
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 0
        assert results.node_pod_count() == {"n1": 1}


class TestTopologySpread:
    def test_zone_spread_across_new_claims(self):
        env = build_env()
        s = make_scheduler(*env)
        selector = {"matchLabels": {"app": "web"}}
        pods = [make_pod(labels={"app": "web"}, tsc=[zone_spread(selector=selector)]) for _ in range(6)]
        results = s.solve(pods)
        assert results.all_pods_scheduled()
        zones = {}
        for nc in results.new_node_claims:
            z = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert len(z.values) == 1
            zones[next(iter(z.values))] = zones.get(next(iter(z.values)), 0) + len(nc.pods)
        # 6 pods over 4 zones with maxSkew 1: counts must differ by <= 1
        assert max(zones.values()) - min(zones.values()) <= 1
        assert sum(zones.values()) == 6

    def test_hostname_anti_affinity_one_per_node(self):
        env = build_env()
        s = make_scheduler(*env)
        selector = {"matchLabels": {"app": "web"}}
        pods = [
            make_pod(labels={"app": "web"}, anti_affinity=[hostname_anti_affinity(selector)], cpu="1")
            for _ in range(5)
        ]
        results = s.solve(pods)
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 5
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)

    def test_zone_anti_affinity_limits_count(self):
        from karpenter_tpu.kube import PodAffinityTerm

        env = build_env()
        s = make_scheduler(*env)
        selector = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(
                labels={"app": "db"},
                anti_affinity=[PodAffinityTerm(label_selector=selector, topology_key=wk.ZONE_LABEL_KEY)],
            )
            for _ in range(5)
        ]
        results = s.solve(pods)
        # Late committal (reference topology_test.go:2683): within one batch a
        # new claim's zone isn't collapsed, so it conservatively blocks all
        # zones — exactly one anti-affinity pod schedules per batch.
        assert len(results.pod_errors) == 4
        assert len([nc for nc in results.new_node_claims if nc.pods]) == 1

    def test_pod_affinity_colocates(self):
        from karpenter_tpu.kube import PodAffinityTerm

        env = build_env()
        s = make_scheduler(*env)
        selector = {"matchLabels": {"app": "cache"}}
        pods = [
            make_pod(labels={"app": "cache"}, pod_affinity=[PodAffinityTerm(label_selector=selector, topology_key=wk.ZONE_LABEL_KEY)])
            for _ in range(4)
        ]
        results = s.solve(pods)
        assert results.all_pods_scheduled()
        zones = set()
        for nc in results.new_node_claims:
            zones.update(nc.requirements.get(wk.ZONE_LABEL_KEY).values)
        assert len(zones) == 1  # all in same zone


class TestPreferences:
    def test_preferred_affinity_relaxed(self):
        env = build_env()
        s = make_scheduler(*env)
        pod = make_pod(preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["mars"]}])])
        results = s.solve([pod])
        assert results.all_pods_scheduled()  # preference dropped

    def test_preferred_affinity_respected_when_possible(self):
        env = build_env()
        s = make_scheduler(*env)
        pod = make_pod(preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-c"]}])])
        results = s.solve([pod])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert nc.requirements.get(wk.ZONE_LABEL_KEY).values == {"test-zone-c"}

    def test_required_or_terms_fallback(self):
        env = build_env()
        s = make_scheduler(*env)
        pod = make_pod(
            required_affinity=[
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["mars"]}],
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}],
            ]
        )
        results = s.solve([pod])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert nc.requirements.get(wk.ZONE_LABEL_KEY).values == {"test-zone-a"}


class TestLimitsAndWeights:
    def test_nodepool_weight_ordering(self):
        heavy = make_nodepool("heavy", requirements=LINUX_AMD64, weight=50, labels={"pool": "heavy"})
        light = make_nodepool("light", requirements=LINUX_AMD64, weight=1, labels={"pool": "light"})
        env = build_env([light, heavy])
        s = make_scheduler(*env)
        results = s.solve([make_pod()])
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].nodepool_name == "heavy"

    def test_node_limit_enforced(self):
        np = make_nodepool(requirements=LINUX_AMD64, limits={"nodes": "1"})
        env = build_env([np])
        s = make_scheduler(*env)
        # force 2 nodes via hostname anti-affinity
        selector = {"matchLabels": {"app": "x"}}
        pods = [make_pod(labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(selector)]) for _ in range(2)]
        results = s.solve(pods)
        assert len(results.new_node_claims) == 1
        assert len(results.pod_errors) == 1

    def test_cpu_limit_enforced(self):
        np = make_nodepool(requirements=LINUX_AMD64, limits={"cpu": "2"})
        env = build_env([np])
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="100")])
        assert not results.all_pods_scheduled()


class TestDaemonOverhead:
    def test_daemon_overhead_reserved(self):
        env = build_env()
        daemon = make_pod(name="daemon", cpu="1")
        s = make_scheduler(*env, daemons=[daemon])
        results = s.solve([make_pod(cpu="1")])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        # all surviving instance types must fit pod + daemon: > 2 cpu needed
        # (1x types have 0.9 allocatable cpu and cannot hold 1+1)
        assert all(it.capacity["cpu"].value >= 4 for it in nc.instance_type_options)


class TestInstanceSelection:
    def test_cheapest_types_survive(self):
        env = build_env()
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="3")])
        nc = results.new_node_claims[0]
        api_nc = nc.to_api_node_claim()
        req = next(r for r in api_nc.spec.requirements if r["key"] == wk.INSTANCE_TYPE_LABEL_KEY)
        # price-ordered: first should be the smallest fitting type (c-4x is
        # cheapest 4-cpu; 1x/2x don't fit 3 cpu + overhead)
        assert req["values"][0].endswith("amd64-linux")
        assert "c-4x-amd64-linux" == req["values"][0]

    def test_min_values_strict_fails_when_unsatisfiable(self):
        np = make_nodepool(
            requirements=[
                *LINUX_AMD64,
                {
                    "key": wk.INSTANCE_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": ["c-4x-amd64-linux"],
                    "minValues": 2,
                },
            ]
        )
        env = build_env([np])
        s = make_scheduler(*env)
        results = s.solve([make_pod()])
        assert not results.all_pods_scheduled()

    def test_min_values_satisfiable(self):
        np = make_nodepool(
            requirements=[
                *LINUX_AMD64,
                {
                    "key": wk.INSTANCE_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": ["c-4x-amd64-linux", "c-8x-amd64-linux"],
                    "minValues": 2,
                },
            ]
        )
        env = build_env([np])
        s = make_scheduler(*env)
        results = s.solve([make_pod()])
        assert results.all_pods_scheduled()


class TestPreferentialFallbackDepth:
    """Relaxation-order specs from provisioning suite_test.go:2386-2560."""

    def _solve(self, pod, node_pools=None, **kw):
        env = build_env(node_pools=node_pools)
        s = make_scheduler(*env, **kw)
        return s.solve([pod])

    def test_final_required_term_not_relaxed(self):
        # :2388 — a single required OR-term is a hard constraint
        pod = make_pod(required_affinity=[[{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["invalid"]}]])
        results = self._solve(pod)
        assert not results.all_pods_scheduled()

    def test_relaxes_multiple_required_terms_in_order(self):
        # :2409 — invalid terms peel one by one; the FIRST satisfiable term
        # wins and later OR-terms are never reached
        pod = make_pod(
            required_affinity=[
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["invalid"]}],
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["invalid"]}],
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}],
                [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}],
            ]
        )
        results = self._solve(pod)
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
        assert zr.has("test-zone-a") and not zr.has("test-zone-b")

    def test_relaxes_all_preferred_terms(self):
        # :2433 — every unsatisfiable preference peels away
        pod = make_pod(
            preferred_affinity=[
                (1, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["invalid"]}]),
                (1, [{"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": ["invalid"]}]),
            ]
        )
        results = self._solve(pod)
        assert results.all_pods_scheduled()

    def test_relaxes_lighter_weights_first(self):
        # :2452 — the highest-weight satisfiable preference survives
        reqs = LINUX_AMD64 + [
            {"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a", "test-zone-b"]}
        ]
        pod = make_pod(
            preferred_affinity=[
                (100, [{"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": ["test-zone-c"]}]),
                (50, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}]),
                (1, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}]),
            ]
        )
        results = self._solve(pod, node_pools=[make_nodepool(requirements=reqs)])
        assert results.all_pods_scheduled()
        zr = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert zr.has("test-zone-b") and not zr.has("test-zone-a")

    def test_prefer_no_schedule_tolerated_after_relaxation(self):
        # :2486 — the PreferNoSchedule taint is tolerated only after all
        # affinity preferences have been peeled
        np = make_nodepool(
            requirements=LINUX_AMD64,
            taints=[Taint(key="soft", value="true", effect="PreferNoSchedule")],
        )
        pod = make_pod(
            preferred_affinity=[
                (1, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["invalid"]}]),
            ]
        )
        results = self._solve(pod, node_pools=[np])
        assert results.all_pods_scheduled()

    def test_ignore_policy_drops_preferences_up_front(self):
        # :2565 — preference_policy=Ignore never honors preferences at all
        pod = make_pod(
            preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-c"]}])]
        )
        reqs = LINUX_AMD64 + [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}]
        results = self._solve(pod, node_pools=[make_nodepool(requirements=reqs)], preference_policy="Ignore")
        assert results.all_pods_scheduled()
        zr = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert zr.has("test-zone-a")


class TestNodePoolSelectionDepth:
    """Pool-selection specs from suite_test.go:2771-2845."""

    def test_explicit_nodepool_selector(self):
        # :2772
        pools = [make_nodepool(name="a", requirements=LINUX_AMD64), make_nodepool(name="b", requirements=LINUX_AMD64)]
        env = build_env(node_pools=pools)
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={wk.NODEPOOL_LABEL_KEY: "b"})])
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.nodepool_name == "b"

    def test_nodepool_by_template_labels(self):
        # :2780 — pods select pools via template labels
        pools = [
            make_nodepool(name="a", requirements=LINUX_AMD64, labels={"team": "red"}),
            make_nodepool(name="b", requirements=LINUX_AMD64, labels={"team": "blue"}),
        ]
        env = build_env(node_pools=pools)
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={"team": "blue"})])
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.nodepool_name == "b"

    def test_prefer_untainted_pool_over_prefer_no_schedule(self):
        # :2796 — a PreferNoSchedule-tainted pool loses to a clean one
        tainted = make_nodepool(
            name="soft", requirements=LINUX_AMD64, weight=50,
            taints=[Taint(key="soft", value="true", effect="PreferNoSchedule")],
        )
        clean = make_nodepool(name="clean", requirements=LINUX_AMD64, weight=10)
        env = build_env(node_pools=[tainted, clean])
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="1")])
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.nodepool_name == "clean"

    def test_highest_weight_pool_wins(self):
        # :2814
        pools = [
            make_nodepool(name="lo", requirements=LINUX_AMD64, weight=1),
            make_nodepool(name="hi", requirements=LINUX_AMD64, weight=80),
        ]
        env = build_env(node_pools=pools)
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="1")])
        assert results.new_node_claims[0].template.nodepool_name == "hi"

    def test_explicit_selection_beats_weight(self):
        # :2830
        pools = [
            make_nodepool(name="lo", requirements=LINUX_AMD64, weight=1),
            make_nodepool(name="hi", requirements=LINUX_AMD64, weight=80),
        ]
        env = build_env(node_pools=pools)
        s = make_scheduler(*env)
        results = s.solve([make_pod(node_selector={wk.NODEPOOL_LABEL_KEY: "lo"})])
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.nodepool_name == "lo"
