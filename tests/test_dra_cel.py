"""CEL device-selector subset: the reference evaluates DRA selectors as CEL
(resourcev1.CELDeviceSelector; allocator_test.go exactRequestWithSelector
corpus). These specs pin the subset interpreter in
scheduling/dynamicresources/cel.py against the corpus expressions and the
upstream error semantics (errors mean no-match, compile errors are sticky)."""

import pytest

from karpenter_tpu.kube import Device
from karpenter_tpu.scheduling.dynamicresources import device_matches_selectors
from karpenter_tpu.scheduling.dynamicresources.cel import (
    CelError,
    evaluate,
    matches_device,
)
from karpenter_tpu.utils.quantity import Quantity
from karpenter_tpu.utils.resources import parse_resource_list


def gpu(model="h100", name="g0", driver_attrs=None, **caps):
    attrs = {"gpu.example.com/model": model, "gpu.example.com/type": "compute"}
    attrs.update(driver_attrs or {})
    return Device(
        name=name,
        attributes=attrs,
        capacity=parse_resource_list(caps or {"memory": "40Gi"}),
    )


class TestCorpusExpressions:
    """Every distinct expression family in allocator_test.go's CEL corpus."""

    def test_driver_equality(self):
        # allocator_test.go:267 `device.driver == "gpu.example.com"`
        d = gpu()
        assert matches_device('device.driver == "gpu.example.com"', d, "gpu.example.com")
        assert not matches_device('device.driver == "gpu.example.com"', d, "nic.example.com")

    def test_attribute_equality(self):
        # allocator_test.go:487 `device.attributes["gpu.example.com"].model == "H100"`
        d = gpu(model="H100")
        assert matches_device('device.attributes["gpu.example.com"].model == "H100"', d)
        assert not matches_device('device.attributes["gpu.example.com"].model == "A100"', d)

    def test_attribute_type_discrimination(self):
        # allocator_test.go:2675/2683 compute vs network `type` split
        d = gpu()
        assert matches_device('device.attributes["gpu.example.com"].type == "compute"', d)
        assert not matches_device('device.attributes["gpu.example.com"].type == "network"', d)

    def test_single_quoted_strings(self):
        d = gpu(model="A")
        assert matches_device("device.attributes['gpu.example.com'].model == 'A'", d)

    def test_missing_attribute_means_no_match(self):
        # upstream cel.Device.Matches: evaluation error -> (false, err)
        d = gpu()
        assert not matches_device('device.attributes["gpu.example.com"].missing == "x"', d)
        assert not matches_device('device.attributes["other.example.com"].model == "x"', d)

    def test_unqualified_driver_domain_attribute(self):
        # attributes published bare resolve under the slice's own driver domain
        d = Device(name="n", attributes={"speed": "fast"})
        assert matches_device('device.attributes["nic.example.com"].speed == "fast"', d, "nic.example.com")
        assert not matches_device('device.attributes["nic.example.com"].speed == "fast"', d, "gpu.example.com")


class TestOperatorsAndLogic:
    def test_inequality(self):
        d = gpu(model="h100")
        assert matches_device('device.attributes["gpu.example.com"].model != "a100"', d)
        assert not matches_device('device.attributes["gpu.example.com"].model != "h100"', d)

    def test_numeric_comparisons(self):
        d = Device(name="n", attributes={"nic.example.com/ports": 8})
        assert matches_device('device.attributes["nic.example.com"].ports >= 8', d)
        assert matches_device('device.attributes["nic.example.com"].ports > 4', d)
        assert not matches_device('device.attributes["nic.example.com"].ports < 8', d)
        assert matches_device('device.attributes["nic.example.com"].ports <= 8', d)

    def test_numeric_string_attribute_coerces(self):
        # flat attribute storage often stringifies ints
        d = Device(name="n", attributes={"nic.example.com/ports": "8"})
        assert matches_device('device.attributes["nic.example.com"].ports >= 8', d)

    def test_boolean_attribute(self):
        d = Device(name="n", attributes={"gpu.example.com/ecc": True})
        assert matches_device('device.attributes["gpu.example.com"].ecc == true', d)
        assert not matches_device('device.attributes["gpu.example.com"].ecc == false', d)

    def test_bool_int_not_equal(self):
        # CEL never equates bool with number
        d = Device(name="n", attributes={"gpu.example.com/ecc": True})
        assert not matches_device('device.attributes["gpu.example.com"].ecc == 1', d)

    def test_and_or_not_parens(self):
        d = gpu(model="h100")
        e = ('device.attributes["gpu.example.com"].model == "h100" && '
             'device.attributes["gpu.example.com"].type == "compute"')
        assert matches_device(e, d)
        e2 = ('device.attributes["gpu.example.com"].model == "a100" || '
              'device.attributes["gpu.example.com"].type == "compute"')
        assert matches_device(e2, d)
        assert matches_device('!(device.attributes["gpu.example.com"].model == "a100")', d)
        e3 = ('(device.attributes["gpu.example.com"].model == "a100" || '
              'device.attributes["gpu.example.com"].model == "h100") && '
              'device.attributes["gpu.example.com"].type == "compute"')
        assert matches_device(e3, d)

    def test_in_list(self):
        d = gpu(model="h100")
        assert matches_device('device.attributes["gpu.example.com"].model in ["a100", "h100"]', d)
        assert not matches_device('device.attributes["gpu.example.com"].model in ["a100", "b200"]', d)

    def test_commutative_and_false_absorbs_error(self):
        # CEL && is commutative: false && <error> == false
        d = gpu(model="h100")
        e = ('device.attributes["gpu.example.com"].model == "a100" && '
             'device.attributes["gpu.example.com"].missing == "x"')
        assert not matches_device(e, d)
        e_rev = ('device.attributes["gpu.example.com"].missing == "x" && '
                 'device.attributes["gpu.example.com"].model == "a100"')
        assert not matches_device(e_rev, d)

    def test_commutative_or_true_absorbs_error(self):
        d = gpu(model="h100")
        e = ('device.attributes["gpu.example.com"].missing == "x" || '
             'device.attributes["gpu.example.com"].model == "h100"')
        assert matches_device(e, d)
        # but error || false is still an error -> no match
        e2 = ('device.attributes["gpu.example.com"].missing == "x" || '
              'device.attributes["gpu.example.com"].model == "a100"')
        assert not matches_device(e2, d)


class TestMacrosAndFunctions:
    def test_has_probe(self):
        d = gpu()
        assert matches_device('has(device.attributes["gpu.example.com"].model)', d)
        assert not matches_device('has(device.attributes["gpu.example.com"].missing)', d)
        assert matches_device('!has(device.attributes["gpu.example.com"].missing)', d)

    def test_quantity_capacity_comparison(self):
        d = gpu(memory="40Gi")
        assert matches_device('device.capacity["gpu.example.com"].memory >= quantity("40Gi")', d, "gpu.example.com")
        assert not matches_device('device.capacity["gpu.example.com"].memory >= quantity("80Gi")', d, "gpu.example.com")

    def test_capacity_missing_means_no_match(self):
        d = gpu(memory="40Gi")
        assert not matches_device('device.capacity["gpu.example.com"].vram >= quantity("1Gi")', d)

    def test_string_methods(self):
        d = gpu(model="h100-sxm")
        assert matches_device('device.attributes["gpu.example.com"].model.startsWith("h100")', d)
        assert matches_device('device.attributes["gpu.example.com"].model.endsWith("sxm")', d)
        assert matches_device('device.attributes["gpu.example.com"].model.contains("100")', d)
        assert matches_device('device.attributes["gpu.example.com"].model.matches("h[0-9]+")', d)
        assert not matches_device('device.attributes["gpu.example.com"].model.matches("^x")', d)

    def test_case_fold_methods(self):
        d = gpu(model="H100")
        assert matches_device('device.attributes["gpu.example.com"].model.lowerAscii() == "h100"', d)
        assert matches_device('device.attributes["gpu.example.com"].model.upperAscii() == "H100"', d)

    def test_size(self):
        d = gpu(model="h100")
        assert matches_device('size(device.attributes["gpu.example.com"].model) == 4', d)


class TestErrorSemantics:
    def test_parse_error_no_match(self):
        d = gpu()
        assert not matches_device('device.attributes[".broken', d)
        assert not matches_device("device.driver === 'x'", d)
        assert not matches_device("", d)

    def test_parse_error_is_sticky(self):
        d = gpu()
        assert not matches_device("device.driver ==", d)
        assert not matches_device("device.driver ==", d)  # cached CelError path

    def test_non_boolean_result_errors(self):
        d = gpu()
        with pytest.raises(CelError):
            evaluate('device.attributes["gpu.example.com"].model', d)
        assert not matches_device('device.attributes["gpu.example.com"].model', d)

    def test_type_confusion_errors(self):
        d = gpu(model="h100")
        # ordering a string against an int is an error, not False
        with pytest.raises(CelError):
            evaluate('device.attributes["gpu.example.com"].model < 5', d)

    def test_trailing_garbage_rejected(self):
        d = gpu()
        assert not matches_device('device.driver == "x" extra', d)

    def test_unparseable_quantity_comparand_is_no_match_not_crash(self):
        # Quantity.parse failures must surface as CelError (no-match), never
        # escape matches_device and crash the allocator DFS
        d = gpu(memory="40Gi")
        assert not matches_device(
            'device.capacity["gpu.example.com"].memory >= "lots"', d, "gpu.example.com"
        )
        assert not matches_device(
            'device.capacity["gpu.example.com"].memory >= true', d, "gpu.example.com"
        )

    def test_bare_capacity_gated_on_driver_domain(self):
        # bare "memory" resolves only under the publishing driver's domain,
        # like the attributes branch
        d = gpu(memory="40Gi")
        expr = 'device.capacity["other.example.com"].memory >= quantity("1Gi")'
        assert not matches_device(expr, d, "gpu.example.com")
        ok = 'device.capacity["gpu.example.com"].memory >= quantity("1Gi")'
        assert matches_device(ok, d, "gpu.example.com")

    def test_commutative_or_absorbs_type_errors(self):
        # upstream CEL: true || <any error> == true, not just missing-attr
        d = gpu(model="h100")
        e = ('device.attributes["gpu.example.com"].model < 5 || '
             'device.attributes["gpu.example.com"].model == "h100"')
        assert matches_device(e, d)
        e_and = ('device.attributes["gpu.example.com"].model < 5 && '
                 'device.attributes["gpu.example.com"].model == "x"')
        assert not matches_device(e_and, d)

    def test_bool_ordering_is_type_error(self):
        # upstream CEL has no ordering overload for booleans
        d = Device(name="n", attributes={"gpu.example.com/ecc": True})
        assert not matches_device('device.attributes["gpu.example.com"].ecc > 0', d)

    def test_string_escapes_decode(self):
        d = Device(name="n", attributes={"d/sep": "\n"})
        assert matches_device('device.attributes["d"].sep == "\\n"', d)
        assert not matches_device('device.attributes["d"].sep == "n"', d)

    def test_negative_numeric_literals(self):
        d = Device(name="n", attributes={"nic.example.com/temp": -3})
        assert matches_device('device.attributes["nic.example.com"].temp > -5', d)
        assert not matches_device('device.attributes["nic.example.com"].temp > -1', d)
        assert matches_device('device.attributes["nic.example.com"].temp == -3', d)

    def test_unary_not_binds_tighter_than_comparison(self):
        # upstream CEL parses `!x == 5` as `(!x) == 5` — a type error on a
        # non-boolean x, hence no-match; `!(x == 5)` is the boolean negation
        d = Device(name="n", attributes={"d/count": 3})
        assert not matches_device('!device.attributes["d"].count == 5', d)
        assert matches_device('!(device.attributes["d"].count == 5)', d)
        db = Device(name="n", attributes={"d/flag": False})
        # (!flag) == true  →  true == true
        assert matches_device('!device.attributes["d"].flag == true', db)


class TestSelectorIntegration:
    def test_cel_selector_dict(self):
        d = gpu(model="H100")
        assert device_matches_selectors(
            d, [{"cel": 'device.attributes["gpu.example.com"].model == "H100"'}]
        )
        assert not device_matches_selectors(
            d, [{"cel": 'device.attributes["gpu.example.com"].model == "A100"'}]
        )

    def test_cel_and_structured_mix(self):
        d = gpu(model="H100")
        sels = [
            {"cel": 'device.attributes["gpu.example.com"].type == "compute"'},
            {"attribute": "gpu.example.com/model", "operator": "In", "values": ["H100"]},
        ]
        assert device_matches_selectors(d, sels)

    def test_driver_threading(self):
        d = gpu()
        assert device_matches_selectors(
            d, [{"cel": 'device.driver == "gpu.example.com"'}], driver="gpu.example.com"
        )
        assert not device_matches_selectors(
            d, [{"cel": 'device.driver == "gpu.example.com"'}], driver="fpga.example.com"
        )

    def test_quantity_value_equivalence(self):
        assert Quantity.parse("40Gi").milli == 40 * 1024**3 * 1000


class TestAllocatorEndToEnd:
    """CEL selectors flowing through the DFS allocator, mirroring
    allocator_test.go:267 (class-level driver filter) and :7470-7474
    (request-level model split)."""

    def _build(self, devices_by_driver):
        from karpenter_tpu.kube import DeviceClass, ObjectMeta, ResourceSlice, Store
        from karpenter_tpu.scheduling.dynamicresources import Allocator
        from karpenter_tpu.state import Cluster
        from karpenter_tpu.state.informer import start_informers
        from karpenter_tpu.utils.clock import FakeClock

        store, clock = Store(), FakeClock()
        cluster = Cluster(store, clock)
        start_informers(store, cluster)
        store.create(
            DeviceClass(
                metadata=ObjectMeta(name="gpu-class"),
                selectors=[{"cel": 'device.driver == "gpu.example.com"'}],
            )
        )
        for i, (driver, devs) in enumerate(devices_by_driver.items()):
            store.create(
                ResourceSlice(
                    metadata=ObjectMeta(name=f"sl-{i}"),
                    driver=driver,
                    pool_name=f"pool-{i}",
                    node_name="node-a",
                    devices=devs,
                )
            )
        return store, Allocator(store)

    def test_class_cel_filters_wrong_driver(self):
        from karpenter_tpu.kube import ObjectMeta, ResourceClaim

        store, alloc = self._build(
            {
                "gpu.example.com": [gpu(model="H100")],
                "nic.example.com": [Device(name="nic0", attributes={"nic.example.com/speed": "100G"})],
            }
        )
        claim = ResourceClaim(
            metadata=ObjectMeta(name="c1", namespace="default"),
            requests=[{"name": "r", "deviceClassName": "gpu-class", "count": 1}],
        )
        result, err = alloc.allocate_for_node("node-a", [claim])
        assert err is None and result is not None
        picks = next(iter(result.picks.values()))
        assert picks[0][1].driver == "gpu.example.com"

    def test_request_cel_model_split(self):
        # two GPUs, one claim demanding the H100 via request-level CEL
        from karpenter_tpu.kube import ObjectMeta, ResourceClaim

        store, alloc = self._build(
            {"gpu.example.com": [gpu(model="A100", name="g0"), gpu(model="H100", name="g1")]}
        )
        claim = ResourceClaim(
            metadata=ObjectMeta(name="c1", namespace="default"),
            requests=[
                {
                    "name": "r",
                    "deviceClassName": "gpu-class",
                    "count": 1,
                    "selectors": [{"cel": 'device.attributes["gpu.example.com"].model == "H100"'}],
                }
            ],
        )
        result, err = alloc.allocate_for_node("node-a", [claim])
        assert err is None and result is not None
        picks = next(iter(result.picks.values()))
        assert picks[0][1].device.attributes["gpu.example.com/model"] == "H100"

    def test_unsatisfiable_cel_fails_allocation(self):
        from karpenter_tpu.kube import ObjectMeta, ResourceClaim

        store, alloc = self._build({"gpu.example.com": [gpu(model="A100")]})
        claim = ResourceClaim(
            metadata=ObjectMeta(name="c1", namespace="default"),
            requests=[
                {
                    "name": "r",
                    "deviceClassName": "gpu-class",
                    "count": 1,
                    "selectors": [{"cel": 'device.attributes["gpu.example.com"].model == "B200"'}],
                }
            ],
        )
        result, err = alloc.allocate_for_node("node-a", [claim])
        assert result is None
