"""TPU tensor solver: equivalence vs the host FFD oracle.

Validation criterion (SURVEY.md §7): all-pods-scheduled parity and cost <=,
plus exact constraint validation of the tensor placement — not bit-identical
placement.
"""

import random

import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube import Store
from karpenter_tpu.solver import FFDSolver, SolverSnapshot
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_snapshot(pods, node_pools=None, types=None):
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    node_pools = node_pools or [make_nodepool(requirements=LINUX_AMD64)]
    for np in node_pools:
        store.create(np)
    types = types if types is not None else catalog.construct_instance_types()
    return SolverSnapshot(
        store=store,
        cluster=cluster,
        node_pools=node_pools,
        instance_types={np.metadata.name: types for np in node_pools},
        state_nodes=cluster.nodes(),
        daemonset_pods=[],
        pods=pods,
        clock=clock,
    )


def claims_cost(results):
    total = 0.0
    for nc in results.new_node_claims:
        best = min(
            (
                o.price
                for it in nc.instance_type_options
                for o in it.offerings
                if o.available and nc.requirements.intersects(o.requirements) is None
            ),
            default=float("inf"),
        )
        total += best
    return total


def compare_backends(pods, node_pools=None, cost_tol=1.001):
    snap = make_snapshot(pods, node_pools)
    ffd_results = FFDSolver().solve(snap)

    snap2 = make_snapshot(pods, node_pools)
    tpu = TPUSolver(force=True)
    tpu_results = tpu.solve(snap2)
    assert tpu.last_backend == "tpu"

    assert set(tpu_results.pod_errors) == set(ffd_results.pod_errors), (
        f"scheduled-set mismatch: tpu={tpu_results.pod_errors} ffd={ffd_results.pod_errors}"
    )
    violations = validate_results(snap2, tpu_results)
    assert not violations, violations
    if ffd_results.new_node_claims:
        assert claims_cost(tpu_results) <= claims_cost(ffd_results) * cost_tol, (
            f"tpu cost {claims_cost(tpu_results)} > ffd cost {claims_cost(ffd_results)}"
        )
    return tpu_results, ffd_results


class TestGroupedZonePath:
    def test_skew_respected_when_zone_unavailable(self):
        # templates only offer zone-a; a spread pod batch allowed in a AND b
        # must not pile into a beyond maxSkew while b stays at zero
        types = [catalog.make_instance_type("c", cpu, zones=["test-zone-a"]) for cpu in (4, 16)]
        sel = {"matchLabels": {"app": "s"}}
        pods = [
            make_pod(
                cpu="100m",
                labels={"app": "s"},
                tsc=[zone_spread(selector=sel)],
                node_selector=None,
            )
            for _ in range(10)
        ]
        snap = make_snapshot(pods, types=types)
        tpu = TPUSolver(force=True)
        results = tpu.solve(snap)
        assert tpu.last_backend == "tpu"
        violations = validate_results(make_snapshot(pods, types=types), results)
        assert not violations, violations
        # FFD parity: with one zone available and maxSkew=1 relative to the
        # other allowed-but-unavailable zones... the reference counts only
        # domains that exist (a single known domain schedules freely)
        ffd = FFDSolver().solve(make_snapshot(pods, types=types))
        assert set(results.pod_errors) == set(ffd.pod_errors)

    def test_redistribution_respects_host_anti_affinity(self):
        # grouped item (count>=2) in BOTH a zone-spread group and a hostname
        # anti-affinity group: the per-zone fill + redistribution loops call
        # place() up to 2Z times in one step, so host caps must derive from
        # the THREADED counts — a stale step-entry cap lets redistribution
        # put a second pod on a slot its zone-fill already used.
        # Setup forces stranding: templates offer only zone-a; zone-b is
        # reachable only via one existing node that (anti-affinity) holds a
        # single pod, so part of zone-b's water-fill quota must redistribute
        # back into zone-a whose slots are already occupied.
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a"])]
        sel = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(
                cpu="500m",
                labels={"app": "db"},
                tsc=[zone_spread(max_skew=50, selector=sel)],
                anti_affinity=[hostname_anti_affinity(sel)],
            )
            for _ in range(8)
        ]

        def snap():
            store = Store()
            clock = FakeClock()
            cluster = Cluster(store, clock)
            start_informers(store, cluster)
            np_ = make_nodepool(requirements=LINUX_AMD64)
            store.create(np_)
            nc = NodeClaim(metadata=ObjectMeta(name="c1", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
            nc.status.provider_id = "kwok://n1"
            nc.status.conditions.set_true(COND_REGISTERED)
            nc.status.conditions.set_true(COND_INITIALIZED)
            store.create(nc)
            store.create(
                Node(
                    metadata=ObjectMeta(
                        name="n1",
                        labels={
                            wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                            wk.HOSTNAME_LABEL_KEY: "n1",
                            wk.ZONE_LABEL_KEY: "test-zone-b",
                        },
                    ),
                    spec=NodeSpec(provider_id="kwok://n1"),
                    status=NodeStatus(
                        capacity=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                        allocatable=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                    ),
                )
            )
            return SolverSnapshot(
                store=store,
                cluster=cluster,
                node_pools=[np_],
                instance_types={np_.metadata.name: types},
                state_nodes=cluster.nodes(),
                daemonset_pods=[],
                pods=pods,
                clock=clock,
            )

        tpu = TPUSolver(force=True)
        results = tpu.solve(snap())
        assert tpu.last_backend == "tpu"
        violations = validate_results(snap(), results)
        assert not violations, violations
        ffd = FFDSolver().solve(snap())
        # TPU must schedule at least what FFD does; here it does strictly
        # better (the FFD, like the reference's random min-domain pick at
        # topologygroup.go:226-236, can pin a pod to the offering-less zone)
        assert set(results.pod_errors) <= set(ffd.pod_errors), (results.pod_errors, ffd.pod_errors)
        assert not results.pod_errors

    def test_redistribution_reuses_open_slot_headroom(self):
        # same staleness class, cost side: a slot OPENED by the zone-a fill
        # call must stay visible (slot_compat) to the redistribution pass of
        # the same step, or zone-b's stranded quota opens a surplus node
        # instead of using the half-full one.
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        types = [catalog.make_instance_type("c", 10, zones=["test-zone-a"])]
        sel = {"matchLabels": {"app": "w"}}
        pods = [
            make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(max_skew=50, selector=sel)])
            for _ in range(11)
        ]

        def snap():
            store = Store()
            clock = FakeClock()
            cluster = Cluster(store, clock)
            start_informers(store, cluster)
            np_ = make_nodepool(requirements=LINUX_AMD64)
            store.create(np_)
            nc = NodeClaim(metadata=ObjectMeta(name="c1", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
            nc.status.provider_id = "kwok://n1"
            nc.status.conditions.set_true(COND_REGISTERED)
            nc.status.conditions.set_true(COND_INITIALIZED)
            store.create(nc)
            store.create(
                Node(
                    metadata=ObjectMeta(
                        name="n1",
                        labels={
                            wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                            wk.HOSTNAME_LABEL_KEY: "n1",
                            wk.ZONE_LABEL_KEY: "test-zone-b",
                        },
                    ),
                    spec=NodeSpec(provider_id="kwok://n1"),
                    status=NodeStatus(
                        capacity=parse_resource_list({"cpu": "2", "memory": "16Gi", "pods": "110"}),
                        allocatable=parse_resource_list({"cpu": "2", "memory": "16Gi", "pods": "110"}),
                    ),
                )
            )
            return SolverSnapshot(
                store=store,
                cluster=cluster,
                node_pools=[np_],
                instance_types={np_.metadata.name: types},
                state_nodes=cluster.nodes(),
                daemonset_pods=[],
                pods=pods,
                clock=clock,
            )

        tpu = TPUSolver(force=True)
        results = tpu.solve(snap())
        assert tpu.last_backend == "tpu"
        assert not results.pod_errors
        assert not validate_results(snap(), results)
        # 11 pods: 2 on the existing zone-b node, 9 fit one cpu-10 node
        # (9.9 cpu allocatable) — exactly ONE new claim; a stale slot_compat
        # opens a surplus second
        assert len(results.new_node_claims) == 1, [len(nc.pods) for nc in results.new_node_claims]

    def test_spread_batch_at_max_level_not_frozen(self):
        # two spread items in one group, placed in sequence: after the first
        # item the zone counts sit imbalanced (some zones at the current max
        # level). The second batch must still place fully — sequentially the
        # counts rise level-by-level and max-level zones re-admit pods; a
        # kernel that freezes zones on the step-entry skew check strands the
        # whole batch's quota.
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a", "test-zone-b"])]
        sel = {"matchLabels": {"app": "s"}}
        # item 1: three 1-cpu pods -> zones [2, 1]; item 2: ten 500m pods
        pods = [make_pod(cpu="1", labels={"app": "s"}, tsc=[zone_spread(selector=sel)]) for _ in range(3)]
        pods += [make_pod(cpu="500m", labels={"app": "s"}, tsc=[zone_spread(selector=sel)]) for _ in range(10)]
        snap = make_snapshot(pods, types=types)
        tpu = TPUSolver(force=True)
        results = tpu.solve(snap)
        assert tpu.last_backend == "tpu"
        assert not results.pod_errors, results.pod_errors
        assert not validate_results(make_snapshot(pods, types=types), results)

    def test_stranded_zone_quota_redistributes(self):
        # large skew: water-fill splits across zones, but only some zones can
        # actually open nodes — the stranded share must land elsewhere
        types = [catalog.make_instance_type("c", cpu, zones=["test-zone-b"]) for cpu in (4, 16)]
        sel = {"matchLabels": {"app": "s"}}
        pods = [
            make_pod(cpu="100m", labels={"app": "s"}, tsc=[zone_spread(max_skew=50, selector=sel)])
            for _ in range(20)
        ]
        snap = make_snapshot(pods, types=types)
        tpu = TPUSolver(force=True)
        results = tpu.solve(snap)
        ffd = FFDSolver().solve(make_snapshot(pods, types=types))
        assert set(results.pod_errors) == set(ffd.pod_errors), (results.pod_errors, ffd.pod_errors)
        assert not validate_results(make_snapshot(pods, types=types), results)


class TestTPUEquivalence:
    def test_single_pod(self):
        tpu, ffd = compare_backends([make_pod(cpu="1")])
        assert len(tpu.new_node_claims) == 1

    def test_homogeneous_packing(self):
        tpu, ffd = compare_backends([make_pod(cpu="1") for _ in range(20)])
        assert len(tpu.new_node_claims) == len(ffd.new_node_claims)

    def test_mixed_sizes(self):
        pods = [make_pod(cpu=c, memory=m) for c, m in [("4", "8Gi"), ("1", "2Gi"), ("2", "1Gi"), ("500m", "512Mi")] * 5]
        compare_backends(pods)

    def test_zone_selector(self):
        pods = [make_pod(node_selector={wk.ZONE_LABEL_KEY: "test-zone-b"}) for _ in range(3)]
        tpu, _ = compare_backends(pods)
        for nc in tpu.new_node_claims:
            assert nc.requirements.get(wk.ZONE_LABEL_KEY).values == {"test-zone-b"}

    def test_unschedulable_pod(self):
        tpu, ffd = compare_backends([make_pod(cpu="10000"), make_pod(cpu="1")])
        assert len(tpu.pod_errors) == 1

    def test_custom_label_unschedulable(self):
        compare_backends([make_pod(node_selector={"team": "infra"})])

    def test_zone_spread(self):
        sel = {"matchLabels": {"app": "web"}}
        pods = [make_pod(labels={"app": "web"}, tsc=[zone_spread(selector=sel)]) for _ in range(8)]
        tpu, _ = compare_backends(pods)
        zones = {}
        for nc in tpu.new_node_claims:
            z = next(iter(nc.requirements.get(wk.ZONE_LABEL_KEY).values))
            zones[z] = zones.get(z, 0) + sum(1 for p in nc.pods if p.metadata.labels.get("app") == "web")
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_hostname_anti_affinity(self):
        sel = {"matchLabels": {"app": "db"}}
        pods = [make_pod(labels={"app": "db"}, anti_affinity=[hostname_anti_affinity(sel)]) for _ in range(6)]
        tpu, ffd = compare_backends(pods)
        assert len(tpu.new_node_claims) == 6

    def test_taints_respected(self):
        from karpenter_tpu.scheduling.taints import Taint

        tainted = make_nodepool("tainted", requirements=LINUX_AMD64, taints=[Taint(key="dedicated", value="x")], weight=50)
        normal = make_nodepool("normal", requirements=LINUX_AMD64, weight=1)
        pods = [make_pod()]  # no toleration -> must use 'normal' despite weight
        tpu, _ = compare_backends(pods, node_pools=[tainted, normal])
        assert tpu.new_node_claims[0].template.nodepool_name == "normal"

    def test_weight_priority(self):
        heavy = make_nodepool("heavy", requirements=LINUX_AMD64, weight=50)
        light = make_nodepool("light", requirements=LINUX_AMD64, weight=1)
        tpu, _ = compare_backends([make_pod()], node_pools=[light, heavy])
        assert tpu.new_node_claims[0].template.nodepool_name == "heavy"

    @pytest.mark.heavy
    def test_random_fuzz_equivalence(self):
        rng = random.Random(42)
        for trial in range(3):
            pods = []
            for i in range(rng.randrange(10, 40)):
                kind = rng.random()
                if kind < 0.5:
                    pods.append(make_pod(cpu=rng.choice(["250m", "500m", "1", "2", "4"]), memory=rng.choice(["512Mi", "1Gi", "4Gi"])))
                elif kind < 0.7:
                    pods.append(make_pod(cpu="1", node_selector={wk.ZONE_LABEL_KEY: rng.choice(catalog.ZONES)}))
                elif kind < 0.9:
                    sel = {"matchLabels": {"app": f"w{trial}"}}
                    pods.append(make_pod(cpu="500m", labels={"app": f"w{trial}"}, tsc=[zone_spread(selector=sel)]))
                else:
                    pods.append(make_pod(cpu="8", memory="16Gi"))
            compare_backends(pods)


class TestMultiGroupSpread:
    def test_pod_in_two_zone_groups_respects_both_skews(self):
        # group g1 has 3 scheduled pods in zone-b, group g2 has 5 in zone-a
        # (both maxSkew=1); a pending pod member of BOTH groups has no
        # feasible zone when templates offer only a and b. The batch kernel
        # must not place it via the summed-counts water-fill (which would
        # violate g1's skew in zone-b).
        from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a", "test-zone-b"])]
        sel1 = {"matchLabels": {"g1": "y"}}
        sel2 = {"matchLabels": {"g2": "y"}}
        pending = [
            make_pod(
                cpu="100m",
                labels={"g1": "y", "g2": "y"},
                tsc=[zone_spread(selector=sel1), zone_spread(selector=sel2)],
            )
        ]

        def snap():
            store = Store()
            clock = FakeClock()
            cluster = Cluster(store, clock)
            start_informers(store, cluster)
            np_ = make_nodepool(requirements=LINUX_AMD64)
            store.create(np_)
            for name, zone in (("na", "test-zone-a"), ("nb", "test-zone-b")):
                nc = NodeClaim(metadata=ObjectMeta(name=f"c-{name}", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
                nc.status.provider_id = f"kwok://{name}"
                nc.status.conditions.set_true(COND_REGISTERED)
                nc.status.conditions.set_true(COND_INITIALIZED)
                store.create(nc)
                store.create(
                    Node(
                        metadata=ObjectMeta(
                            name=name,
                            labels={
                                wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                                wk.HOSTNAME_LABEL_KEY: name,
                                wk.ZONE_LABEL_KEY: zone,
                            },
                        ),
                        spec=NodeSpec(provider_id=f"kwok://{name}"),
                        status=NodeStatus(
                            capacity=parse_resource_list({"cpu": "32", "memory": "64Gi", "pods": "110"}),
                            allocatable=parse_resource_list({"cpu": "32", "memory": "64Gi", "pods": "110"}),
                        ),
                    )
                )
            for i in range(3):  # g1 pods bound in zone-b
                p = make_pod(cpu="100m", name=f"g1-{i}", labels={"g1": "y"})
                p.spec.node_name = "nb"
                store.create(p)
            for i in range(5):  # g2 pods bound in zone-a
                p = make_pod(cpu="100m", name=f"g2-{i}", labels={"g2": "y"})
                p.spec.node_name = "na"
                store.create(p)
            return SolverSnapshot(
                store=store,
                cluster=cluster,
                node_pools=[np_],
                instance_types={np_.metadata.name: types},
                state_nodes=cluster.nodes(),
                daemonset_pods=[],
                pods=pending,
                clock=clock,
            )

        tpu = TPUSolver(force=True)
        results = tpu.solve(snap())
        assert tpu.last_backend == "tpu"
        violations = validate_results(snap(), results)
        assert not violations, violations
        ffd = FFDSolver().solve(snap())
        assert set(results.pod_errors) == set(ffd.pod_errors), (results.pod_errors, ffd.pod_errors)
        assert len(results.pod_errors) == 1  # no feasible zone: a violates g2, b violates g1


class TestSignatureCapability:
    def test_init_container_host_ports_split_signatures(self):
        # hostPorts change the tensor lowering (port masks), so a spec field
        # carrying them (including init containers) must split signatures —
        # otherwise replicas would inherit the wrong port bitmask
        from karpenter_tpu.kube.objects import Container
        from karpenter_tpu.solver.encode import pod_signature

        plain = make_pod(cpu="1")
        ported = make_pod(cpu="1")
        ported.spec.init_containers = [Container(name="init", ports=[{"containerPort": 80, "hostPort": 80}])]
        plain.spec.init_containers = [Container(name="init")]
        assert pod_signature(plain) != pod_signature(ported)

        # host ports are IN-window: the tensor path handles them directly
        snap = make_snapshot([plain, ported])
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()


class TestFallback:
    def test_asymmetric_pod_affinity_falls_back(self):
        # selector-symmetric required affinity is in-window since r4
        # (test_pod_affinity_tpu.py); the ASYMMETRIC direction — a pod whose
        # affinity selector matches other pods that don't declare it — stays
        # on the host oracle
        from karpenter_tpu.kube import PodAffinityTerm

        sel = {"matchLabels": {"app": "x"}}
        pods = [
            make_pod(labels={"app": "x"}, name="target"),
            make_pod(labels={"app": "seeker"}, name="seeker", pod_affinity=[PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)]),
        ]
        snap = make_snapshot(pods)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        # the host oracle may defer the seeker to the next reconcile if it
        # processes before its target lands — but the target must place
        assert "default/target" not in results.pod_errors

    def test_preferred_affinity_falls_back(self):
        pods = [make_pod(preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["mars"]}])])]
        snap = make_snapshot(pods)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        assert results.all_pods_scheduled()


class TestProductionValidation:
    """A device-kernel bug must never reach NodeClaim creation: the in-solve
    validator (solver/check.py) rejects the placement and the solve falls back
    to the exact host FFD path."""

    def _corrupting(self, original):
        import numpy as np

        def corrupted(t, items, n_pods):
            out = original(t, items, n_pods)
            # inject a bug: cram every pod onto slot 0 (overcommits resources)
            counts = np.asarray(items.item_count)
            W = counts.shape[0]
            pad = out["nz_item"].shape[0] - W
            out["nz_item"] = np.concatenate([np.arange(W), np.full(pad, -1)]).astype(out["nz_item"].dtype)
            out["nz_slot"] = np.concatenate([np.zeros(W, np.int64), np.full(pad, -1)]).astype(out["nz_slot"].dtype)
            out["nz_count"] = np.concatenate([counts, np.zeros(pad, counts.dtype)]).astype(out["nz_count"].dtype)
            out["leftovers"] = np.zeros_like(out["leftovers"])
            return out

        return corrupted

    def test_injected_bug_falls_back_to_ffd(self, monkeypatch):
        from karpenter_tpu.metrics import SOLVER_VALIDATION_FAILURES_TOTAL, make_registry
        from karpenter_tpu.models import scheduler_model_grouped as smg

        monkeypatch.setattr(smg, "greedy_pack_grouped_compressed", self._corrupting(smg.greedy_pack_grouped_compressed))
        pods = [make_pod(cpu="7", memory="28Gi") for _ in range(64)]
        registry = make_registry()
        solver = TPUSolver(registry=registry)
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert any("validation" in r for r in solver.last_fallback_reasons)
        assert registry.counter(SOLVER_VALIDATION_FAILURES_TOTAL).total() == 1
        # the fallback result is the exact host solution: everything scheduled
        assert results.all_pods_scheduled()
        assert not validate_results(make_snapshot(pods), results)

    def test_injected_bug_raises_under_force(self, monkeypatch):
        from karpenter_tpu.models import scheduler_model_grouped as smg

        monkeypatch.setattr(smg, "greedy_pack_grouped_compressed", self._corrupting(smg.greedy_pack_grouped_compressed))
        solver = TPUSolver(force=True)
        with pytest.raises(RuntimeError, match="validation"):
            solver.solve(make_snapshot([make_pod(cpu="7", memory="28Gi") for _ in range(64)]))

    def test_valid_solve_passes_validator_with_registry(self):
        from karpenter_tpu.metrics import SOLVER_SOLVE_TOTAL, SOLVER_VALIDATION_FAILURES_TOTAL, make_registry

        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(9)]
        registry = make_registry()
        solver = TPUSolver(force=True, registry=registry)
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "tpu"
        assert registry.counter(SOLVER_VALIDATION_FAILURES_TOTAL).total() == 0
        assert registry.counter(SOLVER_SOLVE_TOTAL).value(backend="tpu") == 1
        assert results.all_pods_scheduled()


class TestRelaxableWindow:
    """Soft constraints are IN-window tier-0 (preferences honored exactly like
    the un-relaxed FFD); the host relaxation loop takes over only when tier-0
    leaves a pod unplaced."""

    def test_satisfiable_preferred_affinity_stays_on_tpu(self):
        pods = [
            make_pod(cpu="1", preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}])])
            for _ in range(6)
        ]
        snap = make_snapshot(pods)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        for nc in results.new_node_claims:
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert zr.has("test-zone-b") and not zr.has("test-zone-a")
        assert not validate_results(make_snapshot(pods), results)

    def test_heaviest_preferred_term_wins(self):
        # only the heaviest term is honored tier-0 (requirements.go:74-110)
        pods = [
            make_pod(
                cpu="1",
                preferred_affinity=[
                    (5, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}]),
                    (50, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-c"]}]),
                ],
            )
        ]
        solver = TPUSolver(force=True)
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "tpu"
        zr = results.new_node_claims[0].requirements.get(wk.ZONE_LABEL_KEY)
        assert zr.has("test-zone-c") and not zr.has("test-zone-a")

    def test_unsatisfiable_preferred_falls_back_to_relaxation(self):
        from karpenter_tpu.metrics import SOLVER_FALLBACK_TOTAL, make_registry

        pods = [make_pod(preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["mars"]}])])]
        registry = make_registry()
        solver = TPUSolver(registry=registry)
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert "relaxation required" in " ".join(solver.last_fallback_reasons)
        assert registry.counter(SOLVER_FALLBACK_TOTAL).value(reason="relaxation") == 1
        # the host loop relaxed the preference and scheduled the pod
        assert results.all_pods_scheduled()

    def test_schedule_anyway_spread_stays_on_tpu(self):
        sel = {"matchLabels": {"app": "s"}}
        pods = [
            make_pod(cpu="1", labels={"app": "s"}, tsc=[zone_spread(1, sel, when="ScheduleAnyway")])
            for _ in range(9)
        ]
        compare_backends(pods)

    def test_or_term_node_affinity_stays_on_tpu(self):
        # two OR-terms; the first is satisfiable, so tier-0 (term[0] only)
        # schedules everything without relaxation
        pods = [
            make_pod(
                cpu="1",
                required_affinity=[
                    [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-a"]}],
                    [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}],
                ],
            )
            for _ in range(4)
        ]
        snap = make_snapshot(pods)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()

    def test_unsatisfiable_first_or_term_falls_back(self):
        pods = [
            make_pod(
                required_affinity=[
                    [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["mars"]}],
                    [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}],
                ],
            )
        ]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        # the host loop dropped the first OR-term and scheduled on zone-b
        assert results.all_pods_scheduled()

    def test_ignore_policy_keeps_conservative_window(self):
        pods = [make_pod(preferred_affinity=[(10, [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}])])]
        snap = make_snapshot(pods)
        snap.preference_policy = "Ignore"
        solver = TPUSolver()
        solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        assert "relaxable node affinity" in " ".join(solver.last_fallback_reasons)


class TestEncodeCache:
    def test_cache_hits_produce_identical_encoding(self):
        from karpenter_tpu.solver.encode import EncodeCache, encode

        pods = [make_pod(cpu="1", labels={"app": "w"}) for _ in range(6)]
        snap = make_snapshot(pods)
        cache = EncodeCache()
        e1 = encode(snap, cache=cache)
        e2 = encode(snap, cache=cache)  # all signature lookups hit
        # signatures are stamped on the Pod objects themselves (cross-solve)
        assert sum(1 for p in pods if getattr(p, "_sig_stamp", None) is not None) == 6
        import numpy as np

        assert np.array_equal(e1.sig_of_pod, e2.sig_of_pod)
        assert np.array_equal(e1.sig_req, e2.sig_req)

    def test_pod_edit_bumps_resource_version_and_recomputes(self):
        from karpenter_tpu.solver.encode import EncodeCache, encode

        snap = make_snapshot([make_pod(cpu="1", name="w0")])
        # route the pod through the store so updates bump resourceVersion
        pod = snap.pods[0]
        snap.store.create(pod)
        stored = snap.store.get("Pod", "w0")
        cache = EncodeCache()
        snap.pods = [stored]
        e1 = encode(snap, cache=cache)
        rv1 = stored.metadata.resource_version

        def grow(p):
            from karpenter_tpu.utils.resources import parse_resource_list

            p.spec.containers[0].resources = {"requests": parse_resource_list({"cpu": "3"})}

        snap.store.patch("Pod", "w0", grow)
        updated = snap.store.get("Pod", "w0")
        assert updated.metadata.resource_version != rv1
        snap2 = make_snapshot([updated])
        e2 = encode(snap2, cache=cache)
        # the changed spec re-encoded: the request vector reflects 3 cpu
        assert float(e2.sig_req[0][0]) == 3000.0  # milli-cpu
        assert float(e1.sig_req[0][0]) == 1000.0

    def test_solver_cache_accelerates_warm_resolve(self):
        # behavioral: repeated solves through one TPUSolver reuse signatures
        pods = [make_pod(cpu="1") for _ in range(30)]
        solver = TPUSolver(force=True)
        r1 = solver.solve(make_snapshot(pods))
        stamps = [getattr(p, "_sig_stamp", None) for p in pods]
        assert sum(1 for s in stamps if s is not None) == 30
        r2 = solver.solve(make_snapshot(pods))
        # pure hits: the stamp objects are untouched (no rebuild)
        assert [getattr(p, "_sig_stamp", None) for p in pods] == stamps
        assert len(r1.new_node_claims) == len(r2.new_node_claims)


class TestHostPortsWindow:
    """Host ports are tensorized (per-slot port bitmasks): replicas sharing a
    hostPort must land one-per-node; distinct specific IPs coexist; wildcard
    conflicts with everything on the (port, proto)."""

    def _ported_pod(self, port=8080, ip=None, proto="TCP", cpu="100m", name=None):
        from karpenter_tpu.kube.objects import Container

        p = make_pod(cpu=cpu, name=name)
        entry = {"containerPort": port, "hostPort": port, "protocol": proto}
        if ip:
            entry["hostIP"] = ip
        p.spec.containers[0].ports = [entry]
        return p

    def test_wildcard_port_replicas_one_per_node(self):
        pods = [self._ported_pod() for _ in range(4)]
        tpu_results, ffd_results = compare_backends(pods)
        assert len(tpu_results.new_node_claims) == 4
        assert all(len(nc.pods) == 1 for nc in tpu_results.new_node_claims)

    def test_distinct_specific_ips_coexist(self):
        pods = [self._ported_pod(ip="10.0.0.1"), self._ported_pod(ip="10.0.0.2")]
        tpu_results, _ = compare_backends(pods)
        assert len([nc for nc in tpu_results.new_node_claims if nc.pods]) == 1

    def test_wildcard_conflicts_with_specific(self):
        pods = [self._ported_pod(ip="10.0.0.1"), self._ported_pod()]  # specific + wildcard
        tpu_results, _ = compare_backends(pods)
        assert len(tpu_results.new_node_claims) == 2

    def test_different_protocols_coexist(self):
        pods = [self._ported_pod(proto="TCP"), self._ported_pod(proto="UDP")]
        tpu_results, _ = compare_backends(pods)
        assert len([nc for nc in tpu_results.new_node_claims if nc.pods]) == 1

    def test_different_ports_coexist(self):
        pods = [self._ported_pod(port=8080), self._ported_pod(port=9090)]
        tpu_results, _ = compare_backends(pods)
        assert len([nc for nc in tpu_results.new_node_claims if nc.pods]) == 1

    def test_existing_node_port_blocks_placement(self):
        # an existing node whose bound pod already holds the port cannot take
        # another ported pod — the tensor path sees the node's port usage
        from test_sharded import existing_node_snapshot

        bound = self._ported_pod(name="bound")
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a"])]
        snap = existing_node_snapshot([self._ported_pod(name="incoming")], types)
        # bind the ported pod to the existing node, then refresh the state
        # view so the node's port usage is visible to encode
        bound.spec.node_name = "n1"
        snap.store.create(bound)
        snap.state_nodes = snap.cluster.nodes()
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        # the incoming pod must NOT land on n1 (port taken): a new claim opens
        assert len(results.new_node_claims) == 1
        assert not any(en.pods for en in results.existing_nodes)

    def test_mixed_ported_and_plain_pack_together(self):
        pods = [self._ported_pod(name=f"ported-{i}") for i in range(3)] + [
            make_pod(cpu="100m", name=f"plain-{i}") for i in range(6)
        ]
        tpu_results, ffd_results = compare_backends(pods)
        # 3 nodes for the ported pods; plain pods share them
        assert len(tpu_results.new_node_claims) == 3

    def test_validator_catches_port_conflicts(self, monkeypatch):
        # corrupt the pack to pile ported replicas onto one slot: the in-solve
        # validator must reject and fall back to FFD
        import numpy as np

        from karpenter_tpu.models import scheduler_model_grouped as smg

        original = smg.greedy_pack_grouped_compressed

        def corrupted(t, items, n_pods):
            # pile every REAL item (pads have count 0) onto slot 0
            out = original(t, items, n_pods)
            counts = np.asarray(items.item_count)
            real = np.nonzero(counts > 0)[0]
            cap = out["nz_item"].shape[0]
            k = min(len(real), cap)
            for key, vals in (("nz_item", real[:k]), ("nz_slot", np.zeros(k, np.int64)), ("nz_count", counts[real[:k]])):
                arr = np.full(cap, -1 if key != "nz_count" else 0, dtype=out[key].dtype)
                arr[:k] = vals
                out[key] = arr
            out["leftovers"] = np.zeros_like(out["leftovers"])
            return out

        monkeypatch.setattr(smg, "greedy_pack_grouped_compressed", corrupted)
        pods = [self._ported_pod(name=f"p{i}") for i in range(3)]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        assert solver.last_backend == "ffd-fallback"
        assert any("host port conflict" in r for r in solver.last_fallback_reasons)
        assert results.all_pods_scheduled()


class TestDecodeLaunchability:
    def test_empty_post_filter_set_falls_back(self, monkeypatch):
        """weak #7: an empty post-filter instance set must NOT silently trust
        the packed row — the claim is re-checked and the solve falls back."""
        import numpy as np

        # sabotage the vectorized fits filter so every type seems too small
        original = TPUSolver._template_ctx

        def broken_ctx(template, groups, enc, cache):
            its, alloc, ginfo, ov_groups = original(template, groups, enc, cache)
            return its, np.zeros_like(alloc), ginfo, ov_groups

        monkeypatch.setattr(TPUSolver, "_template_ctx", staticmethod(broken_ctx))
        pods = [make_pod(cpu="1") for _ in range(4)]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods))
        # fits filter empty AND the packed row's re-check fails (zero alloc
        # matrix is a lie, but the re-check uses real allocatable -> passes);
        # either way the result must be sound
        assert results.all_pods_scheduled()
        if solver.last_backend == "tpu":
            # the re-check accepted the genuinely-launchable packed row
            for nc in results.new_node_claims:
                assert len(nc.instance_type_options) == 1

    def test_unlaunchable_packed_row_raises_under_force(self, monkeypatch):
        import numpy as np

        original = TPUSolver._template_ctx

        def broken_ctx(template, groups, enc, cache):
            its, alloc, ginfo, ov_groups = original(template, groups, enc, cache)
            return its, np.zeros_like(alloc), ginfo, ov_groups

        monkeypatch.setattr(TPUSolver, "_template_ctx", staticmethod(broken_ctx))
        # also make every offering unavailable post-encode so the packed-row
        # re-check cannot pass either
        from karpenter_tpu.solver import tpu as tpu_mod

        orig_decode = TPUSolver._decode

        def sabotage_offerings(self, snap, enc, assignment, slot_basis, slot_zoneset):
            for its in snap.instance_types.values():
                for it in its:
                    for o in it.offerings:
                        o.available = False
            return orig_decode(self, snap, enc, assignment, slot_basis, slot_zoneset)

        monkeypatch.setattr(TPUSolver, "_decode", sabotage_offerings)
        solver = TPUSolver(force=True)
        with pytest.raises(tpu_mod.DecodeError):
            solver.solve(make_snapshot([make_pod(cpu="1")]))

    def test_row_cache_hits_and_invalidates_on_generation(self):
        from karpenter_tpu.solver.encode import EncodeCache, encode

        pods = [make_pod(cpu="1") for _ in range(5)]
        snap = make_snapshot(pods)
        cache = EncodeCache()
        e1 = encode(snap, cache=cache)
        rows1 = cache.rows
        e2 = encode(snap, cache=cache)
        assert cache.rows is rows1, "unchanged cluster must reuse the row artifacts"
        import numpy as np

        assert np.array_equal(e1.row_alloc, e2.row_alloc)
        assert np.array_equal(e1.row_labels, e2.row_labels)
        # pending-pod-only mutations bump `generation` but NOT
        # `node_generation` — the row cache deliberately survives them
        # (steady-state churn would otherwise forbid every delta encode)
        snap.cluster.generation += 1
        encode(snap, cache=cache)
        assert cache.rows is rows1, "a rows-neutral mutation must not rebuild rows"
        # any row-side mutation bumps node_generation: rows rebuild
        snap.cluster.node_generation += 1
        encode(snap, cache=cache)
        assert cache.rows is not rows1

    def test_row_cache_invalidates_on_nodepool_change(self):
        from karpenter_tpu.solver.encode import EncodeCache, encode

        snap = make_snapshot([make_pod(cpu="1")])
        cache = EncodeCache()
        encode(snap, cache=cache)
        rows1 = cache.rows
        snap.node_pools[0].spec.template.labels = {"rolled": "v2"}
        encode(snap, cache=cache)
        assert cache.rows is not rows1, "nodepool hash change must rebuild rows"

    def test_cached_rows_produce_equal_solves(self):
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, {"matchLabels": {"app": "w"}})]) for _ in range(8)]
        solver = TPUSolver(force=True)
        snap = make_snapshot(pods)
        r1 = solver.solve(snap)
        r2 = solver.solve(snap)  # row + signature caches both hit
        assert len(r1.new_node_claims) == len(r2.new_node_claims)
        assert sorted(len(nc.pods) for nc in r1.new_node_claims) == sorted(len(nc.pods) for nc in r2.new_node_claims)
        assert not validate_results(make_snapshot(pods), r2)

    def test_row_cache_distinguishes_snapshot_node_selection(self):
        # the disruption simulation filters candidate nodes out of
        # state_nodes WITHOUT mutating the cluster: same generation, different
        # node selection must NOT share cached rows
        from test_sharded import existing_node_snapshot

        from karpenter_tpu.solver.encode import EncodeCache, encode

        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a"])]
        snap = existing_node_snapshot([make_pod(cpu="1")], types)
        cache = EncodeCache()
        e1 = encode(snap, cache=cache)
        assert e1.n_existing == 1
        # simulate: the candidate node removed from the snapshot view only
        snap.state_nodes = []
        e2 = encode(snap, cache=cache)
        assert e2.n_existing == 0, "filtered-node snapshot must rebuild rows"


class TestDaemonPortsWindow:
    """Daemonset host ports are IN-window: fresh slots open with their row's
    daemon port reservations (suite_test.go:955 semantics on the tensor
    path)."""

    def _ported(self, port, cpu="1", name=None):
        from karpenter_tpu.kube.objects import Container

        p = make_pod(cpu=cpu, name=name)
        p.spec.containers[0].ports = [{"containerPort": port, "hostPort": port, "protocol": "TCP"}]
        return p

    def _snap_with_daemon(self, pods, daemon_port=8080):
        snap = make_snapshot(pods)
        d = make_pod(cpu="500m", name="daemon-tpl")
        d.spec.containers[0].ports = [{"containerPort": daemon_port, "hostPort": daemon_port, "protocol": "TCP"}]
        snap.daemonset_pods = [d]
        return snap

    def test_conflicting_pod_unschedulable_on_both_backends(self):
        from karpenter_tpu.solver import FFDSolver

        pod = self._ported(8080, name="clash")
        ffd = FFDSolver().solve(self._snap_with_daemon([pod]))
        tpu = TPUSolver(force=True)
        res = tpu.solve(self._snap_with_daemon([pod]))
        assert tpu.last_backend == "tpu"
        assert set(res.pod_errors) == set(ffd.pod_errors) == {pod.key()}
        assert not res.new_node_claims

    def test_disjoint_port_schedules_on_tensor_path(self):
        pod = self._ported(9090, name="ok")
        tpu = TPUSolver(force=True)
        res = tpu.solve(self._snap_with_daemon([pod]))
        assert tpu.last_backend == "tpu"
        assert not res.pod_errors
        assert validate_results(self._snap_with_daemon([pod]), res) == []

    def test_portless_pods_unaffected_by_daemon_ports(self):
        pods = [make_pod(cpu="1", name=f"p{i}") for i in range(3)]
        tpu = TPUSolver(force=True)
        res = tpu.solve(self._snap_with_daemon(pods))
        assert tpu.last_backend == "tpu"
        assert not res.pod_errors

    def test_claim_options_exclude_daemon_conflicted_group(self):
        # a daemon pinned (by nodeSelector) to ONE instance type holds 8080
        # only on that type's daemon group: a ported pod may schedule on the
        # other groups, but the conflicted type must never reach the claim's
        # instance_type_options (nodeclaim.py:430 group filtering at decode)
        from karpenter_tpu.solver import FFDSolver

        pinned_it = "c-4x-amd64-linux"
        d = make_pod(cpu="100m", name="daemon-tpl", node_selector={wk.INSTANCE_TYPE_LABEL_KEY: pinned_it})
        d.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]

        def snap():
            s = make_snapshot([self._ported(8080, name="web")])
            s.daemonset_pods = [d]
            return s

        ffd = FFDSolver().solve(snap())
        tpu = TPUSolver(force=True)
        res = tpu.solve(snap())
        assert tpu.last_backend == "tpu"
        assert not res.pod_errors and not ffd.pod_errors
        for nc in res.new_node_claims:
            names = {it.name for it in nc.instance_type_options}
            assert pinned_it not in names, "conflicted daemon group leaked into claim options"
        for nc in ffd.new_node_claims:
            names = {it.name for it in nc.instance_type_options}
            assert pinned_it not in names
