"""Composed delta solves: signature growth + recredit widening + row refresh.

PR 12's contract: the pod-delta path serves EVERY steady-state churn
composition — mixed add+remove batches, arrivals of never-before-seen pod
shapes (the per-signature tensors GROW under the bucket envelope), removals
of ported / keyed-anti pods (slot state recomputed from survivors), and
bind-flush row drift over a stable node set (row refresh) — with placements
equivalent to a fresh full encode and a machine-readable reject reason
(encode.DELTA_REJECT_REASONS) whenever it genuinely cannot.
"""

from __future__ import annotations

import random

import pytest

from helpers import make_pod, zone_spread
from karpenter_tpu.solver.encode import DELTA_REJECT_REASONS
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot


def _placed_pod_names(results):
    names = set()
    for nc in results.new_node_claims:
        names.update(p.metadata.name for p in nc.pods)
    for en in results.existing_nodes:
        names.update(p.metadata.name for p in en.pods)
    return names


def _claims(results):
    return [nc for nc in results.new_node_claims if nc.pods]


def _warm(pods):
    snap = make_snapshot(list(pods))
    solver = TPUSolver(force=True)
    results = solver.solve(snap)
    assert solver.last_solve_mode == "full"
    assert not results.pod_errors
    return snap, solver


SHAPES = [("250m", "512Mi"), ("500m", "512Mi"), ("500m", "1Gi"), ("1", "1Gi")]
NEW_SHAPES = [("311m", "413Mi"), ("613m", "217Mi"), ("911m", "1111Mi"), ("157m", "87Mi")]


class TestMixedChurnComposition:
    @pytest.mark.parametrize("seed", [3, 11, 42])
    def test_randomized_mixed_new_shape_churn_matches_fresh_full(self, seed):
        """Randomized add/remove/new-shape sequences: every step must stay on
        the delta path, and the final placement must match a fresh full
        encode of the same snapshot (placed-set equality + claim-count
        parity, the PR 2/7/10 delta standard)."""
        rng = random.Random(seed)
        pods = [make_pod(cpu=c, memory=m) for c, m in rng.choices(SHAPES, k=24)]
        snap, solver = _warm(pods)
        fresh_pool = list(NEW_SHAPES)
        for _ in range(4):
            for _ in range(rng.randrange(1, 4)):
                snap.pods.pop(rng.randrange(len(snap.pods)))
            for _ in range(rng.randrange(1, 4)):
                if fresh_pool and rng.random() < 0.5:
                    c, m = fresh_pool.pop()  # a never-interned shape: growth
                else:
                    c, m = rng.choice(SHAPES)
                snap.pods.append(make_pod(cpu=c, memory=m))
            results = solver.solve(snap)
            assert solver.last_solve_mode == "delta", (
                solver.last_solve_mode,
                solver.encode_cache.last_delta_reject,
            )
            assert not results.pod_errors
        fresh = TPUSolver(force=True)
        full = fresh.solve(make_snapshot(list(snap.pods)))
        assert not full.pod_errors
        assert _placed_pod_names(results) == _placed_pod_names(full)
        assert len(_claims(results)) <= len(_claims(full)) + 1

    def test_grown_encode_chains_as_next_delta_base(self):
        """A grown encode is a first-class delta base: the next solve deltas
        off it, a later pod of the GROWN shape resolves as interned, and
        parity holds at the end of the chain."""
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(10)])
        newcomer = make_pod(cpu="313m", memory="209Mi")
        snap.pods.append(newcomer)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert newcomer.metadata.name in _placed_pod_names(r)
        # chain 1: removal off the grown base
        snap.pods.pop(0)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        # chain 2: the grown shape is now interned — no second growth needed
        again = make_pod(cpu="313m", memory="209Mi")
        snap.pods.append(again)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert again.metadata.name in _placed_pod_names(r)
        full = TPUSolver(force=True).solve(make_snapshot(list(snap.pods)))
        assert _placed_pod_names(r) == _placed_pod_names(full)

    def test_grown_spread_member_joins_existing_group(self):
        """A new shape that DECLARES an already-built spread group grows onto
        the signature axis with correct membership: the newcomer must honor
        the combined skew against already-placed members."""
        sel = {"app": "web"}
        pods = [make_pod(cpu="500m", labels=sel, tsc=[zone_spread(selector=sel)]) for _ in range(8)]
        snap, solver = _warm(pods)
        # same group (identical constraint + labels), NEW request shape
        newcomer = make_pod(cpu="433m", memory="333Mi", labels=sel, tsc=[zone_spread(selector=sel)])
        snap.pods.append(newcomer)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta", solver.encode_cache.last_delta_reject
        assert not r.pod_errors
        assert newcomer.metadata.name in _placed_pod_names(r)
        # parity: a fresh full encode agrees on the placed set
        full = TPUSolver(force=True).solve(make_snapshot(list(snap.pods)))
        assert _placed_pod_names(r) == _placed_pod_names(full)

    def test_new_group_identity_routes_full_with_reason(self):
        """A new shape declaring a group the base never built cannot grow —
        the group axis would have to grow — and routes full with reason
        "unseen-sig"."""
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(6)])
        # matchLabels form so the selector matches ONLY the declaring pod
        # (a bare dict selector is match-all, which would flag asymmetry)
        sel = {"matchLabels": {"app": "brand-new-spread"}}
        snap.pods.append(make_pod(cpu="500m", labels={"app": "brand-new-spread"}, tsc=[zone_spread(selector=sel)]))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert solver.encode_cache.last_delta_reject == "unseen-sig"
        assert not r.pod_errors


class TestRecreditWidening:
    def test_randomized_ported_pod_churn_parity(self):
        """Removing ported pods rebuilds the slot port planes from survivors
        — and the resulting placements still satisfy host-port exclusivity
        and match a fresh full encode."""
        rng = random.Random(7)

        def ported(port):
            p = make_pod(cpu="500m")
            p.spec.containers[0].ports = [{"containerPort": port, "hostPort": port, "protocol": "TCP"}]
            return p

        pods = [make_pod(cpu="500m") for _ in range(8)] + [ported(8080) for _ in range(3)]
        rng.shuffle(pods)
        snap, solver = _warm(pods)
        # remove one ported + one plain pod, then add one ported back
        snap.pods.remove(next(p for p in snap.pods if p.spec.containers[0].ports))
        snap.pods.pop(0)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not r.pod_errors
        snap.pods.append(ported(8080))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        assert not r.pod_errors
        # port exclusivity: no two ported pods share a node
        holders = []
        for nc in r.new_node_claims:
            holders.extend(nc.hostname for p in nc.pods if p.spec.containers[0].ports)
        for en in r.existing_nodes:
            holders.extend(en.name for p in en.pods if p.spec.containers[0].ports)
        assert len(holders) == len(set(holders))
        full = TPUSolver(force=True).solve(make_snapshot(list(snap.pods)))
        assert _placed_pod_names(r) == _placed_pod_names(full)

    def test_spread_removal_then_refill_parity(self):
        """Spread-member removals recredit the committed domain; refilling
        with the same shape must rebalance into the vacated domains exactly
        like a fresh full solve would."""
        sel = {"app": "spread"}
        pods = [make_pod(cpu="500m", labels=sel, tsc=[zone_spread(selector=sel)]) for _ in range(12)]
        snap, solver = _warm(pods)
        for _ in range(3):
            snap.pods.pop(2)
        r = solver.solve(snap)
        assert solver.last_backend == "tpu"
        snap.pods.extend(make_pod(cpu="500m", labels=sel, tsc=[zone_spread(selector=sel)]) for _ in range(3))
        r = solver.solve(snap)
        assert not r.pod_errors
        assert len(_placed_pod_names(r)) == 12
        full = TPUSolver(force=True).solve(make_snapshot(list(snap.pods)))
        assert len(_placed_pod_names(full)) == 12

    def test_dom_affinity_owner_removal_still_routes_full(self):
        """Required pod-affinity recording (domain bootstrap/commit) stays
        the one hard-irreversible removal family, with reason
        "irreversible"."""
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.kube.objects import PodAffinityTerm

        sel = {"app": "aff"}
        term = PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL_KEY)
        pods = [make_pod(cpu="500m", labels=sel, pod_affinity=[term]) for _ in range(4)]
        snap, solver = _warm(pods)
        snap.pods.pop()
        r = solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert not r.pod_errors
        # the reject is attributed on the newest trace
        assert solver._trace.attribution.get("delta_reject") == "irreversible"


class TestDeltaRejectAttribution:
    def test_reason_enum_is_closed(self):
        assert set(DELTA_REJECT_REASONS) == {
            "unseen-sig", "row-key", "vol-rv", "pvc", "cap", "reorder",
            "fallback-global", "irreversible", "slot-exhausted", "validate",
            "no-carry",
        }

    def test_pvc_append_reason(self):
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(4)])
        snap.pods.append(make_pod(cpu="500m", volumes=[{"persistentVolumeClaim": {"claimName": "c1"}}]))
        solver.solve(snap)
        assert solver.encode_cache.last_delta_reject == "pvc"

    def test_cap_reason(self):
        snap, solver = _warm([make_pod(cpu="250m") for _ in range(4)])
        snap.pods.extend(make_pod(cpu="250m") for _ in range(200))  # > max(64, 3*4)
        solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert solver.encode_cache.last_delta_reject == "cap"

    def test_reorder_reason(self):
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(6)])
        snap.pods[0], snap.pods[3] = snap.pods[3], snap.pods[0]
        solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert solver.encode_cache.last_delta_reject == "reorder"

    def test_unseen_sig_reason_for_ungrowable_shape(self):
        # a custom resource name outside the base's resource axis cannot be
        # appended to the [S, R] tensors — growth refuses, reason unseen-sig
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(4)])
        odd = make_pod(cpu="500m")
        odd.spec.containers[0].resources["requests"]["vendor.example/gpu"] = __import__(
            "karpenter_tpu.utils.quantity", fromlist=["Quantity"]
        ).Quantity.parse("1")
        snap.pods.append(odd)
        solver.solve(snap)
        assert solver.encode_cache.last_delta_reject == "unseen-sig"

    def test_row_key_reason_on_pool_change(self):
        snap, solver = _warm([make_pod(cpu="500m") for _ in range(4)])
        snap.pods.append(make_pod(cpu="500m"))
        # shrink the catalog: the instance-type identity tuple in the row
        # key changes — a genuine row-side move the refresh cannot absorb
        name = snap.node_pools[0].metadata.name
        snap.instance_types[name] = snap.instance_types[name][:-1]
        solver.solve(snap)
        assert solver.last_solve_mode == "full"
        assert solver.encode_cache.last_delta_reject == "row-key"

    def test_reject_counter_emitted(self):
        from karpenter_tpu import metrics as m

        reg = m.make_registry()
        snap = make_snapshot([make_pod(cpu="500m") for _ in range(4)])
        solver = TPUSolver(force=True, registry=reg)
        solver.solve(snap)
        snap.pods[0], snap.pods[1] = snap.pods[1], snap.pods[0]
        solver.solve(snap)
        assert reg.counter(m.SOLVER_DELTA_REJECT_TOTAL).value(reason="reorder") == 1


class TestGrowthBucketMonotonicity:
    def test_growth_under_highwater_records_zero_recompiles(self, monkeypatch):
        """With high-water bucketing ON, a signature-growth delta whose axes
        stay inside the established marks must not retrace any jitted
        kernel."""
        from karpenter_tpu.models.scheduler_model import reset_bucket_highwater
        from karpenter_tpu.obs.trace import sentinel

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "1")
        reset_bucket_highwater()
        try:
            snap, solver = _warm([make_pod(cpu=c, memory=mem) for c, mem in SHAPES * 3])
            # warm BOTH delta directions (their cold compiles land here)
            snap.pods.append(make_pod(cpu="500m", memory="512Mi"))
            solver.solve(snap)
            snap.pods.pop(0)
            solver.solve(snap)
            before = sentinel().snapshot()
            # mixed churn with an UNSEEN shape: growth under the marks
            snap.pods.pop(0)
            snap.pods.append(make_pod(cpu="619m", memory="153Mi"))
            r = solver.solve(snap)
            assert solver.last_solve_mode == "delta"
            assert not r.pod_errors
            assert sentinel().delta(before) == {}
        finally:
            reset_bucket_highwater()


class TestRowRefresh:
    def test_bind_flush_churn_stays_on_delta_path(self):
        """The live-store integration: with pods binding and departing on a
        STABLE node set (the churn harness steady state), the row-refresh
        delta absorbs the node_generation drift — steady solves stay
        "delta" and the full-solve breakdown stays empty."""
        from test_churn_loop import small_spec

        from karpenter_tpu.serving import ChurnHarness

        h = ChurnHarness(small_spec(iterations=4, warmup_cycles=1))
        try:
            rep = h.run()
        finally:
            h.close()
        assert rep.solves > 0
        assert rep.delta_hit_rate >= 0.9, (rep.modes, rep.full_solve_reasons)
        # whatever little routed full must carry a known reject reason
        assert set(rep.full_solve_reasons) <= set(DELTA_REJECT_REASONS)

    def test_row_refresh_diff_applies_to_carry(self):
        """Unit-level: a refreshed delta encode carries delta_row_diff and
        the solver's delta path consumes it (trace attribution names the
        refresh)."""
        from test_churn_loop import small_spec

        from karpenter_tpu.serving import ChurnHarness

        h = ChurnHarness(small_spec(iterations=2, warmup_cycles=1))
        try:
            h.run()
            refreshed = [
                t
                for t in h.recorder.traces()
                if t.mode == "delta" and t.attribution.get("row_refresh")
            ]
            assert refreshed, "no solve recorded a row refresh"
        finally:
            h.close()
