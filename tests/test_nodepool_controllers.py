"""NodePool aux controllers (reference: pkg/controllers/nodepool/{hash,counter,
readiness,registrationhealth,validation}).
"""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_DRIFTED
from karpenter_tpu.apis.nodepool import (
    COND_NODE_REGISTRATION_HEALTHY,
    COND_NODEPOOL_READY,
    COND_NODEPOOL_VALIDATION_SUCCEEDED,
)
from karpenter_tpu.controllers.nodepool.hash import NODEPOOL_HASH_VERSION
from karpenter_tpu.controllers.nodepool.readiness import COND_NODECLASS_READY
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.state import nodepoolhealth

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env(pool=None):
    env = Environment(options=Options())
    env.store.create(pool or make_nodepool(requirements=LINUX_AMD64))
    return env


class TestHash:
    def test_stamps_hash_and_version_annotations(self):
        env = make_env()
        env.nodepool_hash.reconcile()
        np = env.store.list("NodePool")[0]
        assert np.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == np.hash()
        assert np.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] == NODEPOOL_HASH_VERSION

    def test_hash_changes_when_template_changes(self):
        env = make_env()
        env.nodepool_hash.reconcile()
        before = env.store.list("NodePool")[0].metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]

        def mutate(np):
            np.spec.template.labels["team"] = "infra"

        env.store.patch("NodePool", "default-pool", mutate)
        env.nodepool_hash.reconcile()
        after = env.store.list("NodePool")[0].metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]
        assert before != after

    def test_version_bump_rehashes_undrifted_claims_only(self):
        env = make_env()
        env.store.create(make_pod())
        env.settle()
        # simulate claims stamped by an older hash version
        for nc in env.store.list("NodeClaim"):
            def stale(obj):
                obj.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
                obj.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = "stale"
            env.store.patch("NodeClaim", nc.metadata.name, stale)
        def stale_np(obj):
            obj.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
        env.store.patch("NodePool", "default-pool", stale_np)
        env.nodepool_hash.reconcile()
        np = env.store.list("NodePool")[0]
        for nc in env.store.list("NodeClaim"):
            assert nc.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] == NODEPOOL_HASH_VERSION
            assert nc.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == np.hash()

    def test_version_bump_keeps_drifted_claim_hash(self):
        env = make_env()
        env.store.create(make_pod())
        env.settle()
        nc = env.store.list("NodeClaim")[0]
        def drift(obj):
            obj.status.conditions.set_true(COND_DRIFTED, now=env.clock.now())
            obj.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
            obj.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = "stale"
        env.store.patch("NodeClaim", nc.metadata.name, drift)
        def stale_np(obj):
            obj.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
        env.store.patch("NodePool", "default-pool", stale_np)
        env.nodepool_hash.reconcile()
        nc = env.store.get("NodeClaim", nc.metadata.name)
        assert nc.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == "stale"
        assert nc.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] == NODEPOOL_HASH_VERSION


class TestCounter:
    def test_counts_nodes_and_resources(self):
        env = make_env()
        for _ in range(3):
            env.store.create(make_pod(cpu="3"))
        env.settle()
        np = env.store.list("NodePool")[0]
        assert np.status.node_count == len(env.store.list("Node"))
        assert np.status.resources["cpu"].value >= 3
        assert "memory" in np.status.resources

    def test_zero_after_scale_down(self):
        env = make_env()
        env.store.create(make_pod())
        env.settle()
        assert env.store.list("NodePool")[0].status.node_count >= 1
        for p in env.store.list("Pod"):
            env.store.delete("Pod", p.metadata.name, namespace=p.metadata.namespace, grace=False)
        env.settle(rounds=30)
        np = env.store.list("NodePool")[0]
        assert np.status.node_count == env.store.count("Node")


class TestValidation:
    def test_valid_pool_passes(self):
        env = make_env()
        env.nodepool_validation.reconcile()
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_true(COND_NODEPOOL_VALIDATION_SUCCEEDED)

    def test_restricted_label_fails(self):
        pool = make_nodepool(requirements=LINUX_AMD64)
        pool.spec.template.labels["karpenter.sh/custom"] = "x"
        env = make_env(pool)
        env.nodepool_validation.reconcile()
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)

    def test_nodepool_key_in_requirements_fails(self):
        pool = make_nodepool(requirements=LINUX_AMD64 + [{"key": wk.NODEPOOL_LABEL_KEY, "operator": "In", "values": ["x"]}])
        env = make_env(pool)
        env.nodepool_validation.reconcile()
        assert env.store.list("NodePool")[0].status.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)

    def test_bad_operator_fails(self):
        pool = make_nodepool(requirements=LINUX_AMD64 + [{"key": "team", "operator": "Wat", "values": ["x"]}])
        env = make_env(pool)
        env.nodepool_validation.reconcile()
        assert env.store.list("NodePool")[0].status.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)

    def test_gt_requires_single_integer(self):
        pool = make_nodepool(requirements=LINUX_AMD64 + [{"key": "slots", "operator": "Gt", "values": ["a"]}])
        env = make_env(pool)
        env.nodepool_validation.reconcile()
        assert env.store.list("NodePool")[0].status.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)

    def test_duplicate_taint_fails(self):
        from karpenter_tpu.scheduling.taints import Taint

        pool = make_nodepool(requirements=LINUX_AMD64)
        pool.spec.template.taints = [Taint("a", "x", "NoSchedule"), Taint("a", "y", "NoSchedule")]
        env = make_env(pool)
        env.nodepool_validation.reconcile()
        assert env.store.list("NodePool")[0].status.conditions.is_false(COND_NODEPOOL_VALIDATION_SUCCEEDED)


class TestReadiness:
    def test_ready_with_kwok_nodeclass(self):
        env = make_env()
        env.nodepool_readiness.reconcile()
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_true(COND_NODECLASS_READY)
        assert np.status.conditions.is_true(COND_NODEPOOL_READY)

    def test_missing_nodeclass_blocks(self):
        pool = make_nodepool(requirements=LINUX_AMD64)
        pool.spec.template.node_class_ref = {"group": "karpenter.kwok.sh", "kind": "KWOKNodeClass", "name": "missing"}
        env = make_env(pool)
        env.nodepool_readiness.reconcile()
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_false(COND_NODECLASS_READY)
        assert np.status.conditions.is_false(COND_NODEPOOL_READY)

    def test_not_ready_pool_is_not_provisioned(self):
        pool = make_nodepool(requirements=LINUX_AMD64)
        pool.spec.template.node_class_ref = {"group": "karpenter.kwok.sh", "kind": "KWOKNodeClass", "name": "missing"}
        env = make_env(pool)
        env.store.create(make_pod())
        env.settle()
        assert env.store.count("NodeClaim") == 0


class TestRegistrationHealth:
    def test_successful_registrations_mark_healthy(self):
        env = make_env()
        env.store.create(make_pod())
        env.settle()
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_true(COND_NODE_REGISTRATION_HEALTHY)

    def test_repeated_failures_mark_unhealthy(self):
        env = make_env()
        pool = env.store.list("NodePool")[0]
        uid = pool.metadata.uid
        env.nodepool_registration_health.reconcile()
        for _ in range(2):
            env.np_state.update(uid, False)
        assert env.np_state.status(uid) == nodepoolhealth.STATUS_UNHEALTHY

    def test_liveness_timeout_flips_condition_false(self):
        from karpenter_tpu.controllers.nodeclaim.lifecycle import REGISTRATION_TTL_SECONDS

        env = make_env()
        # provision directly (no lifecycle tick): the claim is never launched,
        # so no node ever appears and registration can only time out
        env.store.create(make_pod())
        env.clock.step(2.0)
        # provision but block node materialization: drop pending nodes forever
        env.provisioner.reconcile(force=True)
        assert env.store.count("NodeClaim") == 1
        # two registration timeouts in a row -> unhealthy
        for _ in range(2):
            nc = env.store.list("NodeClaim")[0]
            env.clock.step(REGISTRATION_TTL_SECONDS + 1)
            env.lifecycle._liveness(nc)
            if env.store.count("NodeClaim") == 0:
                env.provisioner.trigger(None)
                env.clock.step(2.0)
                env.provisioner.reconcile(force=True)
        np = env.store.list("NodePool")[0]
        assert np.status.conditions.is_false(COND_NODE_REGISTRATION_HEALTHY)

    def test_spec_change_resets_to_unknown(self):
        env = make_env()
        env.store.create(make_pod())
        env.settle()
        assert env.store.list("NodePool")[0].status.conditions.is_true(COND_NODE_REGISTRATION_HEALTHY)

        # a spec change alone must reset health: the store bumps generation
        def bump(np):
            np.spec.template.labels["x"] = "y"

        env.store.patch("NodePool", "default-pool", bump)
        env.nodepool_registration_health.reconcile()
        np = env.store.list("NodePool")[0]
        cond = np.status.conditions.get(COND_NODE_REGISTRATION_HEALTHY)
        assert cond is not None and cond.status == "Unknown"
