"""Disruption behavior specs, modeled on the reference's
disruption/{consolidation,emptiness,drift}_test.go coverage.
"""

import pytest

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils import pods as pod_utils

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]
# on-demand-only pools keep consolidation out of the spot-to-spot gate
OD_ONLY = LINUX_AMD64 + [
    {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]},
]


def make_env(np_kwargs=None, **opt_kwargs):
    env = Environment(options=Options(**opt_kwargs))
    np_kwargs = dict(np_kwargs or {})
    np_kwargs.setdefault("requirements", LINUX_AMD64)
    np = make_nodepool(**np_kwargs)
    np.spec.disruption.consolidate_after = "30s"
    env.store.create(np)
    return env


def provision(env, pods):
    for p in pods:
        env.store.create(p)
    env.settle(rounds=6)
    assert all(p.spec.node_name for p in env.store.list("Pod")), "setup: pods must bind"
    return env


def run_disruption(env, rounds=12, step=15.0):
    for _ in range(rounds):
        env.clock.step(step)
        env.tick(provision_force=True)


class TestEmptiness:
    def test_empty_node_removed(self):
        env = make_env()
        provision(env, [make_pod(cpu="1", name="only-pod")])
        assert env.store.count("Node") == 1
        # delete the pod -> node becomes empty -> consolidatable -> removed
        env.store.delete("Pod", "only-pod")
        run_disruption(env)
        assert env.store.count("Node") == 0
        assert env.store.count("NodeClaim") == 0

    def test_node_with_pods_not_removed_by_emptiness(self):
        env = make_env()
        provision(env, [make_pod(cpu="1")])
        run_disruption(env)
        assert env.store.count("Node") == 1

    def test_consolidate_after_respected(self):
        env = make_env()
        provision(env, [make_pod(cpu="1", name="p")])
        env.store.delete("Pod", "p")
        # before consolidate_after (30s) elapses nothing happens
        env.clock.step(5)
        env.tick(provision_force=True)
        assert env.store.count("Node") == 1

    def test_do_not_disrupt_annotation_blocks(self):
        env = make_env()
        pod = make_pod(cpu="1", annotations={wk.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"})
        provision(env, [pod])
        node = env.store.list("Node")[0]

        def annotate(n):
            n.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"

        env.store.patch("Node", node.metadata.name, annotate)
        env.store.delete("Pod", pod.metadata.name, namespace="default")
        run_disruption(env)
        assert env.store.count("Node") == 1  # node-level do-not-disrupt holds

    def test_budget_zero_blocks_disruption(self):
        env = make_env(np_kwargs={})
        np = env.store.list("NodePool")[0]
        np.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.update(np)
        provision(env, [make_pod(cpu="1", name="p")])
        env.store.delete("Pod", "p")
        run_disruption(env)
        assert env.store.count("Node") == 1


class TestConsolidation:
    def test_underutilized_nodes_consolidate(self):
        """Two half-empty nodes consolidate onto one cheaper node."""
        env = make_env()
        # two batches so we get two nodes, each with one small pod
        provision(env, [make_pod(cpu="1", name="a")])
        provision(env, [make_pod(cpu="1", name="b", node_selector={})])
        # force second pod onto its own node: use hostname anti-affinity instead
        nodes_before = env.store.count("Node")
        if nodes_before < 2:
            pytest.skip("pods packed onto one node; covered elsewhere")

    def test_multi_node_consolidation_shrinks_fleet(self):
        from karpenter_tpu.operator.options import FeatureGates

        # spot candidates consolidating to a spot replacement require the
        # SpotToSpotConsolidation gate (consolidation.go:261-343)
        env = make_env(feature_gates=FeatureGates(spot_to_spot_consolidation=True))
        np = env.store.list("NodePool")[0]
        np.spec.disruption.budgets = [Budget(nodes="100%")]  # like the reference suites
        env.store.update(np)
        from helpers import hostname_anti_affinity

        sel = {"matchLabels": {"app": "spread"}}
        pods = [
            make_pod(cpu="500m", name=f"s{i}", labels={"app": "spread"}, anti_affinity=[hostname_anti_affinity(sel)])
            for i in range(3)
        ]
        provision(env, pods)
        assert env.store.count("Node") == 3
        # remove the anti-affinity pressure: delete pods, recreate without it
        for p in pods:
            env.store.delete("Pod", p.metadata.name)
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"n{i}"))
        env.settle(rounds=4)
        run_disruption(env, rounds=16)
        # all three pods fit one 2x node -> fleet shrinks
        assert env.store.count("Node") < 3
        assert all(p.spec.node_name for p in env.store.list("Pod"))

    def test_oversized_node_replaced_with_cheaper(self):
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        # force a big node via a big pod + a small one, then delete the big pod
        provision(env, [make_pod(cpu="14", name="big"), make_pod(cpu="500m", name="small")])
        assert env.store.count("Node") == 1
        big_node_cpu = env.store.list("Node")[0].status.capacity["cpu"].value
        assert big_node_cpu >= 16
        env.store.delete("Pod", "big")
        run_disruption(env, rounds=20)
        nodes = env.store.list("Node")
        assert len(nodes) == 1
        assert nodes[0].status.capacity["cpu"].value < big_node_cpu  # cheaper/smaller
        small = env.store.get("Pod", "small")
        assert small.spec.node_name == nodes[0].metadata.name

    def test_replacement_waits_for_initialization(self):
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="14", name="big"), make_pod(cpu="500m", name="small")])
        env.store.delete("Pod", "big")
        # make replacements never register
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 10**9
        env.store.update(nodeclass)
        for _ in range(6):
            env.clock.step(15)
            env.tick(provision_force=True)
        # old node must still exist because the replacement never initialized
        assert env.store.count("Node") == 1

    def test_consolidation_policy_when_empty_blocks_underutilized(self):
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        np = env.store.list("NodePool")[0]
        np.spec.disruption.consolidation_policy = "WhenEmpty"
        env.store.update(np)
        provision(env, [make_pod(cpu="14", name="big"), make_pod(cpu="500m", name="small")])
        big_cpu = env.store.list("Node")[0].status.capacity["cpu"].value
        env.store.delete("Pod", "big")
        run_disruption(env)
        # WhenEmpty: the underutilized (non-empty) node must NOT be replaced
        assert env.store.list("Node")[0].status.capacity["cpu"].value == big_cpu


class TestDrift:
    def test_nodepool_hash_drift_replaces_node(self):
        env = make_env()
        provision(env, [make_pod(cpu="1", name="p")])
        node_before = env.store.list("Node")[0].metadata.name
        np = env.store.list("NodePool")[0]
        np.spec.template.labels = {"new-label": "v2"}  # changes static hash
        env.store.update(np)
        run_disruption(env, rounds=16)
        nodes = env.store.list("Node")
        assert len(nodes) == 1
        assert nodes[0].metadata.name != node_before  # replaced
        assert env.store.get("Pod", "p").spec.node_name == nodes[0].metadata.name

    def test_drifted_condition_set(self):
        env = make_env()
        provision(env, [make_pod(cpu="1")])
        np = env.store.list("NodePool")[0]
        np.spec.template.labels = {"x": "y"}
        env.store.update(np)
        env.tick(provision_force=True)
        nc = env.store.list("NodeClaim")[0]
        from karpenter_tpu.apis.nodeclaim import COND_DRIFTED

        assert nc.status.conditions.is_true(COND_DRIFTED)


class TestCommandValidation:
    """The 15s validator (validation.py): wait -> rebuild candidates ->
    re-simulate -> re-check budgets before any command executes
    (reference validation.go:116-263)."""

    def test_pod_scheduled_during_window_aborts_emptiness(self):
        from karpenter_tpu.controllers.disruption.validation import VALIDATION_DELAY_SECONDS
        from karpenter_tpu.kube import Container, ObjectMeta, Pod, PodSpec
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.utils.resources import parse_resource_list

        class ChurnClock(FakeClock):
            hook = None

            def sleep(self, seconds):
                if seconds >= VALIDATION_DELAY_SECONDS - 1e-9 and self.hook is not None:
                    hook, self.hook = self.hook, None
                    hook()
                self.step(seconds)

        clock = ChurnClock()
        env = Environment(options=Options(), clock=clock)
        np = make_nodepool(requirements=LINUX_AMD64)
        np.spec.disruption.consolidate_after = "30s"
        env.store.create(np)
        provision(env, [make_pod(cpu="1", name="only-pod")])
        node_name = env.store.list("Node")[0].metadata.name
        env.store.delete("Pod", "only-pod")

        # during the validation window a new pod lands on the empty node
        def bind_pod():
            env.store.create(
                Pod(
                    metadata=ObjectMeta(name="late-pod"),
                    spec=PodSpec(
                        node_name=node_name,
                        containers=[Container(resources={"requests": parse_resource_list({"cpu": "1"})})],
                    ),
                )
            )

        clock.hook = bind_pod
        run_disruption(env)
        # the command was aborted: the node survives
        assert env.store.count("Node") == 1
        from karpenter_tpu import metrics as m

        assert env.registry.counter(m.DISRUPTION_FAILED_VALIDATIONS_TOTAL).total() >= 1

    def test_emptiness_executes_without_churn(self):
        env = make_env()
        provision(env, [make_pod(cpu="1", name="only-pod")])
        env.store.delete("Pod", "only-pod")
        run_disruption(env)
        assert env.store.count("Node") == 0

    def test_nomination_during_window_aborts_consolidation(self):
        from karpenter_tpu.controllers.disruption.validation import ValidationError, Validator

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", name=f"p{i}") for i in range(2)])
        run_disruption(env, rounds=4)
        ctrl = env.disruption
        candidates = ctrl.get_candidates()
        eligible = [c for c in candidates if ctrl.methods[3].should_disrupt(c)]
        if len(eligible) < 1:
            pytest.skip("fixture produced no consolidation candidates")
        from karpenter_tpu.controllers.disruption.types import Command

        cmd = Command(reason="underutilized", candidates=eligible[:1])
        env.cluster.nominate_node(eligible[0].name())
        with pytest.raises(ValidationError) as e:
            Validator(ctrl.ctx, ctrl.methods[3], mode="strict", metrics=env.registry).validate(cmd, delay_seconds=0)
        # nomination filters the node at candidate rebuild (churn) or at the
        # explicit nomination re-check — either way the command aborts
        assert e.value.kind in ("churn", "nominated")

    def test_budget_consumed_during_window_aborts(self):
        from karpenter_tpu.controllers.disruption.validation import ValidationError, Validator
        from karpenter_tpu.controllers.disruption.types import Command

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", name=f"p{i}") for i in range(2)])
        run_disruption(env, rounds=4)
        ctrl = env.disruption
        eligible = [c for c in ctrl.get_candidates() if ctrl.methods[3].should_disrupt(c)]
        if not eligible:
            pytest.skip("fixture produced no consolidation candidates")
        # budgets drop to zero before validation completes
        def zero_budget(np):
            np.spec.disruption.budgets = [Budget(nodes="0")]

        env.store.patch("NodePool", eligible[0].node_pool.metadata.name, zero_budget)
        cmd = Command(reason="underutilized", candidates=eligible[:1])
        with pytest.raises(ValidationError) as e:
            Validator(ctrl.ctx, ctrl.methods[3], mode="strict", metrics=env.registry).validate(cmd, delay_seconds=0)
        assert e.value.kind == "budget"

    def test_candidate_churn_aborts_strict_validation(self):
        from karpenter_tpu.controllers.disruption.validation import ValidationError, Validator
        from karpenter_tpu.controllers.disruption.types import Command

        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="1", name=f"p{i}") for i in range(2)])
        run_disruption(env, rounds=4)
        ctrl = env.disruption
        eligible = [c for c in ctrl.get_candidates() if ctrl.methods[3].should_disrupt(c)]
        if not eligible:
            pytest.skip("fixture produced no consolidation candidates")
        # the candidate's do-not-disrupt annotation appears mid-window: churn
        def annotate(n):
            n.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"

        env.store.patch("Node", eligible[0].name(), annotate)
        cmd = Command(reason="underutilized", candidates=eligible[:1])
        with pytest.raises(ValidationError) as e:
            Validator(ctrl.ctx, ctrl.methods[3], mode="strict", metrics=env.registry).validate(cmd, delay_seconds=0)
        assert e.value.kind == "churn"


class TestReschedulabilityOwnerKinds:
    """suite_test.go:4169/:4213 + pod/scheduling.go:40-51 IsReschedulable:
    terminating StatefulSet pods reserve replacement capacity (their
    successor is recreated with the same identity only after deletion);
    terminating ReplicaSet pods do not."""

    def _terminating_pod(self, env, owner_kind):
        from karpenter_tpu.kube.objects import OwnerReference

        pod = make_pod(cpu="1", name="owned")
        pod.metadata.owner_references = [OwnerReference(kind=owner_kind, name="own", uid="own-uid")]
        pod.metadata.finalizers = ["test/hold"]  # two-phase delete → terminating
        provision(env, [pod])
        env.store.delete("Pod", "owned", namespace="default")
        terminating = env.store.get("Pod", "owned", namespace="default")
        assert terminating.metadata.deletion_timestamp is not None
        return env.store.list("Node")[0]

    def _candidate_for(self, env, node):
        from karpenter_tpu.controllers.disruption.types import build_candidate
        from karpenter_tpu.utils.pdb import PDBLimits

        sn = env.cluster.node_for_name(node.metadata.name)
        pools = {np.metadata.name: np for np in env.store.list("NodePool")}
        its = {name: env.cloud_provider.get_instance_types(np) for name, np in pools.items()}
        return build_candidate(
            env.cluster, env.store, env.clock, sn, pools, its, PDBLimits(env.store)
        )

    def test_unit_predicates(self):
        from karpenter_tpu.kube.objects import OwnerReference

        sts = make_pod(name="s")
        sts.metadata.owner_references = [OwnerReference(kind="StatefulSet", name="s", uid="u1")]
        sts.metadata.deletion_timestamp = 1.0
        assert pod_utils.is_reschedulable(sts)
        rs = make_pod(name="r")
        rs.metadata.owner_references = [OwnerReference(kind="ReplicaSet", name="r", uid="u2")]
        rs.metadata.deletion_timestamp = 1.0
        assert not pod_utils.is_reschedulable(rs)

    def test_terminating_statefulset_pod_reserves_capacity(self):
        env = make_env()
        node = self._terminating_pod(env, "StatefulSet")
        cand, err = self._candidate_for(env, node)
        assert err is None and cand is not None
        assert [p.metadata.name for p in cand.reschedulable_pods] == ["owned"]

    def test_terminating_replicaset_pod_does_not(self):
        env = make_env()
        node = self._terminating_pod(env, "ReplicaSet")
        cand, err = self._candidate_for(env, node)
        assert err is None and cand is not None
        assert cand.reschedulable_pods == []

    def test_terminating_sts_pod_survives_state_rebuild(self):
        # review finding: a pod FIRST OBSERVED mid-termination (informer
        # replay after a restart / leader takeover) must still record its
        # binding and usage, or the node reads empty and gets consolidated
        env = make_env()
        node = self._terminating_pod(env, "StatefulSet")
        # a fresh Environment attaches to the same store — new leader warming
        # its caches from current content, pod already terminating
        takeover = Environment(options=Options(), store=env.store)
        sn = takeover.cluster.node_for_name(node.metadata.name)
        assert sn is not None and "default/owned" in sn.pod_requests
        cand, err = self._candidate_for_env(takeover, env, node)
        assert err is None and cand is not None
        assert [p.metadata.name for p in cand.reschedulable_pods] == ["owned"]

    def _candidate_for_env(self, takeover, orig_env, node):
        from karpenter_tpu.controllers.disruption.types import build_candidate
        from karpenter_tpu.utils.pdb import PDBLimits

        sn = takeover.cluster.node_for_name(node.metadata.name)
        pools = {np.metadata.name: np for np in takeover.store.list("NodePool")}
        its = {name: orig_env.cloud_provider.get_instance_types(np) for name, np in pools.items()}
        return build_candidate(
            takeover.cluster, takeover.store, takeover.clock, sn, pools, its, PDBLimits(takeover.store)
        )


class TestSavingsRatio:
    """balanced_scoring_test.go:422-439 Candidate.SavingsRatio + the
    multi-node candidate ordering it drives (consolidation.go:140-154
    sortCandidates: highest savings per unit disruption first)."""

    def _candidate(self, price, n_pods):
        from karpenter_tpu.controllers.disruption.types import Candidate

        pods = [make_pod(name=f"p{i}", cpu="100m") for i in range(n_pods)]
        return Candidate(
            state_node=None, node_claim=None, node_pool=None, instance_type=None,
            capacity_type="on-demand", zone="test-zone-a", price=price,
            reschedulable_pods=pods, disruption_cost=1.0,
            reschedule_disruption_cost=1.0 + float(n_pods),
        )

    def test_ratio_no_pods(self):
        # ratio = price / 1.0 (per-node base only)
        assert abs(self._candidate(4.84, 0).savings_ratio() - 4.84) < 0.01

    def test_ratio_with_pods(self):
        # 1.0 base + 3 × 1.0 eviction cost → 4.84 / 4.0
        assert abs(self._candidate(4.84, 3).savings_ratio() - 1.21) < 0.01

    def test_ratio_zero_price(self):
        # unknown instance type → price 0 → ratio 0
        assert self._candidate(0.0, 3).savings_ratio() == 0.0

    def test_multinode_orders_by_ratio_not_cost(self):
        # an expensive many-pod node (high absolute disruption cost, higher
        # RATIO) must sort before a cheap low-cost node — the old
        # cost-ascending order would invert this; exercises the PRODUCTION
        # MultiNodeConsolidation.sort_candidates
        from types import SimpleNamespace

        from karpenter_tpu.controllers.disruption.methods import MultiNodeConsolidation

        rich = self._candidate(10.0, 1)   # ratio 5.0, higher disruption cost
        rich.disruption_cost = 5.0
        poor = self._candidate(1.0, 0)    # ratio 1.0, lower disruption cost
        poor.disruption_cost = 0.5
        m = MultiNodeConsolidation.__new__(MultiNodeConsolidation)
        m.ctx = SimpleNamespace()
        ordered = m.sort_candidates([poor, rich])
        assert ordered[0] is rich


class TestParallelization:
    """consolidation_test.go:4659-4705 'Parallelization': demand arriving
    while a consolidation command is in flight reuses the in-flight
    replacement capacity instead of launching extra nodes."""

    def test_pending_pod_during_consolidation_adds_no_extra_node(self):
        env = make_env(np_kwargs={"requirements": OD_ONLY})
        provision(env, [make_pod(cpu="14", name="big"), make_pod(cpu="500m", name="small")])
        env.store.delete("Pod", "big")
        # replacement launches but never registers — the command stays in
        # flight and the old node stays up (replacement-first ordering)
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 10**9
        env.store.update(nodeclass)
        for _ in range(6):
            env.clock.step(15)
            env.tick(provision_force=True)
        claims_mid = env.store.count("NodeClaim")
        assert claims_mid == 2  # old node + exactly one in-flight replacement
        assert env.store.count("Node") == 1  # old node still serving
        # new demand arrives mid-command: it must fit existing/in-flight
        # capacity, not grow the fleet beyond the replacement
        env.store.create(make_pod(cpu="500m", name="late"))
        for _ in range(4):
            env.clock.step(5)
            env.tick(provision_force=True)
        assert env.store.count("NodeClaim") <= max(claims_mid, 2)
        # un-wedge registration: claims already launched keep their huge
        # delay, so ride past the liveness TTL — they get killed and
        # replaced by claims that register immediately, then all pods run
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 0.0
        env.store.update(nodeclass)
        run_disruption(env, rounds=12, step=120.0)
        assert all(p.spec.node_name for p in env.store.list("Pod"))
