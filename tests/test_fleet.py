"""Fleet front-end (karpenter_tpu/serving/fleet.py): push-driven wake +
multi-tenant solver multiplexing with shared jitted kernels.

Pins the subsystem's contracts:
- push wake: a store watch event marks the tenant runnable and wakes the
  fleet loop — no idle-window poll on the arrival path (the batcher's
  `eta()` makes the window a coalescing bound, not a latency floor);
- push-vs-poll parity: identical event streams through the fleet's DRR pump
  and the legacy per-tenant serving loop produce bit-identical placements;
- coalescing through the fleet: N mid-solve triggers still fold into ONE
  batched follow-up solve;
- shared kernels: tenant B's first solve after tenant A warmed the fleet
  records ZERO new compiles (RecompileSentinel pin) — shapes/marks are
  fleet-scoped, tensors are not (isolation audit);
- fairness: deficit round-robin caps a bursty tenant's consecutive solves
  so it cannot starve the rest;
- record/replay: a recorded JSONL event stream replays deterministically
  (ChurnSpec.from_event_log), including into fleet tenants;
- racecheck: the threaded fleet loop under the runtime sanitizer records
  zero violations.
"""

from __future__ import annotations

import time

import pytest

from helpers import make_pod
from test_churn_loop import placement_shape, small_spec
from karpenter_tpu import metrics as m
from karpenter_tpu.obs import racecheck
from karpenter_tpu.obs.trace import sentinel
from karpenter_tpu.operator.options import Options
from karpenter_tpu.serving import ChurnHarness, ChurnSpec
from karpenter_tpu.serving.fleet import (
    TENANT_LABEL_CAP,
    FleetFrontend,
    reset_tenant_labels,
    tenant_label,
)


@pytest.fixture(autouse=True)
def _fresh_labels():
    reset_tenant_labels()
    yield
    reset_tenant_labels()


def tenant_options(spec: ChurnSpec) -> Options:
    return Options(
        solver_backend="tpu",
        batch_idle_duration=spec.batch_idle_seconds,
        batch_max_duration=10.0,
    )


def add_churn_tenant(fleet: FleetFrontend, tenant_id: str, spec: ChurnSpec) -> ChurnHarness:
    """A fleet tenant wired exactly like ChurnHarness.build()'s private
    stack (same catalog scale, same batch windows), attached to a harness
    that solves through the fleet pump."""
    from karpenter_tpu.cloudprovider.fake import instance_types_assorted

    sess = fleet.add_tenant(
        tenant_id,
        options=tenant_options(spec),
        instance_types=instance_types_assorted(spec.n_types),
        double_buffer=spec.double_buffer,
        worker=spec.worker,
    )
    return ChurnHarness(spec).attach(sess, fleet=fleet)


class TestTenantLabel:
    def test_cap_and_overflow(self):
        for i in range(TENANT_LABEL_CAP):
            assert tenant_label(f"cluster-{i}") == f"cluster-{i}"
        assert tenant_label("one-more") == "overflow"
        # established assignments keep their label
        assert tenant_label("cluster-0") == "cluster-0"

    def test_sanitization(self):
        assert tenant_label("team a/prod cluster!") == "team-a-prod-cluster-"
        assert tenant_label("") == "default"

    def test_sanitize_collisions_never_merge_tenants(self):
        # two DISTINCT ids with the same sanitized form must not share a
        # metric label (their series would silently merge)
        a = tenant_label("team/a")
        b = tenant_label("team:a")
        assert a != b
        # and the assignment is sticky per original id
        assert tenant_label("team/a") == a and tenant_label("team:a") == b


class TestBatcherEta:
    def test_eta_tracks_the_window(self):
        from karpenter_tpu.controllers.provisioning.batcher import Batcher
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        b = Batcher(clock, idle_seconds=1.0, max_seconds=10.0)
        assert b.eta() is None
        b.trigger("a")
        assert b.eta() == pytest.approx(1.0)
        clock.step(0.4)
        assert b.eta() == pytest.approx(0.6)
        clock.step(1.0)
        assert b.eta() == 0.0 and b.ready()
        # a coalesced drain is ready NOW
        b.reset()
        b.begin_solve()
        b.trigger("during")
        b.end_solve()
        assert b.eta() == 0.0

    def test_wake_hook_fires_on_trigger(self):
        from karpenter_tpu.controllers.provisioning.batcher import Batcher
        from karpenter_tpu.utils.clock import FakeClock

        b = Batcher(FakeClock(), idle_seconds=1.0, max_seconds=10.0)
        hits = []
        b.wake_hook = lambda: hits.append(1)
        b.trigger("x")
        b.trigger("y")
        assert hits == [1, 1]


class TestPushWake:
    def test_watch_event_marks_runnable_and_wakes(self):
        spec = small_spec(n_base_pods=0)
        fleet = FleetFrontend()
        try:
            h = add_churn_tenant(fleet, "t0", spec)
            assert fleet.runnable_tenants() == []
            h.apply_arrivals(1)
            # the pod create's watch delivery marked the tenant runnable —
            # push-style, with no pump/poll having run
            assert fleet.runnable_tenants() == ["t0"]
            assert fleet._wake.is_set()
            # wake attribution: the batcher trigger hook fires first on the
            # create's watch delivery, so the episode is attributed to the
            # bounded "batcher-window" cause (the ?cause= split of ISSUE 14)
            assert fleet.registry.counter(m.SOLVER_FLEET_WAKE_TOTAL).value(tenant="t0", cause="batcher-window") == 1
            assert fleet.registry.gauge(m.SOLVER_FLEET_RUNNABLE_TENANTS).value() == 1
            sess = fleet.session("t0")
            assert sess.wake_count() >= 1
        finally:
            fleet.close()

    def test_pump_serves_then_retires(self):
        spec = small_spec(n_base_pods=0)
        fleet = FleetFrontend()
        try:
            h = add_churn_tenant(fleet, "t0", spec)
            h.apply_arrivals(3)
            # window not closed: a pump round leaves the batch coalescing
            assert fleet.pump() == {}
            h.env.clock.step(spec.batch_idle_seconds + 0.05)
            fleet.rearm_ready()
            served = fleet.pump()
            assert served.get("t0", 0) >= 1
            assert fleet.runnable_tenants() == []
            # wake-to-solve wait was observed for the tenant
            assert fleet.registry.histogram(m.SOLVER_FLEET_SCHED_WAIT_SECONDS).count(tenant="t0") >= 1
        finally:
            fleet.close()

    def test_next_eta_surfaces_nearest_window(self):
        spec = small_spec(n_base_pods=0)
        fleet = FleetFrontend()
        try:
            h = add_churn_tenant(fleet, "t0", spec)
            assert fleet.next_eta() is None
            h.apply_arrivals(1)
            eta = fleet.next_eta()
            assert eta is not None and 0 < eta <= spec.batch_idle_seconds + 1e-6
        finally:
            fleet.close()


class TestCoalescingThroughFleet:
    def test_midsolve_burst_folds_into_one_followup(self):
        spec = small_spec(n_base_pods=0)
        fleet = FleetFrontend()
        try:
            h = add_churn_tenant(fleet, "t0", spec)
            env = h.env
            prov = env.provisioner
            solver = prov.solver
            seen: list[int] = []
            injected = {"done": False}
            orig_solve = solver.solve

            def spying_solve(snap):
                seen.append(len(snap.pods))
                if not injected["done"]:
                    injected["done"] = True
                    h.apply_arrivals(7)  # mid-solve burst
                return orig_solve(snap)

            solver.solve = spying_solve
            h.apply_arrivals(3)
            env.clock.step(1.0)
            fleet.rearm_ready()
            served = fleet.pump()
            # the fleet round ran the first solve AND the one coalesced
            # follow-up (the drain armed ready() again mid-round)
            assert served["t0"] == 2
            assert seen == [3, 10]
            assert env.registry.counter(m.SOLVER_CHURN_COALESCED_TOTAL).value(tenant="t0") == 7
        finally:
            fleet.close()


class TestPushPollParity:
    def test_fleet_pump_bit_identical_to_poll_loop(self, monkeypatch):
        """The same scripted churn through (a) the legacy per-tenant serving
        loop and (b) the fleet's push-wake DRR pump must place bit-
        identically: the fleet changes WHEN solves run, never the result."""
        monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
        shapes = []
        for arm in ("poll", "push"):
            spec = small_spec()
            if arm == "poll":
                h = ChurnHarness(spec).build()
                fleet = None
            else:
                fleet = FleetFrontend()
                h = add_churn_tenant(fleet, "solo", spec)
            try:
                h.provision_base_fleet()
                h.apply_departures(40)
                h.bind_flush()
                for _ in range(3):
                    h.run_cycle()
                shapes.append(placement_shape(h.env))
            finally:
                h.close() if fleet is None else fleet.close()
        assert shapes[0] == shapes[1]


class TestSharedKernels:
    def test_tenant_b_first_solves_record_zero_compiles(self, monkeypatch):
        """The fleet warm-start pin: after tenant A establishes the shape
        ladder (provisioning + churn cycles), tenant B's ENTIRE lifecycle —
        cold provisioning through steady churn — records zero new compiles
        on the sentinel watchlist."""
        from karpenter_tpu.models.scheduler_model import reset_bucket_highwater

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "1")
        reset_bucket_highwater()
        fleet = FleetFrontend()
        try:
            spec = small_spec()
            ha = add_churn_tenant(fleet, "a", spec)
            ha.provision_base_fleet()
            ha.apply_departures(40)
            ha.bind_flush()
            ha.run_cycle()
            ha.run_cycle()
            mark = sentinel().snapshot()
            hb = add_churn_tenant(fleet, "b", small_spec())
            hb.provision_base_fleet()
            hb.apply_departures(40)
            hb.bind_flush()
            hb.run_cycle()
            delta = sentinel().delta(mark)
            assert delta == {}, f"tenant b paid compiles after a warmed the fleet: {delta}"
            # and tenant b actually solved (on its own tensors)
            assert len(hb.env.cluster.nodes()) > 0
        finally:
            fleet.close()
            reset_bucket_highwater()

    def test_isolation_audit(self, monkeypatch):
        from karpenter_tpu.models.scheduler_model import reset_bucket_highwater

        monkeypatch.setenv("KARPENTER_SOLVER_BUCKET", "1")
        reset_bucket_highwater()
        fleet = FleetFrontend()
        try:
            specs = small_spec(n_base_pods=40)
            ha = add_churn_tenant(fleet, "a", specs)
            hb = add_churn_tenant(fleet, "b", small_spec(n_base_pods=40))
            ha.provision_base_fleet()
            hb.provision_base_fleet()
            audit = fleet.isolation_audit()
            # shapes/marks shared; tensors keyed per cluster epoch
            assert audit["shared_shapes"], "high-water marks empty after two provisioned tenants"
            assert len(audit["tenant_epochs"]) == 2
            assert len(set(audit["tenant_epochs"].values())) == 2
        finally:
            fleet.close()
            reset_bucket_highwater()

    def test_per_tenant_metrics_split(self):
        fleet = FleetFrontend()
        try:
            spec = small_spec(n_base_pods=40)
            ha = add_churn_tenant(fleet, "a", spec)
            hb = add_churn_tenant(fleet, "b", small_spec(n_base_pods=40))
            ha.provision_base_fleet()
            hb.provision_base_fleet()
            c = fleet.registry.counter(m.SOLVER_SOLVE_TOTAL)
            assert c.value(backend="tpu", tenant="a") > 0
            assert c.value(backend="tpu", tenant="b") > 0
            ev = fleet.registry.counter(m.SOLVER_CHURN_EVENTS_TOTAL)
            assert ev.value(event="arrival", tenant="a") > 0
            assert ev.value(event="arrival", tenant="b") > 0
            # per-tenant latency quantiles come from per-session recorders
            assert fleet.session("a").recorder is not fleet.session("b").recorder
            stats = fleet.stats()
            assert stats["a"]["solves"] > 0 and stats["b"]["solves"] > 0
        finally:
            fleet.close()


class TestFairness:
    def test_bursty_tenant_cannot_starve_the_rest(self):
        """Tenant A re-arms its batcher after every solve (a continuous
        backlog); tenant B has one small batch. One DRR round must serve B
        and cap A at backlog_solve_cap solves."""
        fleet = FleetFrontend(backlog_solve_cap=3.0)
        try:
            ha = add_churn_tenant(fleet, "bursty", small_spec(n_base_pods=0))
            hb = add_churn_tenant(fleet, "small", small_spec(n_base_pods=0))
            prov_a = ha.env.provisioner
            orig = prov_a.solver.solve

            def refeeding_solve(snap):
                # a new arrival lands during EVERY solve of A: the coalesced
                # drain re-arms ready() immediately after each solve
                ha.apply_arrivals(1)
                return orig(snap)

            prov_a.solver.solve = refeeding_solve
            ha.apply_arrivals(5)
            hb.apply_arrivals(5)
            ha.env.clock.step(1.0)
            hb.env.clock.step(1.0)
            fleet.rearm_ready()
            served = fleet.pump()
            assert served["small"] >= 1, "bursty tenant starved the small one"
            assert served["bursty"] <= 3, f"DRR cap violated: {served}"
        finally:
            fleet.close()


class TestRecordReplay:
    def test_record_then_replay_bit_identical(self, tmp_path, monkeypatch):
        """A recorded run replays deterministically: same placements, and
        the replay's steady window reports through the same machinery."""
        monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
        log = str(tmp_path / "churn.jsonl")
        spec = small_spec(iterations=2, warmup_cycles=1, record_path=log)
        h = ChurnHarness(spec)
        rep = h.run()
        shape_recorded = placement_shape(h.env)
        h.close()
        assert rep.events > 0

        rspec = ChurnSpec.from_event_log(log)
        assert rspec.replay_events, "log loaded empty"
        assert rspec.n_base_pods == spec.n_base_pods  # header round-trips
        h2 = ChurnHarness(rspec)
        rep2 = h2.run()
        shape_replayed = placement_shape(h2.env)
        h2.close()
        assert shape_replayed == shape_recorded
        assert rep2.events == rep.events
        assert rep2.solves == rep.solves

    def test_replay_into_fleet_tenants(self, tmp_path, monkeypatch):
        """One recorded log drives K fleet tenants (sequentially, RNG
        re-seeded per tenant): each tenant reproduces the recorded
        placements bit-for-bit — the multi-tenant bench's replay mode."""
        monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
        log = str(tmp_path / "churn.jsonl")
        spec = small_spec(iterations=2, warmup_cycles=1, record_path=log)
        h = ChurnHarness(spec)
        h.run()
        shape_recorded = placement_shape(h.env)
        h.close()

        fleet = FleetFrontend()
        try:
            for tid in ("r0", "r1"):
                rspec = ChurnSpec.from_event_log(log)
                from karpenter_tpu.cloudprovider.fake import instance_types_assorted

                sess = fleet.add_tenant(
                    tid,
                    options=tenant_options(rspec),
                    instance_types=instance_types_assorted(rspec.n_types),
                )
                ht = ChurnHarness(rspec).attach(sess, fleet=fleet)
                ht.run()
                assert placement_shape(ht.env) == shape_recorded, tid
        finally:
            fleet.close()


class TestThreadedFleetRacecheck:
    def test_serve_loop_under_sanitizer_is_clean(self):
        """The wall-clock fleet loop threaded against a concurrent event
        driver: solves happen, and the runtime sanitizer (on for the whole
        suite) records zero violations."""
        from karpenter_tpu.utils.clock import Clock

        racecheck.reset()
        spec = small_spec(n_base_pods=0, batch_idle_seconds=0.05)
        fleet = FleetFrontend(poll_floor_seconds=0.05)
        try:
            sess = fleet.add_tenant(
                "live",
                options=tenant_options(spec),
                clock=Clock(),
            )
            h = ChurnHarness(spec).attach(sess)
            fleet.start()
            assert fleet.serving()
            for _ in range(10):
                h.apply_arrivals(5)
                time.sleep(0.03)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not sess.recorder.traces():
                time.sleep(0.05)
            fleet.stop()
            assert not fleet.serving()
            assert sess.recorder.traces(), "fleet loop never solved"
            snap = racecheck.snapshot()
            assert snap["violations"] == [], snap["violations"]
            assert sess.wake_count() > 0
        finally:
            fleet.close()
            racecheck.reset()
