"""Chaos specs (reference: test/suites/regression/chaos_test.go + the fault
taxonomy in SURVEY.md §5) — the control plane must converge, never runaway,
under: random node kills, taint tug-of-war, cloud-provider error storms
(scripted NextCreateErr/NextDeleteErr analogue on the KWOK provider),
partial-registration storms racing the liveness TTL, and leader failover
that abandons an in-flight disruption command."""

import random

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.kwoknodeclass import KWOKNodeClass
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.errors import (
    CreateError,
    InsufficientCapacityError,
    NodeClassNotReadyError,
)
from karpenter_tpu.cloudprovider.kwok import KWOKCloudProvider
from karpenter_tpu.controllers.nodeclaim.lifecycle import REGISTRATION_TTL_SECONDS
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.testing import Monitor

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


class FlakyProvider:
    """Scripted-error decorator over the KWOK provider — the e2e analogue of
    the fake provider's NextCreateErr/NextDeleteErr hooks
    (fake/cloudprovider.go:60-63), driven by rates so storms span rounds."""

    def __init__(self, inner, rng):
        self._inner = inner
        self._rng = rng
        self.create_error_rate = 0.0
        self.delete_error_rate = 0.0
        self.create_error_factory = lambda: InsufficientCapacityError("chaos: capacity storm")
        self.create_errors = 0
        self.delete_errors = 0

    def create(self, node_claim):
        if self._rng.random() < self.create_error_rate:
            self.create_errors += 1
            raise self.create_error_factory()
        return self._inner.create(node_claim)

    def delete(self, node_claim):
        if self._rng.random() < self.delete_error_rate:
            self.delete_errors += 1
            raise RuntimeError("chaos: cloud API 500")
        return self._inner.delete(node_claim)

    def __getattr__(self, item):
        return getattr(self._inner, item)


def make_env():
    env = Environment(options=Options())
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env, Monitor(env.store, env.cluster)


def make_flaky_env(seed: int = 0):
    """Environment whose cloud provider injects scripted errors."""
    from karpenter_tpu.kube import Store
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = Store(clock=clock)
    store.create(KWOKNodeClass())
    flaky = FlakyProvider(KWOKCloudProvider(store, catalog.construct_instance_types(), clock=clock), random.Random(seed))
    env = Environment(options=Options(), clock=clock, cloud_provider=flaky, store=store)
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env, flaky, Monitor(env.store, env.cluster)


class TestChaos:
    def test_random_node_kills_converge(self):
        """Kill random nodes repeatedly; pods must always end up running and
        the fleet must not grow without bound (chaos_test.go ExpectNoCrashes)."""
        rng = random.Random(42)
        env, monitor = make_env()
        for i in range(60):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"p-{i}", labels={"app": "chaos"}))
        env.settle()
        assert monitor.pending_pod_count() == 0
        max_nodes = 0
        for round_ in range(8):
            nodes = env.store.list("Node")
            if nodes:
                victim = rng.choice(nodes)
                env.store.delete("Node", victim.metadata.name, grace=False)
                env.cluster.delete_node(victim.metadata.name)
            for _ in range(6):
                env.clock.step(5.0)
                env.tick(provision_force=True)
            max_nodes = max(max_nodes, env.store.count("Node"))
        env.settle(rounds=20)
        assert monitor.pending_pod_count() == 0, "pods left stranded after chaos"
        assert monitor.running_pod_count() == 60
        # runaway guard: fleet never ballooned past a small multiple of needs
        assert max_nodes <= 3 * env.store.count("Node") + 3, max_nodes

    def test_tainted_nodes_replaced_not_multiplied(self):
        """A user tainting a node NoSchedule must not trigger unbounded
        scale-up (chaos_test.go taint scenario)."""
        env, monitor = make_env()
        for i in range(20):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        node = env.store.list("Node")[0]

        def taint(n):
            from karpenter_tpu.scheduling.taints import Taint

            n.spec.taints.append(Taint(key="chaos", value="true", effect="NoSchedule"))

        env.store.patch("Node", node.metadata.name, taint)
        before = env.store.count("Node")
        env.settle(rounds=15)
        # running pods stay; fleet grows by at most a couple nodes for any
        # evicted pods, never runs away
        assert env.store.count("Node") <= before + 2
        assert monitor.pending_pod_count() == 0


class TestProviderErrorStorms:
    def test_create_error_storm_converges(self):
        """InsufficientCapacity on ~60% of launches for a while: failed
        claims delete and re-provision (launch.go terminal-error path); once
        the storm passes every pod runs and the fleet is right-sized."""
        env, flaky, monitor = make_flaky_env(seed=7)
        for i in range(40):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"p-{i}"))
        flaky.create_error_rate = 0.6
        env.settle(rounds=12)
        assert flaky.create_errors > 0, "storm never fired"
        flaky.create_error_rate = 0.0
        env.settle(rounds=20)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 40
        # no claim leak: every claim is backed by a registered node
        assert env.store.count("NodeClaim") == env.store.count("Node")

    def test_transient_create_error_retries_same_claim(self):
        """A RETRYABLE CreateError (cloud API 500) must not delete the claim
        (unlike InsufficientCapacity's terminal path, launch.go): per-item
        reconcile isolation retries it next round. Reference-faithful
        convergence: re-provisioning rounds may add claims while the first
        sits unlaunched (its pre-launch StateNode has no capacity,
        statenode.go:359-397 — same in the reference), and the extras are
        reclaimed by emptiness once everything launches."""
        env, flaky, monitor = make_flaky_env(seed=3)
        flaky.create_error_factory = lambda: CreateError("chaos: cloud API 500")
        env.store.create(make_pod(cpu="1", name="p-0"))
        flaky.create_error_rate = 1.0
        env.settle(rounds=3)
        mid_storm = {c.metadata.name for c in env.store.list("NodeClaim")}
        assert mid_storm, "claims must survive transient launch errors"
        env.settle(rounds=2)
        late_storm = {c.metadata.name for c in env.store.list("NodeClaim")}
        # the retryable error path never DELETES a claim (unlike the
        # InsufficientCapacity terminal path): the set only grows
        assert mid_storm <= late_storm
        assert env.store.count("Node") == 0
        flaky.create_error_rate = 0.0
        # one recovery tick: every storm-era claim must STILL exist (a
        # delete-and-recreate regression would replace them) and launch now
        env.clock.step(2.0)
        env.tick(provision_force=True)
        post_recovery = {c.metadata.name for c in env.store.list("NodeClaim")}
        assert late_storm <= post_recovery, "recovery must reuse retried claims, not recreate"
        env.settle(rounds=10)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 1
        # extra claims from the storm window consolidate away as empty
        env.settle(rounds=20, step_seconds=30.0)
        assert env.store.count("NodeClaim") == env.store.count("Node") == 1

    def test_nodeclass_not_ready_flapping(self):
        """NodeClassNotReady bursts: claims hold (Launched=False) and retry;
        convergence once the class recovers (launch.go NodeClassNotReady)."""
        env, flaky, monitor = make_flaky_env(seed=11)
        flaky.create_error_factory = lambda: NodeClassNotReadyError("chaos: class flapping")
        for i in range(10):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        flaky.create_error_rate = 1.0
        env.settle(rounds=6)
        assert env.store.count("NodeClaim") >= 1
        assert env.store.count("Node") == 0, "nothing may launch while NotReady"
        flaky.create_error_rate = 0.0
        env.settle(rounds=15)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 10

    def test_delete_error_storm_during_drain(self):
        """Cloud deletes fail with untyped 500s while nodes drain: the
        termination finalizer must retry each round (per-item isolation) and
        release only when the cloud delete finally lands."""
        env, flaky, monitor = make_flaky_env(seed=23)
        for i in range(12):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        assert monitor.pending_pod_count() == 0
        flaky.delete_error_rate = 1.0
        victims = [n.metadata.name for n in env.store.list("Node")[:2]]
        for name in victims:
            env.store.delete("Node", name)  # graceful: finalizer drain path
        env.settle(rounds=8)
        assert flaky.delete_errors > 0, "storm never fired"
        # finalizers held: the nodes must still exist while deletes fail
        still = [n.metadata.name for n in env.store.list("Node")]
        assert all(v in still for v in victims)
        flaky.delete_error_rate = 0.0
        env.settle(rounds=25)
        assert all(env.store.try_get("Node", v) is None for v in victims)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 12

    def test_error_storm_under_pod_churn(self):
        """Pods appear and vanish WHILE creates are flaky — the batcher,
        provisioner, and lifecycle must never wedge; after the storm the
        fleet serves exactly the surviving pods."""
        rng = random.Random(5)
        env, flaky, monitor = make_flaky_env(seed=5)
        flaky.create_error_rate = 0.5
        live = []
        seq = 0
        for round_ in range(10):
            for _ in range(rng.randrange(1, 5)):
                env.store.create(make_pod(cpu="500m", name=f"churn-{seq}"))
                live.append(f"churn-{seq}")
                seq += 1
            if live and rng.random() < 0.5:
                gone = live.pop(rng.randrange(len(live)))
                env.store.try_delete("Pod", gone)
            env.clock.step(3.0)
            env.tick(provision_force=True)
        flaky.create_error_rate = 0.0
        env.settle(rounds=25)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == len(live)


class TestRegistrationStorms:
    def test_partial_registration_storm_liveness_recovers(self):
        """Nodes stuck past the liveness TTL: the claims are killed
        (liveness.go:62), their orphaned late-arriving instances are GC'd,
        and re-provisioned claims converge once registration heals."""
        env, flaky, monitor = make_flaky_env(seed=31)

        def slow(nc):
            nc.spec.node_registration_delay = REGISTRATION_TTL_SECONDS + 300

        env.store.patch("KWOKNodeClass", "default", slow)
        for i in range(6):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle(rounds=3)
        first_claims = {c.metadata.name for c in env.store.list("NodeClaim")}
        assert first_claims
        # cross the TTL: liveness must kill every unregistered claim
        for _ in range(4):
            env.clock.step(REGISTRATION_TTL_SECONDS / 3)
            env.tick(provision_force=True)
        surviving = {c.metadata.name for c in env.store.list("NodeClaim")}
        assert not (first_claims & surviving), "unregistered claims must die by TTL"

        # registration heals; replacements converge
        def fast(nc):
            nc.spec.node_registration_delay = 0.0

        env.store.patch("KWOKNodeClass", "default", fast)
        env.settle(rounds=25)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 6
        # the storm's late-arriving orphan instances must not linger past GC
        env.settle(rounds=10, step_seconds=60.0)
        assert env.store.count("Node") == env.store.count("NodeClaim")

    def test_registration_delay_below_ttl_no_churn(self):
        """Slow-but-legal registration (delay < TTL) must NOT trigger the
        liveness killer — the original claims survive to serve the pods."""
        env, flaky, monitor = make_flaky_env(seed=37)

        def slow(nc):
            nc.spec.node_registration_delay = REGISTRATION_TTL_SECONDS / 3

        env.store.patch("KWOKNodeClass", "default", slow)
        for i in range(6):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle(rounds=3)
        first_claims = {c.metadata.name for c in env.store.list("NodeClaim")}
        for _ in range(6):
            env.clock.step(REGISTRATION_TTL_SECONDS / 6)
            env.tick(provision_force=True)
        env.settle(rounds=10)
        assert monitor.pending_pod_count() == 0
        surviving = {c.metadata.name for c in env.store.list("NodeClaim")}
        assert first_claims <= surviving, "no claim may be killed below the TTL"


class TestLeaderFailover:
    def _manufacture_inflight_command(self, env):
        """Leave the store looking like a leader crashed mid-command
        (queue.go:313: taint applied, claim marked Disrupted, candidates not
        yet deleted): the recovery contract is controller.go:147-164."""
        from karpenter_tpu.scheduling.taints import Taint

        node = env.store.list("Node")[0]

        def taint(n):
            n.spec.taints.append(Taint(key=wk.DISRUPTED_TAINT_KEY, effect="NoSchedule"))

        env.store.patch("Node", node.metadata.name, taint)
        return node.metadata.name

    def test_takeover_cleans_leftover_disruption_taints(self):
        """A new leader must un-taint candidates of the dead leader's
        abandoned command so they serve pods again (controller.go:147-164)."""
        env, monitor = make_env()
        for i in range(12):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        tainted = self._manufacture_inflight_command(env)
        # the dead leader never ticks again; a standby takes over the store
        env2 = Environment(options=Options(), clock=env.clock, store=env.store)
        m2 = Monitor(env2.store, env2.cluster)
        env2.settle(rounds=15)
        node = env2.store.try_get("Node", tainted)
        assert node is not None
        assert not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints), (
            "leftover disruption taint must be cleaned on takeover"
        )
        assert m2.pending_pod_count() == 0
        assert m2.running_pod_count() == 12

    def test_takeover_converges_orphan_replacement(self):
        """The dead leader had already created a replacement NodeClaim whose
        command died with it: the new leader must converge — the orphan
        either initializes and is consolidated away as empty, or is removed —
        with every pod running and the fleet bounded."""
        env, monitor = make_env()
        for i in range(8):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        nodes_before = env.store.count("Node")
        self._manufacture_inflight_command(env)
        # orphan replacement: a spare claim the dead leader launched
        from karpenter_tpu.apis.nodeclaim import NodeClaim, NodeClassReference as NodeClassRef

        pool = env.store.list("NodePool")[0]
        orphan = NodeClaim()
        orphan.metadata.name = "orphan-replacement"
        orphan.metadata.labels[wk.NODEPOOL_LABEL_KEY] = pool.metadata.name
        orphan.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] = wk.CAPACITY_TYPE_ON_DEMAND
        orphan.spec.node_class_ref = NodeClassRef(kind="KWOKNodeClass", name="default")
        orphan.spec.requirements = [
            {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": [catalog.construct_instance_types()[0].name]},
            {"key": wk.NODEPOOL_LABEL_KEY, "operator": "In", "values": [pool.metadata.name]},
        ]
        env.store.create(orphan)
        env2 = Environment(options=Options(), clock=env.clock, store=env.store)
        m2 = Monitor(env2.store, env2.cluster)
        env2.settle(rounds=10)
        env2.settle(rounds=15, step_seconds=30.0)  # let emptiness engage
        assert m2.pending_pod_count() == 0
        assert m2.running_pod_count() == 8
        # converged fleet: bounded by the pre-crash fleet plus at most the
        # orphan (if it initialized and emptiness hasn't collected it yet,
        # the disrupted-taint cleanup keeps it schedulable, not leaked)
        assert env2.store.count("Node") <= nodes_before + 1
        # nothing is left carrying the dead command's taint
        for n in env2.store.list("Node"):
            assert not any(t.key == wk.DISRUPTED_TAINT_KEY for t in n.spec.taints)

    def test_mass_kill_with_create_errors(self):
        """Half the fleet dies WHILE the cloud is throwing capacity errors:
        the worst compound storm must still converge once capacity returns."""
        env, flaky, monitor = make_flaky_env(seed=13)
        for i in range(24):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        assert monitor.pending_pod_count() == 0
        flaky.create_error_rate = 0.7
        nodes = env.store.list("Node")
        for victim in nodes[: max(1, len(nodes) // 2)]:
            env.store.delete("Node", victim.metadata.name, grace=False)
            env.cluster.delete_node(victim.metadata.name)
        env.settle(rounds=10)
        flaky.create_error_rate = 0.0
        env.settle(rounds=25)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 24
        assert env.store.count("NodeClaim") == env.store.count("Node")
