"""Chaos specs (reference: test/suites/regression/chaos_test.go) — the
control plane must converge, not runaway, under random node kills and a
taint/consolidation tug-of-war."""

import random

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.testing import Monitor

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env():
    env = Environment(options=Options())
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env, Monitor(env.store, env.cluster)


class TestChaos:
    def test_random_node_kills_converge(self):
        """Kill random nodes repeatedly; pods must always end up running and
        the fleet must not grow without bound (chaos_test.go ExpectNoCrashes)."""
        rng = random.Random(42)
        env, monitor = make_env()
        for i in range(60):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"p-{i}", labels={"app": "chaos"}))
        env.settle()
        assert monitor.pending_pod_count() == 0
        max_nodes = 0
        for round_ in range(8):
            nodes = env.store.list("Node")
            if nodes:
                victim = rng.choice(nodes)
                env.store.delete("Node", victim.metadata.name, grace=False)
                env.cluster.delete_node(victim.metadata.name)
            for _ in range(6):
                env.clock.step(5.0)
                env.tick(provision_force=True)
            max_nodes = max(max_nodes, env.store.count("Node"))
        env.settle(rounds=20)
        assert monitor.pending_pod_count() == 0, "pods left stranded after chaos"
        assert monitor.running_pod_count() == 60
        # runaway guard: fleet never ballooned past a small multiple of needs
        assert max_nodes <= 3 * env.store.count("Node") + 3, max_nodes

    def test_tainted_nodes_replaced_not_multiplied(self):
        """A user tainting a node NoSchedule must not trigger unbounded
        scale-up (chaos_test.go taint scenario)."""
        env, monitor = make_env()
        for i in range(20):
            env.store.create(make_pod(cpu="1", name=f"p-{i}"))
        env.settle()
        node = env.store.list("Node")[0]

        def taint(n):
            from karpenter_tpu.scheduling.taints import Taint

            n.spec.taints.append(Taint(key="chaos", value="true", effect="NoSchedule"))

        env.store.patch("Node", node.metadata.name, taint)
        before = env.store.count("Node")
        env.settle(rounds=15)
        # running pods stay; fleet grows by at most a couple nodes for any
        # evicted pods, never runs away
        assert env.store.count("Node") <= before + 2
        assert monitor.pending_pod_count() == 0
