"""E2E observability harness: metrics poller + object-churn watcher.

Reference: test/pkg/environment/common/karpenter_metrics_poller.go and
test/pkg/debug/.
"""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu import metrics as m
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.testing import MetricsPoller, ObjectChurnWatcher, scrape_exposition

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def make_env():
    env = Environment(options=Options())
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env


class TestMetricsPoller:
    def test_resource_stats_over_ticks(self):
        env = make_env()
        poller = MetricsPoller(env.registry)
        for i in range(6):
            env.store.create(make_pod(cpu="500m", name=f"p{i}"))
            env.clock.step(5)
            env.tick(provision_force=True)
            poller.poll()
        stats = poller.stats()
        assert stats.sample_count == 6
        assert stats.max_memory_mb > 0
        assert stats.p95_memory_mb <= stats.max_memory_mb
        assert stats.avg_memory_mb <= stats.max_memory_mb
        assert stats.max_cpu_cores >= stats.avg_cpu_cores >= 0

    def test_metric_series_tracks_registry(self):
        env = make_env()
        poller = MetricsPoller(env.registry, track=(m.SCHEDULER_SCHEDULING_DURATION, m.NODECLAIMS_CREATED_TOTAL))
        poller.poll()  # before any scheduling
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"p{i}"))
        env.clock.step(5)
        env.tick(provision_force=True)
        poller.poll()
        series = poller.series[m.SCHEDULER_SCHEDULING_DURATION]
        assert series[0] == 0 and series[-1] >= 1, series  # solves observed
        created = poller.series[m.NODECLAIMS_CREATED_TOTAL]
        assert created[-1] >= 1

    def test_http_exposition_scrape(self):
        from karpenter_tpu.operator.server import OperatorServer
        import urllib.request

        env = make_env()
        env.store.create(make_pod(cpu="500m", name="w"))
        env.clock.step(5)
        env.tick(provision_force=True)
        server = OperatorServer(env, port=0, bind="127.0.0.1")
        port = server.start()
        try:
            body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        finally:
            server.stop()
        samples = scrape_exposition(body)
        assert any(name == m.CLUSTER_STATE_NODE_COUNT for name, _ in samples)
        count_keys = [k for k in samples if k[0] == f"{m.SCHEDULER_SCHEDULING_DURATION}_count"]
        assert count_keys and samples[count_keys[0]] >= 1


class TestObjectChurnWatcher:
    def test_records_lifecycle_events(self):
        env = make_env()
        watcher = ObjectChurnWatcher(env.store, clock=env.clock)
        env.store.create(make_pod(cpu="500m", name="w0"))
        env.clock.step(5)
        env.tick(provision_force=True)
        counts = watcher.counts()
        assert counts.get(("Pod", "ADDED")) == 1
        assert counts.get(("Node", "ADDED"), 0) >= 1
        assert counts.get(("NodeClaim", "ADDED"), 0) >= 1
        assert counts.get(("Pod", "MODIFIED"), 0) >= 1  # the bind
        dump = watcher.dump()
        assert "w0" in dump and "ADDED" in dump

    def test_dump_is_bounded_and_recent(self):
        env = make_env()
        watcher = ObjectChurnWatcher(env.store, kinds=("Pod",), clock=env.clock, max_events=10)
        for i in range(25):
            env.store.create(make_pod(cpu="100m", name=f"p{i}"))
        assert len(watcher.events) <= 10
        # the retained half is the most recent
        assert any("p24" in e.key for e in watcher.events)

    def test_context_manager_dumps_on_failure(self):
        env = make_env()
        captured = []
        try:
            with ObjectChurnWatcher(env.store, clock=env.clock, sink=captured.append):
                env.store.create(make_pod(cpu="100m", name="doomed"))
                raise AssertionError("spec failed")
        except AssertionError:
            pass
        assert captured and "doomed" in captured[0]

    def test_context_manager_silent_on_success(self):
        env = make_env()
        captured = []
        with ObjectChurnWatcher(env.store, clock=env.clock, sink=captured.append):
            env.store.create(make_pod(cpu="100m", name="fine"))
        assert not captured

    def test_close_unsubscribes(self):
        env = make_env()
        with ObjectChurnWatcher(env.store, kinds=("Pod",), clock=env.clock) as w:
            env.store.create(make_pod(cpu="100m", name="seen"))
        n = len(w.events)
        env.store.create(make_pod(cpu="100m", name="unseen"))
        assert len(w.events) == n, "closed watcher must not receive events"
