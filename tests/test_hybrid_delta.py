"""Hybrid-delta: the steady-state provisioner loop over hybrid snapshots.

PR 1's hybrid partitioned solve encoded every hybrid snapshot twice and
poisoned the EncodeCache delta base with the sub-encode. Now the sub-encode
is a MASK of the full encode (no second encode, cache untouched) and hybrid
is a first-class mode of the delta machinery: a small pod delta of the
previous hybrid snapshot re-packs only the delta against the retained masked
carry (last_solve_mode == "hybrid-delta"), and the full-snapshot delta base
survives a hybrid solve intact (full -> hybrid -> full-plus-one-pod resolves
as "delta").
"""

import pytest

from helpers import make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import (
    Affinity,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.metrics import (
    SOLVER_ENCODE_SECONDS,
    SOLVER_HYBRID_RESIDUAL_TOTAL,
    SOLVER_SOLVE_TOTAL,
    make_registry,
)
from karpenter_tpu.solver import FFDSolver
from karpenter_tpu.solver.tpu import TPUSolver
from test_solver import make_snapshot


def odd_pod(name="odd", cpu="500m"):
    """Pod-local out-of-window: preferred pod affinity."""
    p = make_pod(cpu=cpu, name=name)
    p.spec.affinity = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=1,
                term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
            )
        ]
    )
    return p


def _placed_names(results):
    names = set()
    for nc in results.new_node_claims:
        names.update(p.metadata.name for p in nc.pods)
    for en in results.existing_nodes:
        names.update(p.metadata.name for p in en.pods)
    return names


def _hybrid_snap(n_plain=6):
    pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(n_plain)] + [odd_pod()]
    return make_snapshot(pods)


class TestHybridDelta:
    def test_identical_resubmit_takes_hybrid_delta(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        r1 = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        r2 = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        assert solver.last_backend == "hybrid"
        assert not r2.pod_errors
        assert _placed_names(r1) == _placed_names(r2)

    def test_appended_pod_takes_hybrid_delta(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        solver.solve(snap)  # land the hybrid carry + resubmit path
        newcomer = make_pod(cpu="500m", name="newcomer")
        snap.pods.append(newcomer)
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        assert not r.pod_errors
        assert newcomer.metadata.name in _placed_names(r)
        assert len(_placed_names(r)) == 8

    def test_appended_flagged_pod_grows_residual(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        snap.pods.append(odd_pod(name="odd2"))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        assert not r.pod_errors
        assert {"odd", "odd2"} <= _placed_names(r)

    def test_removed_tensor_pod_recredits(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        gone = snap.pods.pop(0)  # a plain (tensor-side) pod
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        assert not r.pod_errors
        assert gone.metadata.name not in _placed_names(r)
        assert len(_placed_names(r)) == 6

    def test_chained_hybrid_deltas(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        for i in range(3):
            snap.pods.append(make_pod(cpu="500m", name=f"n{i}"))
            r = solver.solve(snap)
            assert solver.last_solve_mode == "hybrid-delta"
            assert not r.pod_errors
        assert len(_placed_names(r)) == 10

    def test_resubmit_after_delta_does_not_replay_stale_delta(self):
        # review regression: full -> append (delta) -> IDENTICAL resubmit
        # used to replay the consumed delta arrays against the merged carry
        # (IndexError in assignment_from_triples) — pure tensor path
        pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(5)]
        snap = make_snapshot(list(pods))
        solver = TPUSolver()
        solver.solve(snap)
        snap.pods.append(make_pod(cpu="500m", name="p5"))
        solver.solve(snap)
        assert solver.last_solve_mode == "delta"
        r = solver.solve(snap)  # identical resubmit
        assert solver.last_solve_mode == "delta"
        assert not r.pod_errors
        assert len(_placed_names(r)) == 6

    def test_resubmit_after_hybrid_delta_does_not_replay_stale_delta(self):
        # same regression through the hybrid path: hybrid -> hybrid-delta
        # (append) -> identical resubmit
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        snap.pods.append(make_pod(cpu="500m", name="pp"))
        solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        r = solver.solve(snap)  # identical resubmit
        assert solver.last_solve_mode == "hybrid-delta"
        assert not r.pod_errors
        assert len(_placed_names(r)) == 8

    def test_hybrid_delta_parity_with_pure_ffd(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        snap.pods.append(make_pod(cpu="500m", name="extra"))
        hybrid_results = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        ffd_results = FFDSolver().solve(make_snapshot(list(snap.pods)))
        assert set(hybrid_results.pod_errors) == set(ffd_results.pod_errors) == set()
        assert _placed_names(hybrid_results) == _placed_names(ffd_results)

    def test_unseen_shape_falls_back_to_cold_hybrid(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        # an unseen signature cannot ride the delta encode: cold hybrid re-runs
        snap.pods.append(make_pod(cpu="333m", memory="333Mi", name="strange"))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        assert solver.last_backend == "hybrid"
        assert not r.pod_errors


class TestEncodeCachePreserved:
    def test_full_hybrid_full_plus_one_resolves_as_delta(self):
        """The satellite regression: a hybrid solve's sub-encode must not
        overwrite the full-snapshot cache slot — after full -> hybrid, the
        next full-shape snapshot (odd pod gone, one known-shape pod added)
        still rides the delta machinery."""
        plain = [make_pod(cpu="500m", name=f"p{i}") for i in range(6)]
        snap = make_snapshot(list(plain))
        solver = TPUSolver()
        solver.solve(snap)
        assert solver.last_solve_mode == "full"
        odd = odd_pod()
        snap.pods.append(odd)
        solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        # the cache slot holds the FULL hybrid-snapshot encode, not the
        # tensor-side sub-encode
        cached = solver.encode_cache.last_enc
        assert cached.n_pods == 7 and cached.fallback_reasons
        snap.pods.remove(odd)
        snap.pods.append(make_pod(cpu="500m", name="p-new"))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "delta", (solver.last_solve_mode, solver.last_fallback_reasons)
        assert solver.last_backend == "tpu"
        assert not r.pod_errors
        assert len(_placed_names(r)) == 7

    def test_removing_flagged_pod_clears_reasons_via_attribution(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        snap.pods = [p for p in snap.pods if p.metadata.name != "odd"]
        r = solver.solve(snap)
        # reasons re-derived empty by per-signature attribution; the solve
        # rides the tensor path (delta against the masked carry)
        assert solver.last_solve_mode == "delta"
        assert solver.last_backend == "tpu"
        assert not solver.last_fallback_reasons
        assert not r.pod_errors


class TestPartitionInvalidation:
    def test_nodepool_edit_invalidates_retained_partition(self):
        """README decision-tree note: nodepool edits break the row cache key,
        so the next hybrid solve re-encodes in full (cold hybrid, not
        hybrid-delta)."""
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        snap.node_pools[0].spec.template.labels["edited"] = "1"  # hash-visible nodepool edit
        snap.pods.append(make_pod(cpu="500m", name="after-edit"))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        assert not r.pod_errors

    def test_group_membership_change_invalidates_partition(self):
        # a new pod shape declaring a topology group is an unseen signature:
        # the delta encode cannot extend the sig axis, so cold hybrid re-runs
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        sel = {"matchLabels": {"app": "w"}}
        snap.pods.append(make_pod(cpu="500m", name="grouped", labels={"app": "w"}, tsc=[zone_spread(selector=sel)]))
        r = solver.solve(snap)
        assert solver.last_solve_mode == "hybrid"
        assert not r.pod_errors


class TestMetrics:
    def test_encode_histogram_and_hybrid_delta_counter(self):
        reg = make_registry()
        snap = _hybrid_snap()
        solver = TPUSolver(registry=reg)
        solver.solve(snap)
        h = reg.histogram(SOLVER_ENCODE_SECONDS)
        assert h.count(mode="full") >= 1
        assert h.count(mode="masked") >= 1
        assert reg.counter(SOLVER_SOLVE_TOTAL).value(backend="hybrid") == 1
        solver.solve(snap)
        assert solver.last_solve_mode == "hybrid-delta"
        assert reg.counter(SOLVER_SOLVE_TOTAL).value(backend="hybrid-delta") == 1
        assert h.count(mode="delta") >= 1
        assert reg.counter(SOLVER_HYBRID_RESIDUAL_TOTAL).value(reason="pod-affinity") >= 2

    def test_phase_seconds_populated(self):
        snap = _hybrid_snap()
        solver = TPUSolver()
        solver.solve(snap)
        ph = solver.last_phase_seconds
        assert set(ph) == {"encode", "pack", "residual"}
        assert ph["encode"] > 0 and ph["pack"] > 0 and ph["residual"] > 0
