"""Tensorized minValues + coupled-spread seam parity (ISSUE 3 tentpole).

NodePool minValues no longer demotes the snapshot to the host FFD: the pack
runs unconstrained and decode enforces `satisfies_min_values` per produced
claim (TPUSolver._enforce_min_values) — widening decode-added domain pins,
relaxing under the BestEffort policy, and routing irreparable claims through
the bounded host repair. This suite proves, over randomized snapshots, that
every produced claim satisfies every minValues bound, that the bound
propagates into the API NodeClaim, and that node counts match the host FFD.

The coupled-spread half proves the other tentpole leg: a spread group whose
selector spans the hybrid seam splits cleanly because the residual scheduler
sees the tensor side's per-domain occupancy (tpu._seam_records) — no
spread-constraint violation across the partition seam.
"""

import random

import pytest

from helpers import make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.types import satisfies_min_values
from karpenter_tpu.kube.objects import Affinity, PodAffinityTerm, WeightedPodAffinityTerm
from karpenter_tpu.solver import FFDSolver
from karpenter_tpu.solver.encode import check_capability
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results
from test_solver import LINUX_AMD64, make_snapshot

MV_KEY = wk.INSTANCE_TYPE_LABEL_KEY


def minvalues_pool(key=MV_KEY, operator="Exists", values=(), mv=2):
    return make_nodepool(
        requirements=LINUX_AMD64 + [{"key": key, "operator": operator, "values": list(values), "minValues": mv}]
    )


def random_pods(rng, n):
    pods = []
    for i in range(n):
        k = rng.random()
        cpu = rng.choice(["250m", "500m", "1", "2", "4"])
        mem = rng.choice(["256Mi", "512Mi", "1Gi", "4Gi"])
        if k < 0.15:
            pods.append(
                make_pod(cpu=cpu, memory=mem, name=f"z{i}", node_selector={wk.ZONE_LABEL_KEY: rng.choice(["test-zone-a", "test-zone-b"])})
            )
        elif k < 0.3:
            pods.append(make_pod(cpu=cpu, memory=mem, name=f"l{i}", labels={"tier": rng.choice(["a", "b"])}))
        else:
            pods.append(make_pod(cpu=cpu, memory=mem, name=f"p{i}"))
    return pods


def assert_claims_satisfy_min_values(results):
    for nc in results.new_node_claims:
        assert nc.requirements.has_min_values(), "template minValues must survive to the claim"
        _, unsat = satisfies_min_values(nc.instance_type_options, nc.requirements)
        assert not unsat, f"claim violates minValues: {unsat}"


class TestMinValuesTensorized:
    def test_min_values_is_not_a_capability_reason(self):
        snap = make_snapshot([make_pod(cpu="1")], node_pools=[minvalues_pool(mv=3)])
        assert check_capability(snap) == []

    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_parity_with_host_ffd(self, seed):
        rng = random.Random(seed)
        mv = rng.choice([2, 3, 4])
        n = rng.randrange(20, 60)

        def snap():
            return make_snapshot(random_pods(random.Random(seed), n), node_pools=[minvalues_pool(mv=mv)])

        solver = TPUSolver()
        results = solver.solve(snap())
        assert solver.last_backend == "tpu", solver.last_fallback_reasons
        assert not results.pod_errors, list(results.pod_errors.values())[:3]
        assert_claims_satisfy_min_values(results)
        assert not validate_results(snap(), results)

        ffd_results = FFDSolver().solve(snap())
        assert not ffd_results.pod_errors
        assert len(results.new_node_claims) == len(ffd_results.new_node_claims)

    def test_min_values_propagates_to_api_node_claim(self):
        snap = make_snapshot(random_pods(random.Random(7), 12), node_pools=[minvalues_pool(mv=3)])
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "tpu" and not results.pod_errors
        for nc in results.new_node_claims:
            api = nc.to_api_node_claim()
            it_reqs = [d for d in api.spec.requirements if d["key"] == MV_KEY and d["operator"] == "In"]
            assert it_reqs and it_reqs[0].get("minValues") == 3
            assert len(set(it_reqs[0]["values"])) >= 3

    def test_zone_min_values_widens_decode_pin(self):
        # minValues on the ZONE key: the decode's row-commitment pin would
        # observe a single zone; with no zone topology group and no pod zone
        # constraints the pin is widened and the bound met tensor-side
        pool = minvalues_pool(key=wk.ZONE_LABEL_KEY, operator="Exists", mv=2)
        pods = [make_pod(cpu="1", name=f"p{i}") for i in range(8)]
        snap = make_snapshot(pods, node_pools=[pool])
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert not results.pod_errors, list(results.pod_errors.values())[:3]
        for nc in results.new_node_claims:
            _, unsat = satisfies_min_values(nc.instance_type_options, nc.requirements)
            assert not unsat
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert len({z for it in nc.instance_type_options for o in it.offerings if o.available and zr.has(o.zone()) for z in [o.zone()]}) >= 2

    def test_zone_min_values_with_spread_keeps_pin_and_repairs(self):
        # the slot's pods DECLARE a zone spread: the commitment is
        # load-bearing, widening is refused, and the claims route through
        # the bounded host repair, which reproduces the host outcome exactly
        pool = minvalues_pool(key=wk.ZONE_LABEL_KEY, operator="Exists", mv=2)
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", name=f"s{i}", labels={"app": "w"}, tsc=[zone_spread(selector=sel)]) for i in range(6)]
        snap = make_snapshot(pods, node_pools=[pool])
        solver = TPUSolver()
        results = solver.solve(snap)
        ffd_results = FFDSolver().solve(
            make_snapshot(
                [make_pod(cpu="1", name=f"s{i}", labels={"app": "w"}, tsc=[zone_spread(selector=sel)]) for i in range(6)],
                node_pools=[minvalues_pool(key=wk.ZONE_LABEL_KEY, operator="Exists", mv=2)],
            )
        )
        # parity on the OUTCOME: same scheduled/failed pod partition
        assert {k for k in results.pod_errors} == {k for k in ffd_results.pod_errors}
        for nc in results.new_node_claims:
            _, unsat = satisfies_min_values(nc.instance_type_options, nc.requirements)
            assert not unsat

    def test_best_effort_relaxes_like_host(self):
        n_types = len(catalog.construct_instance_types())
        pool = minvalues_pool(mv=n_types + 50)  # more flexibility than exists
        pods = [make_pod(cpu="1", name=f"p{i}") for i in range(6)]
        snap = make_snapshot(pods, node_pools=[pool])
        snap.min_values_policy = "BestEffort"
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert not results.pod_errors, list(results.pod_errors.values())[:3]
        for nc in results.new_node_claims:
            relaxed = nc.requirements.get(MV_KEY).min_values
            assert relaxed is not None and relaxed <= len(nc.instance_type_options)
            _, unsat = satisfies_min_values(nc.instance_type_options, nc.requirements)
            assert not unsat

        ffd_snap = make_snapshot(
            [make_pod(cpu="1", name=f"p{i}") for i in range(6)], node_pools=[minvalues_pool(mv=n_types + 50)]
        )
        ffd_snap.min_values_policy = "BestEffort"
        ffd_results = FFDSolver().solve(ffd_snap)
        # both backends schedule everything; claim COUNTS legitimately differ
        # (the host's in-flight no-relax rule splinters claims, the tensor
        # path relaxes once over the co-packed claim)
        assert not ffd_results.pod_errors
        assert len(results.new_node_claims) <= len(ffd_results.new_node_claims)

    def test_strict_unsatisfiable_repairs_to_host_errors(self):
        n_types = len(catalog.construct_instance_types())
        pool = minvalues_pool(mv=n_types + 50)
        pods = [make_pod(cpu="1", name=f"p{i}") for i in range(4)]
        solver = TPUSolver()
        results = solver.solve(make_snapshot(pods, node_pools=[pool]))
        ffd_results = FFDSolver().solve(
            make_snapshot([make_pod(cpu="1", name=f"p{i}") for i in range(4)], node_pools=[minvalues_pool(mv=n_types + 50)])
        )
        # both paths fail every pod, with the host's minValues message
        assert set(results.pod_errors) == set(ffd_results.pod_errors)
        assert all("minValues" in e for e in results.pod_errors.values())

    def test_repair_clears_resident_carry(self):
        # a repaired solve must not leave a divergent device carry behind
        n_types = len(catalog.construct_instance_types())
        pool = minvalues_pool(mv=n_types + 50)
        pods = [make_pod(cpu="1", name=f"p{i}") for i in range(4)]
        solver = TPUSolver()
        solver.solve(make_snapshot(pods, node_pools=[pool]))
        assert solver._resident is None
        # the next (clean) solve takes the full path and succeeds
        results = solver.solve(make_snapshot([make_pod(cpu="1", name="ok")]))
        assert solver.last_solve_mode == "full" and not results.pod_errors

    def test_decode_repair_metric_counts(self):
        from karpenter_tpu.metrics import SOLVER_DECODE_REPAIR_TOTAL, make_registry

        registry = make_registry()
        n_types = len(catalog.construct_instance_types())
        pool = minvalues_pool(mv=n_types + 50)
        solver = TPUSolver(registry=registry)
        solver.solve(make_snapshot([make_pod(cpu="1")], node_pools=[pool]))
        assert registry.counter(SOLVER_DECODE_REPAIR_TOTAL).value(reason="min-values") >= 1


class TestCoupledSpreadSeam:
    """The residual must respect tensor-side domain occupancy: a spread
    group spanning the hybrid seam keeps its combined skew bound."""

    @pytest.mark.parametrize("seed", range(4))
    def test_no_skew_violation_across_seam(self, seed):
        rng = random.Random(seed)
        sel = {"matchLabels": {"app": "web"}}
        n_clean = rng.randrange(6, 14)
        n_flagged = rng.randrange(1, 4)

        def flagged(i):
            p = make_pod(cpu="500m", name=f"f{i}", labels={"app": "web"}, tsc=[zone_spread(selector=sel)])
            p.spec.affinity = Affinity(
                pod_affinity_preferred=[
                    WeightedPodAffinityTerm(
                        weight=1,
                        term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
                    )
                ]
            )
            return p

        pods = [make_pod(cpu="500m", name=f"w{i}", labels={"app": "web"}, tsc=[zone_spread(selector=sel)]) for i in range(n_clean)]
        pods += [flagged(i) for i in range(n_flagged)]
        pods += [make_pod(cpu=rng.choice(["1", "2"]), name=f"x{i}") for i in range(rng.randrange(0, 6))]
        snap = make_snapshot(pods)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "hybrid", (solver.last_backend, solver.last_fallback_reasons[:2])
        assert not results.pod_errors, list(results.pod_errors.values())[:3]

        zone_counts: dict[str, int] = {}
        for nc in results.new_node_claims:
            members = [p for p in nc.pods if p.metadata.labels.get("app") == "web"]
            if not members:
                continue
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert len(zr.values) == 1, "spread-member claim must commit to one zone"
            z = next(iter(zr.values))
            zone_counts[z] = zone_counts.get(z, 0) + len(members)
        for en in results.existing_nodes:
            members = [p for p in en.pods if p.metadata.labels.get("app") == "web"]
            if members:
                z = en.state_node.labels().get(wk.ZONE_LABEL_KEY)
                zone_counts[z] = zone_counts.get(z, 0) + len(members)
        observed = [c for c in zone_counts.values() if c > 0]
        assert observed and max(observed) - min(observed) <= 1, zone_counts

    def test_seam_records_cover_only_cross_seam_members(self):
        # no cross-seam spread -> empty export (the common case stays free)
        import numpy as np

        from karpenter_tpu.solver.encode import encode

        pods = [make_pod(cpu="500m", name=f"p{i}") for i in range(4)]
        odd = make_pod(cpu="500m", name="odd")
        odd.spec.affinity = Affinity(
            pod_affinity_preferred=[
                WeightedPodAffinityTerm(
                    weight=1,
                    term=PodAffinityTerm(label_selector={"matchLabels": {"x": "y"}}, topology_key=wk.ZONE_LABEL_KEY),
                )
            ]
        )
        snap = make_snapshot(pods + [odd])
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "hybrid"
        enc = solver.encode_cache.last_enc
        keep = np.ones(enc.n_sigs, dtype=bool)
        keep[list(enc.fallback_sig_local)] = False
        assert TPUSolver._seam_records(enc, keep, results) == []


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
