"""Registration gating: provider-supplied lifecycle hooks and the
do-not-sync-taints node label (registration.go:93-116 hook gating +
:211-217 taint-sync skip; registration_test.go:299-494 taint-sync corpus,
suite hooks contexts :668-790)."""

from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.taints import Taint

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


class Hook:
    def __init__(self, name, ready=False):
        self.name = name
        self.ready = ready
        self.calls = 0

    def registered(self, nc):
        self.calls += 1
        return self.ready


def make_env(hooks=None, taints=None, startup_taints=None):
    env = Environment(options=Options(), registration_hooks=hooks)
    np = make_nodepool(requirements=LINUX_AMD64)
    if taints:
        np.spec.template.taints = taints
    if startup_taints:
        np.spec.template.startup_taints = startup_taints
    env.store.create(np)
    return env


class TestRegistrationHooks:
    def test_single_passing_hook_completes_registration(self):
        # suite :668 — a ready hook lets registration complete normally
        hook = Hook("h1", ready=True)
        env = make_env(hooks=[hook])
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_registered()
        assert hook.calls >= 1
        assert env.store.get("Pod", "p", namespace="default").spec.node_name

    def test_unready_hook_defers_registration(self):
        # suite :697 — hook returns false: unregistered taint stays, the
        # Registered condition reports the pending hook
        hook = Hook("slow-hook", ready=False)
        env = make_env(hooks=[hook])
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        nc = env.store.list("NodeClaim")[0]
        assert not nc.is_registered()
        node = env.store.list("Node")[0]
        assert any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints)
        # labels/annotations still synced while deferred (registration.go:92)
        assert node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY)

    def test_second_hook_unready_defers_with_multiple_hooks(self):
        # suite :762 — ALL hooks must pass
        h1, h2 = Hook("ready", ready=True), Hook("not-ready", ready=False)
        env = make_env(hooks=[h1, h2])
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        assert not env.store.list("NodeClaim")[0].is_registered()

    def test_hook_becoming_ready_completes_registration(self):
        hook = Hook("late", ready=False)
        env = make_env(hooks=[hook])
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        assert not env.store.list("NodeClaim")[0].is_registered()
        hook.ready = True
        env.settle(rounds=4)
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_registered()
        node = env.store.get("Node", nc.status.node_name)
        assert not any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints)
        assert env.store.get("Pod", "p", namespace="default").spec.node_name


class TestDoNotSyncTaints:
    """The provider (not the template — karpenter.sh/* template labels are
    restricted) stamps the label on the NODE, exactly like the reference
    tests set node.Labels[NodeDoNotSyncTaintsLabelKey] directly."""

    def _launch_with_node_label(self, env, value):
        """Provision with a registration delay, stamp the label on the node
        the moment it appears (pre-registration), then let lifecycle run."""
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 2.0
        env.store.update(nodeclass)
        env.provisioner.reconcile(force=True)
        env.lifecycle.reconcile_all()  # launch
        env.clock.step(3.0)
        env.cloud_provider.flush_pending()  # node created, unregistered
        node = env.store.list("Node")[0]

        def stamp(n):
            n.metadata.labels[wk.NODE_DO_NOT_SYNC_TAINTS_LABEL_KEY] = value

        env.store.patch("Node", node.metadata.name, stamp)
        env.settle(rounds=4)
        return env.store.list("NodeClaim")[0]

    def test_taints_not_synced_with_label(self):
        # registration_test.go:347 — provider-managed taints: claim taints
        # are NOT copied, but the unregistered taint is still removed
        taint = Taint(key="custom/taint", value="v", effect="NoSchedule")
        env = make_env(taints=[taint])
        env.store.create(make_pod(cpu="100m", name="p", tolerations=[{"operator": "Exists"}]))
        nc = self._launch_with_node_label(env, "true")
        assert nc.is_registered()
        node = env.store.get("Node", nc.status.node_name)
        assert not any(t.key == "custom/taint" for t in node.spec.taints)
        assert not any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints)

    def test_taints_synced_when_label_not_true(self):
        # registration_test.go:320 — label present but != "true" syncs
        taint = Taint(key="custom/taint", value="v", effect="NoSchedule")
        env = make_env(taints=[taint])
        env.store.create(make_pod(cpu="100m", name="p", tolerations=[{"operator": "Exists"}]))
        nc = self._launch_with_node_label(env, "false")
        assert nc.is_registered()
        node = env.store.get("Node", nc.status.node_name)
        assert any(t.key == "custom/taint" for t in node.spec.taints)

    def test_startup_taints_not_synced_with_label(self):
        # registration_test.go:377 — startupTaints skipped too; without the
        # startup taint ever appearing, initialization proceeds
        st = Taint(key="startup/gate", value="", effect="NoSchedule")
        env = make_env(startup_taints=[st])
        env.store.create(make_pod(cpu="100m", name="p"))
        nc = self._launch_with_node_label(env, "true")
        assert nc.is_registered()
        node = env.store.get("Node", nc.status.node_name)
        assert not any(t.key == "startup/gate" for t in node.spec.taints)
        assert nc.is_initialized()


class TestLivenessTimeouts:
    """liveness.go:57-103 — an unlaunched claim dies on the 5-minute launch
    timeout; an unregistered one on the 15-minute registration timeout, each
    anchored at its CONDITION's transition time, never the claim's creation
    (liveness_test.go:130,:224,:264)."""

    def test_unlaunched_claim_killed_on_launch_timeout(self):
        from karpenter_tpu.controllers.nodeclaim.lifecycle import LAUNCH_TIMEOUT_SECONDS

        env = make_env()
        # the nodeclass is never ready → Launched=False, claim stuck
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.status.conditions.set_false("Ready", "NotReady", now=env.clock.now())
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="100m", name="p"))
        env.provisioner.reconcile(force=True)
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 1
        nc = env.store.list("NodeClaim")[0]
        assert not nc.is_launched()
        # inside the launch window: survives
        env.clock.step(LAUNCH_TIMEOUT_SECONDS - 30)
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 1
        # past it: killed (second pass finalizes the two-phase delete)
        env.clock.step(60)
        env.lifecycle.reconcile_all()
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 0

    def test_registration_timeout_anchors_at_condition_transition(self):
        from karpenter_tpu.controllers.nodeclaim.lifecycle import (
            LAUNCH_TIMEOUT_SECONDS,
            REGISTRATION_TTL_SECONDS,
        )

        env = make_env()
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 10**9  # never registers
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="100m", name="p"))
        # age the world a bit BEFORE the claim launches: the timeout must
        # count from the Registered=Unknown transition, not claim creation
        env.provisioner.reconcile(force=True)
        env.clock.step(120)
        env.lifecycle.reconcile_all()  # launch + Registered=Unknown anchor
        nc = env.store.list("NodeClaim")[0]
        assert nc.is_launched() and not nc.is_registered()
        # at creation + TTL the claim is still inside the condition-anchored
        # window (anchor is 120s after creation)
        env.clock.step(REGISTRATION_TTL_SECONDS - 60)
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 1
        env.clock.step(120)
        env.lifecycle.reconcile_all()
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 0

    def test_anchor_does_not_reset_on_node_flap_with_pending_hooks(self):
        # review finding: the Registered status must stay Unknown whether the
        # node is missing OR hooks are pending — an Unknown↔False oscillation
        # would reset the liveness anchor and let the claim evade the TTL
        hook = Hook("never-ready", ready=False)
        env = make_env(hooks=[hook])
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=3)
        nc = env.store.list("NodeClaim")[0]
        cond = nc.status.conditions.get("Registered")
        assert cond is not None and cond.status == "Unknown"
        anchor = cond.last_transition_time
        # more rounds with the node present + hook pending: no transition
        env.clock.step(60)
        env.lifecycle.reconcile_all()
        nc = env.store.list("NodeClaim")[0]
        assert nc.status.conditions.get("Registered").last_transition_time == anchor


class TestClaimTermination:
    """nodeclaim lifecycle finalize guards (controller.go:198-260;
    termination_test.go:233,:270,:297,:400)."""

    def _provisioned(self):
        env = make_env()
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        return env

    def test_all_duplicate_nodes_deleted(self):
        # :233/:270 — every node mapping to the claim is deleted, and the
        # claim waits for all of them
        from karpenter_tpu.kube import Node, ObjectMeta
        from karpenter_tpu.kube.objects import NodeSpec, NodeStatus

        env = self._provisioned()
        nc = env.store.list("NodeClaim")[0]
        dup = Node(
            metadata=ObjectMeta(name="dup-node", labels={wk.NODE_REGISTERED_LABEL_KEY: "true"}),
            spec=NodeSpec(provider_id=nc.status.provider_id),
            status=NodeStatus(),
        )
        env.store.create(dup)
        env.store.delete("Pod", "p", namespace="default")  # no re-provision noise
        env.store.delete("NodeClaim", nc.metadata.name)
        env.settle(rounds=8)
        assert env.store.count("Node") == 0
        assert env.store.count("NodeClaim") == 0

    def test_unregistered_claim_does_not_delete_nodes(self):
        # :400 — deleting an unregistered claim terminates the instance
        # directly; no graceful node-drain cycle is started for a node the
        # claim never registered against
        env = make_env()
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 2.0
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="100m", name="p"))
        env.provisioner.reconcile(force=True)
        env.lifecycle.reconcile_all()  # launch; node held back
        env.clock.step(3.0)
        env.cloud_provider.flush_pending()  # node exists, unregistered
        nc = env.store.list("NodeClaim")[0]
        assert not nc.is_registered()
        env.store.delete("NodeClaim", nc.metadata.name)
        env.lifecycle.reconcile_all()
        env.lifecycle.reconcile_all()
        # instance (and with it the KWOK node) is gone without a drain cycle
        assert env.store.count("NodeClaim") == 0
        assert env.store.count("Node") == 0

    def test_unlaunched_claim_skips_cloud_delete(self):
        # :297 — no providerID: the finalizer falls off without touching the
        # cloud provider
        env = make_env()
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.status.conditions.set_false("Ready", "NotReady", now=env.clock.now())
        env.store.update(nodeclass)
        env.store.create(make_pod(cpu="100m", name="p"))
        env.provisioner.reconcile(force=True)
        env.lifecycle.reconcile_all()
        nc = env.store.list("NodeClaim")[0]
        assert not nc.status.provider_id
        calls = []
        real_delete = env.cloud_provider.delete
        env.cloud_provider.delete = lambda claim: (calls.append(claim.metadata.name), real_delete(claim))
        env.store.delete("NodeClaim", nc.metadata.name)
        env.lifecycle.reconcile_all()
        assert env.store.count("NodeClaim") == 0
        assert calls == [], "cloud provider must not be touched for an unlaunched claim" 


class TestEphemeralTaintInitialization:
    """initialization_test.go:508-658 — known ephemeral taints
    (not-ready/unreachable/cloud-provider-uninitialized and readiness.k8s.io/
    prefixed gates) block initialization until they lift."""

    def _registered_env(self):
        env = make_env()
        env.store.create(make_pod(cpu="100m", name="p"))
        nodeclass = env.store.get("KWOKNodeClass", "default")
        nodeclass.spec.node_registration_delay = 2.0
        env.store.update(nodeclass)
        env.provisioner.reconcile(force=True)
        env.lifecycle.reconcile_all()
        env.clock.step(3.0)
        env.cloud_provider.flush_pending()
        return env, env.store.list("Node")[0]

    def _with_taint(self, key, effect="NoSchedule"):
        env, node = self._registered_env()

        def taint(n):
            n.spec.taints.append(Taint(key=key, value="", effect=effect))

        env.store.patch("Node", node.metadata.name, taint)
        env.settle(rounds=3)
        nc = env.store.list("NodeClaim")[0]
        return env, node, nc

    def test_not_ready_taint_blocks_until_removed(self):
        env, node, nc = self._with_taint("node.kubernetes.io/not-ready")
        assert nc.is_registered() and not nc.is_initialized()

        def lift(n):
            n.spec.taints = [t for t in n.spec.taints if t.key != "node.kubernetes.io/not-ready"]

        env.store.patch("Node", node.metadata.name, lift)
        env.settle(rounds=3)
        assert env.store.list("NodeClaim")[0].is_initialized()

    def test_readiness_prefix_taint_blocks_until_removed(self):
        env, node, nc = self._with_taint("readiness.k8s.io/kube-proxy")
        assert nc.is_registered() and not nc.is_initialized()

        def lift(n):
            n.spec.taints = [t for t in n.spec.taints if not t.key.startswith("readiness.k8s.io/")]

        env.store.patch("Node", node.metadata.name, lift)
        env.settle(rounds=3)
        assert env.store.list("NodeClaim")[0].is_initialized()

    def test_unrelated_taint_does_not_block(self):
        env, node, nc = self._with_taint("custom/fine")
        assert nc.is_initialized()


class TestNodeOwnerReference:
    def test_owner_reference_added_once(self):
        # registration_test.go:142-196 — the claim owns its node; re-syncs
        # must not duplicate the reference
        env = make_env()
        env.store.create(make_pod(cpu="100m", name="p"))
        env.settle(rounds=4)
        nc = env.store.list("NodeClaim")[0]
        node = env.store.get("Node", nc.status.node_name)
        owners = [r for r in node.metadata.owner_references if r.kind == "NodeClaim"]
        assert len(owners) == 1
        assert owners[0].uid == nc.metadata.uid and owners[0].block_owner_deletion
        env.settle(rounds=2)  # extra reconciles: still exactly one
        node = env.store.get("Node", nc.status.node_name)
        assert len([r for r in node.metadata.owner_references if r.kind == "NodeClaim"]) == 1
