"""Scheduler depth, batch 2: unowned existing nodes, deleting-node
rescheduling, in-flight balancing, and startup-taint assumptions — ported
from suite_test.go's existing/in-flight node families."""

from helpers import make_nodepool, make_pod, parse_resource_list, zone_spread
from test_solver import LINUX_AMD64
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.kube.objects import Node, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling.taints import Taint


def make_env(np_kwargs=None):
    env = Environment(options=Options())
    np_kwargs = dict(np_kwargs or {})
    np_kwargs.setdefault("requirements", LINUX_AMD64)
    env.store.create(make_nodepool(**np_kwargs))
    return env


def unowned_node(name="byo-1", zone="test-zone-a", cpu="16"):
    """A bring-your-own Node with no NodeClaim (suite_test.go 'existing node
    unowned by Karpenter')."""
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={wk.HOSTNAME_LABEL_KEY: name, wk.ZONE_LABEL_KEY: zone},
        ),
        spec=NodeSpec(provider_id=f"byo://{name}"),
        status=NodeStatus(
            capacity=parse_resource_list({"cpu": cpu, "memory": "32Gi", "pods": "110"}),
            allocatable=parse_resource_list({"cpu": cpu, "memory": "32Gi", "pods": "110"}),
        ),
    )


class TestUnownedExistingNodes:
    def test_pod_schedules_to_unowned_node(self):
        # suite_test.go "should schedule a pod to an existing node unowned by
        # Karpenter": no NodeClaim exists, yet the node's capacity is used
        env = make_env()
        env.store.create(unowned_node())
        env.store.create(make_pod(cpu="1", name="p0"))
        env.settle(rounds=6)
        pod = env.store.get("Pod", "p0")
        assert pod.spec.node_name == "byo-1"
        assert env.store.count("NodeClaim") == 0, "no new capacity launched"

    def test_multiple_pods_schedule_to_unowned_node(self):
        env = make_env()
        env.store.create(unowned_node(cpu="32"))
        for i in range(5):
            env.store.create(make_pod(cpu="2", name=f"p{i}"))
        env.settle(rounds=6)
        assert all(p.spec.node_name == "byo-1" for p in env.store.list("Pod"))
        assert env.store.count("NodeClaim") == 0

    def test_overflow_beyond_unowned_capacity_launches(self):
        env = make_env()
        env.store.create(unowned_node(cpu="2"))
        for i in range(4):
            env.store.create(make_pod(cpu="1500m", name=f"p{i}"))
        env.settle(rounds=8)
        assert all(p.spec.node_name for p in env.store.list("Pod"))
        assert env.store.count("NodeClaim") >= 1


class TestDeletingNodeRescheduling:
    def test_pods_reschedule_from_marked_for_deletion_node(self):
        # suite_test.go "should re-schedule pods from a deleting node when
        # pods are active": a node being drained counts its reschedulable
        # pods as pending demand so replacement capacity launches BEFORE the
        # pods are actually evicted
        env = make_env()
        env.store.create(make_pod(cpu="2", name="p0"))
        env.settle(rounds=6)
        node = env.store.list("Node")[0]
        env.store.delete("Node", node.metadata.name)  # finalizer drain begins
        env.settle(rounds=15)
        pod = env.store.get("Pod", "p0")
        assert pod.spec.node_name and pod.spec.node_name != node.metadata.name
        assert env.store.try_get("Node", node.metadata.name) is None


class TestInflightBalancing:
    def test_zone_spread_balances_across_inflight_nodes(self):
        # suite_test.go "should balance pods across zones with in-flight
        # nodes": the second batch sees the first batch's in-flight claims'
        # committed zones and keeps the spread balanced
        env = make_env()
        sel = {"matchLabels": {"app": "web"}}
        for i in range(6):
            env.store.create(
                make_pod(cpu="4", name=f"a{i}", labels={"app": "web"}, tsc=[zone_spread(selector=sel)])
            )
        env.settle(rounds=6)
        for i in range(6):
            env.store.create(
                make_pod(cpu="4", name=f"b{i}", labels={"app": "web"}, tsc=[zone_spread(selector=sel)])
            )
        env.settle(rounds=8)
        counts = {}
        for p in env.store.list("Pod"):
            assert p.spec.node_name
            node = env.store.get("Node", p.spec.node_name)
            z = node.metadata.labels.get(wk.ZONE_LABEL_KEY)
            counts[z] = counts.get(z, 0) + 1
        assert max(counts.values()) - min(counts.values()) <= 1, counts


class TestStartupTaintAssumptions:
    def test_pod_assumed_onto_node_with_startup_taint_before_init(self):
        # suite_test.go "should assume pod will schedule to a tainted node
        # with a custom startup taint": the SCHEDULER's assumption holds (no
        # duplicate capacity launches while the startup taint is present);
        # the taint's owner (e.g. a CNI daemon) clears it when ready, and
        # only then does the pod bind — initialization waits for the clear
        env = make_env(np_kwargs={"taints": None})
        np = env.store.list("NodePool")[0]

        def add_startup(p):
            p.spec.template.startup_taints = [Taint(key="custom/startup", value="true", effect="NoSchedule")]

        env.store.patch("NodePool", np.metadata.name, add_startup)
        env.store.create(make_pod(cpu="1", name="p0"))
        env.settle(rounds=8)
        # the assumption: exactly one claim, no duplicate despite the taint
        assert env.store.count("NodeClaim") == 1
        assert not env.store.get("Pod", "p0").spec.node_name

        # the taint owner clears its startup taint once its daemon is ready
        for n in env.store.list("Node"):

            def clear(x):
                x.spec.taints = [t for t in x.spec.taints if t.key != "custom/startup"]

            env.store.patch("Node", n.metadata.name, clear)
        env.settle(rounds=8)
        assert env.store.get("Pod", "p0").spec.node_name, "pod binds after the startup taint clears"
        assert env.store.count("NodeClaim") == 1

    def test_regular_template_taint_blocks_intolerant_pod(self):
        env = make_env(np_kwargs={"taints": [Taint(key="dedicated", value="gpu", effect="NoSchedule")]})
        env.store.create(make_pod(cpu="1", name="p0"))
        env.settle(rounds=6)
        assert not env.store.get("Pod", "p0").spec.node_name

    def test_not_ready_ephemeral_taint_does_not_block_assumption(self):
        # the node.kubernetes.io/not-ready:NoExecute taint on an
        # uninitialized node is ephemeral — pods still schedule against it
        env = make_env()
        env.store.create(make_pod(cpu="1", name="p0"))
        env.settle(rounds=6)
        nodes = env.store.list("Node")
        assert nodes, "setup: the first pod must have provisioned a node"

        def taint(n):
            n.spec.taints.append(Taint(key="node.kubernetes.io/not-ready", value="", effect="NoExecute"))

        env.store.patch("Node", nodes[0].metadata.name, taint)
        env.store.create(make_pod(cpu="1", name="p1"))
        env.settle(rounds=8)
        assert env.store.get("Pod", "p1").spec.node_name
