"""Required pod affinity + inverse anti-affinity on the TPU tensor path.

Parity specs ported from the reference's topology_test.go affinity sections
(topology.go:54-58,246-355 semantics): self pod affinity on hostname/zone
(co-location + single-domain bootstrap), capacity-bounded co-location,
recorded-domain attraction from running pods, inverse anti-affinity blocking
from running pods, and the capability window (asymmetric / preferred /
combined terms stay on the host FFD oracle).
"""

import numpy as np
import pytest

from helpers import make_nodepool, make_pod, parse_resource_list, zone_spread
from test_solver import LINUX_AMD64, make_snapshot
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube import Store
from karpenter_tpu.kube.objects import Node, NodeSpec, NodeStatus, ObjectMeta, PodAffinityTerm
from karpenter_tpu.solver.encode import check_capability, encode
from karpenter_tpu.solver.ffd import FFDSolver
from karpenter_tpu.solver.snapshot import SolverSnapshot
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

AFF_LABELS = {"security": "s2"}


def self_aff(key, labels=AFF_LABELS):
    return PodAffinityTerm(label_selector={"matchLabels": dict(labels)}, topology_key=key)


def aff_pods(n, key, cpu="500m", labels=AFF_LABELS, **kw):
    return [
        make_pod(cpu=cpu, name=f"aff-{key.split('/')[-1]}-{i}", labels=dict(labels), pod_affinity=[self_aff(key, labels)], **kw)
        for i in range(n)
    ]


def existing_cluster(nodes=(("na", "test-zone-a"), ("nb", "test-zone-b")), node_cpu="32"):
    """Store + cluster with registered/initialized existing nodes."""
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    np_ = make_nodepool(requirements=LINUX_AMD64)
    store.create(np_)
    for name, zone in nodes:
        nc = NodeClaim(metadata=ObjectMeta(name=f"c-{name}", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
        nc.status.provider_id = f"kwok://{name}"
        nc.status.conditions.set_true(COND_REGISTERED)
        nc.status.conditions.set_true(COND_INITIALIZED)
        store.create(nc)
        store.create(
            Node(
                metadata=ObjectMeta(
                    name=name,
                    labels={
                        wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                        wk.HOSTNAME_LABEL_KEY: name,
                        wk.ZONE_LABEL_KEY: zone,
                    },
                ),
                spec=NodeSpec(provider_id=f"kwok://{name}"),
                status=NodeStatus(
                    capacity=parse_resource_list({"cpu": node_cpu, "memory": "64Gi", "pods": "110"}),
                    allocatable=parse_resource_list({"cpu": node_cpu, "memory": "64Gi", "pods": "110"}),
                ),
            )
        )
    return store, clock, cluster, np_


def snapshot_of(store, clock, cluster, np_, pending, types=None):
    types = types if types is not None else catalog.construct_instance_types()
    return SolverSnapshot(
        store=store,
        cluster=cluster,
        node_pools=[np_],
        instance_types={np_.metadata.name: types},
        state_nodes=cluster.nodes(),
        daemonset_pods=[],
        pods=pending,
        clock=clock,
    )


class TestSelfAffinityTensorPath:
    def test_hostname_self_affinity_one_node(self):
        # topology_test.go:2013 "should respect self pod affinity (hostname)"
        snap = make_snapshot(aff_pods(3, wk.HOSTNAME_LABEL_KEY))
        assert check_capability(snap) == []
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        placed = [nc for nc in results.new_node_claims if nc.pods]
        assert len(placed) == 1 and len(placed[0].pods) == 3

    def test_hostname_self_affinity_capacity_bound(self):
        # topology_test.go:2037 "first empty topology domain only": once one
        # host is bootstrapped, overflow pods do NOT open a second node
        types = [catalog.make_instance_type("c", 4, zones=["test-zone-a"])]
        snap = make_snapshot(aff_pods(10, wk.HOSTNAME_LABEL_KEY, cpu="1"), types=types)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        placed = [nc for nc in results.new_node_claims if nc.pods]
        assert len(placed) == 1, "exactly one co-location node"
        n_placed = len(placed[0].pods)
        assert 1 <= n_placed < 10
        assert len(results.pod_errors) == 10 - n_placed

    def test_zone_self_affinity_one_zone(self):
        # topology_test.go:2123 "should respect self pod affinity (zone)"
        snap = make_snapshot(aff_pods(12, wk.ZONE_LABEL_KEY, cpu="4"))
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        zones = set()
        for nc in results.new_node_claims:
            if not nc.pods:
                continue
            zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
            assert len(zr.values) == 1, "claims must pin exactly one zone"
            zones |= set(zr.values)
        assert len(zones) == 1, f"all claims in one zone, got {zones}"

    def test_zone_self_affinity_with_constraint(self):
        # topology_test.go:2147 "(zone w/ constraint)": the pod's own zone
        # selector narrows the bootstrap choice
        pods = aff_pods(3, wk.ZONE_LABEL_KEY, node_selector={wk.ZONE_LABEL_KEY: "test-zone-c"})
        snap = make_snapshot(pods)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        for nc in results.new_node_claims:
            if nc.pods:
                zr = nc.requirements.get(wk.ZONE_LABEL_KEY)
                assert list(zr.values) == ["test-zone-c"]

    def test_zone_affinity_attracted_to_recorded_domain(self):
        # a running pod matching the selector pins the recorded domain: all
        # solve pods co-locate with it instead of bootstrapping elsewhere
        # (_next_domain_affinity: recorded domains win over bootstrap)
        store, clock, cluster, np_ = existing_cluster()
        runner = make_pod(cpu="100m", name="runner", labels=dict(AFF_LABELS))
        runner.spec.node_name = "nb"  # zone-b
        store.create(runner)
        snap = snapshot_of(store, clock, cluster, np_, aff_pods(6, wk.ZONE_LABEL_KEY, cpu="2"))
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        used = {en.state_node.name() for en in results.existing_nodes if en.pods}
        assert used <= {"nb"}
        for nc in results.new_node_claims:
            if nc.pods:
                assert list(nc.requirements.get(wk.ZONE_LABEL_KEY).values) == ["test-zone-b"]

    def test_hostname_affinity_attracted_to_recorded_host(self):
        store, clock, cluster, np_ = existing_cluster()
        runner = make_pod(cpu="100m", name="runner", labels=dict(AFF_LABELS))
        runner.spec.node_name = "na"
        store.create(runner)
        snap = snapshot_of(store, clock, cluster, np_, aff_pods(4, wk.HOSTNAME_LABEL_KEY, cpu="1"))
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        used = {en.state_node.name() for en in results.existing_nodes if en.pods}
        assert used == {"na"}
        assert not [nc for nc in results.new_node_claims if nc.pods]

    def test_mixed_affinity_and_plain_workload_equivalence(self):
        # affinity deployments alongside plain + zone-spread pods: the tensor
        # result must match the host oracle on the simulation contract
        pods = aff_pods(8, wk.ZONE_LABEL_KEY, cpu="2")
        pods += aff_pods(5, wk.HOSTNAME_LABEL_KEY, cpu="500m", labels={"app": "co"})
        pods += [make_pod(cpu="1", name=f"plain-{i}") for i in range(20)]
        sel = {"matchLabels": {"spread": "y"}}
        pods += [
            make_pod(cpu="1", name=f"sp-{i}", labels={"spread": "y"}, tsc=[zone_spread(selector=sel)])
            for i in range(9)
        ]
        snap = make_snapshot(pods)
        assert check_capability(snap) == []
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        ffd_results = FFDSolver().solve(make_snapshot(pods))
        assert results.all_pods_scheduled() == ffd_results.all_pods_scheduled()
        assert results.all_pods_scheduled()


class TestInverseAntiAffinityTensorPath:
    def _snap(self, key, pending_n=6, node_cpu="32"):
        store, clock, cluster, np_ = existing_cluster(node_cpu=node_cpu)
        runner = make_pod(
            cpu="100m",
            name="runner",
            labels={"sentinel": "y"},
            anti_affinity=[PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}}, topology_key=key)],
        )
        runner.spec.node_name = "na"
        store.create(runner)
        pending = [make_pod(cpu="100m", name=f"w{i}", labels={"app": "web"}) for i in range(pending_n)]
        return snapshot_of(store, clock, cluster, np_, pending)

    def test_running_anti_affinity_is_in_window(self):
        snap = self._snap(wk.ZONE_LABEL_KEY)
        assert check_capability(snap) == []

    def test_zone_inverse_blocks_existing_node_and_zone(self):
        # topology_test.go:2463 "should not violate pod anti-affinity on zone
        # (inverse)" — matched incoming pods avoid the running pod's zone
        snap = self._snap(wk.ZONE_LABEL_KEY)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        used = {en.state_node.name() for en in results.existing_nodes if en.pods}
        assert "na" not in used
        for nc in results.new_node_claims:
            if nc.pods:
                assert not nc.requirements.get(wk.ZONE_LABEL_KEY).has("test-zone-a")

    def test_zone_inverse_new_claims_avoid_blocked_zone(self):
        # existing nodes too small -> new claims open, still out of zone-a
        snap = self._snap(wk.ZONE_LABEL_KEY, pending_n=40, node_cpu="1")
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        for nc in results.new_node_claims:
            if nc.pods:
                assert not nc.requirements.get(wk.ZONE_LABEL_KEY).has("test-zone-a")

    def test_hostname_inverse_blocks_only_that_node(self):
        # topology_test.go:2530 "(inverse w/existing nodes)" hostname flavor:
        # only the runner's node is off-limits
        snap = self._snap(wk.HOSTNAME_LABEL_KEY)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        used = {en.state_node.name() for en in results.existing_nodes if en.pods}
        assert "na" not in used and "nb" in used

    def test_unmatched_pods_unaffected(self):
        store, clock, cluster, np_ = existing_cluster()
        runner = make_pod(
            cpu="100m",
            name="runner",
            labels={"sentinel": "y"},
            anti_affinity=[
                PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}}, topology_key=wk.ZONE_LABEL_KEY)
            ],
        )
        runner.spec.node_name = "na"
        store.create(runner)
        pending = [make_pod(cpu="100m", name=f"o{i}", labels={"app": "other"}) for i in range(4)]
        snap = snapshot_of(store, clock, cluster, np_, pending)
        solver = TPUSolver(force=True)
        results = solver.solve(snap)
        assert solver.last_backend == "tpu"
        assert results.all_pods_scheduled()
        used = {en.state_node.name() for en in results.existing_nodes if en.pods}
        assert "na" in used  # first-fit picks the first node: no blocking

    def test_ffd_oracle_agreement(self):
        for key in (wk.ZONE_LABEL_KEY, wk.HOSTNAME_LABEL_KEY):
            snap = self._snap(key)
            tpu = TPUSolver(force=True).solve(snap)
            ffd = FFDSolver().solve(self._snap(key))
            t_used = {en.state_node.name() for en in tpu.existing_nodes if en.pods}
            f_used = {en.state_node.name() for en in ffd.existing_nodes if en.pods}
            assert t_used == f_used
            assert tpu.all_pods_scheduled() == ffd.all_pods_scheduled()


class TestAffinityCapabilityWindow:
    def test_asymmetric_affinity_falls_back(self):
        # topology_test.go:2710 "affinity to a non-existent pod": the pod does
        # not select itself -> asymmetric -> host oracle (which leaves it
        # unschedulable, no co-location target existing)
        pods = [
            make_pod(
                cpu="1",
                name="a0",
                labels={"app": "seeker"},
                pod_affinity=[PodAffinityTerm(label_selector={"matchLabels": {"app": "target"}}, topology_key=wk.ZONE_LABEL_KEY)],
            )
        ]
        snap = make_snapshot(pods)
        reasons = check_capability(snap)
        assert any("asymmetric pod affinity" in r for r in reasons)
        solver = TPUSolver()
        results = solver.solve(snap)
        assert solver.last_backend == "ffd-fallback"
        assert len(results.pod_errors) == 1  # no target pod anywhere

    def test_preferred_affinity_falls_back(self):
        p = make_pod(cpu="1", name="p0", labels=dict(AFF_LABELS))
        p.spec.affinity = type(p.spec.affinity)() if p.spec.affinity else None
        from karpenter_tpu.kube.objects import Affinity, WeightedPodAffinityTerm

        p.spec.affinity = Affinity(
            pod_affinity_preferred=[WeightedPodAffinityTerm(weight=1, term=self_aff(wk.ZONE_LABEL_KEY))]
        )
        snap = make_snapshot([p])
        assert any("preferred pod affinity" in r for r in check_capability(snap))

    def test_combined_affinity_and_spread_falls_back(self):
        sel = {"matchLabels": dict(AFF_LABELS)}
        p = make_pod(
            cpu="1",
            name="c0",
            labels=dict(AFF_LABELS),
            pod_affinity=[self_aff(wk.ZONE_LABEL_KEY)],
            tsc=[zone_spread(selector=sel)],
        )
        snap = make_snapshot([p])
        assert any("combined with other topology constraints" in r for r in check_capability(snap))

    def test_explicit_namespaces_fall_back(self):
        term = PodAffinityTerm(
            label_selector={"matchLabels": dict(AFF_LABELS)},
            topology_key=wk.ZONE_LABEL_KEY,
            namespaces=["other-ns"],
        )
        p = make_pod(cpu="1", name="n0", labels=dict(AFF_LABELS), pod_affinity=[term])
        snap = make_snapshot([p])
        assert any("explicit namespaces" in r for r in check_capability(snap))


class TestAffinityValidation:
    def test_fast_validate_rejects_split_affinity(self):
        # hand-corrupt a placement: affinity members across two hosts with no
        # recorded host must fail fast_validate (host-affinity co-location)
        from karpenter_tpu.solver.check import fast_validate
        from karpenter_tpu.models.scheduler_model import make_tensors
        from karpenter_tpu.models.scheduler_model_grouped import (
            assignment_from_triples,
            build_items,
            make_item_tensors,
        )
        from karpenter_tpu.models.scheduler_model_grouped import greedy_pack_grouped_compressed

        snap = make_snapshot(aff_pods(4, wk.HOSTNAME_LABEL_KEY, cpu="1"))
        enc = encode(snap)
        assert enc.fallback_reasons == []
        item_arrays, item_pods = build_items(enc)
        items = make_item_tensors(item_arrays)
        t = make_tensors(enc, n_slots=enc.n_existing + min(enc.n_pods, 4096), with_pods=False)
        out = greedy_pack_grouped_compressed(t, items, enc.n_pods)
        assignment = assignment_from_triples(
            out["nz_item"], out["nz_slot"], out["nz_count"], item_pods, enc.n_pods
        )
        ok = fast_validate(enc, assignment, out["slot_basis"], out["slot_zoneset"])
        assert ok == []
        # corrupt: open a second slot on the same basis row and move one pod
        # there — co-location is broken, the validator must catch it
        bad = assignment.copy()
        src = int(bad[0])
        other = src + 1
        slot_basis = np.asarray(out["slot_basis"]).copy()
        slot_basis[other] = slot_basis[src]
        slot_zoneset = np.asarray(out["slot_zoneset"]).copy()
        slot_zoneset[other] = slot_zoneset[src]
        bad[0] = other
        violations = fast_validate(enc, bad, slot_basis, slot_zoneset)
        assert any("affinity" in v for v in violations)
