import pytest

from karpenter_tpu.kube import Node, ObjectMeta, Pod, Store
from karpenter_tpu.kube.store import AlreadyExists, Conflict, NotFound
from karpenter_tpu.utils.clock import FakeClock


def test_create_get_list():
    s = Store()
    s.create(Pod(metadata=ObjectMeta(name="a", namespace="ns1")))
    s.create(Pod(metadata=ObjectMeta(name="b", namespace="ns2")))
    assert s.get("Pod", "a", "ns1").metadata.name == "a"
    assert len(s.list("Pod")) == 2
    assert len(s.list("Pod", namespace="ns1")) == 1
    with pytest.raises(AlreadyExists):
        s.create(Pod(metadata=ObjectMeta(name="a", namespace="ns1")))


def test_optimistic_concurrency():
    s = Store()
    s.create(Node(metadata=ObjectMeta(name="n1")))
    a = s.get("Node", "n1")
    b = s.get("Node", "n1")
    a.metadata.labels["x"] = "1"
    s.update(a)
    b.metadata.labels["y"] = "2"
    with pytest.raises(Conflict):
        s.update(b)
    # patch retries through conflicts
    s.patch("Node", "n1", lambda n: n.metadata.labels.update({"y": "2"}))
    assert s.get("Node", "n1").metadata.labels == {"x": "1", "y": "2"}


def test_isolation_deep_copy():
    s = Store()
    s.create(Node(metadata=ObjectMeta(name="n1")))
    n = s.get("Node", "n1")
    n.metadata.labels["mutated"] = "yes"
    assert "mutated" not in s.get("Node", "n1").metadata.labels


def test_finalizer_two_phase_delete():
    clock = FakeClock()
    s = Store(clock=clock)
    s.create(Node(metadata=ObjectMeta(name="n1", finalizers=["karpenter.sh/termination"])))
    s.delete("Node", "n1")
    n = s.get("Node", "n1")  # still present: finalizer holds it
    assert n.metadata.deletion_timestamp is not None
    s.remove_finalizer("Node", "n1", "karpenter.sh/termination")
    with pytest.raises(NotFound):
        s.get("Node", "n1")


def test_watch_events():
    s = Store()
    events = []
    s.watch("Pod", lambda e, o: events.append((e, o.metadata.name)))
    s.create(Pod(metadata=ObjectMeta(name="a")))
    s.patch("Pod", "a", lambda p: p.metadata.labels.update({"x": "1"}))
    s.delete("Pod", "a")
    assert events == [("ADDED", "a"), ("MODIFIED", "a"), ("DELETED", "a")]
