"""DRA allocator behavior depth, ported from the reference's
pkg/scheduling/dynamicresources/allocator_test.go (8,935 LoC of specs).

Each class mirrors one of the reference's Describe blocks; each spec cites
the reference It() by line number. Selector expressions map onto the
structured-dict language (the declared CEL divergence): the behaviors under
test — eligibility, constraint satisfaction, backtracking, counter budgets,
consumable capacity, allocated-claim handling — are language-independent.
"""

from helpers import make_pod
from karpenter_tpu.kube import (
    Device,
    DeviceClass,
    ObjectMeta,
    ResourceClaim,
    ResourceSlice,
    Store,
)
from karpenter_tpu.scheduling.dynamicresources import Allocator
from karpenter_tpu.scheduling.dynamicresources.allocator import AllocationTracker
from karpenter_tpu.scheduling.requirements import Requirement, Requirements
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.quantity import Quantity
from karpenter_tpu.utils.resources import parse_resource_list

from test_dra import build_store, gpu, gpu_claim
from test_dra_superposition import gpu_it, zoned_gpu

DRIVER = "gpu.example.com"


def dev(name, model="a100", multi=False, capacity=None, consumes=None, attrs=None):
    return Device(
        name=name,
        attributes={f"{DRIVER}/model": model, **(attrs or {})},
        capacity=parse_resource_list(capacity) if capacity else {},
        allow_multiple_allocations=multi,
        consumes_counters=consumes or [],
    )


def slice_on(store, node, devices, pool="pool-1", driver=DRIVER, counters=None):
    store.create(
        ResourceSlice(
            metadata=ObjectMeta(name=f"sl-{node}-{pool}"),
            driver=driver,
            pool_name=pool,
            node_name=node,
            devices=devices,
            shared_counters=counters or [],
        )
    )


def claim(name, requests, constraints=None, ns="default"):
    return ResourceClaim(
        metadata=ObjectMeta(name=name, namespace=ns),
        requests=requests,
        constraints=constraints or [],
    )


def req(name="gpus", count=1, model=None, mode=None, capacity=None, selectors=None):
    r = {"name": name, "deviceClassName": "gpu-class", "count": count}
    sels = list(selectors or [])
    if model:
        sels.append({"attribute": "model", "operator": "In", "values": [model]})
    if sels:
        r["selectors"] = sels
    if mode:
        r["allocationMode"] = mode
    if capacity:
        r["capacity"] = parse_resource_list(capacity)
    return r


def picked_names(result, claim_key):
    return sorted(ref.device.name for _n, ref, _c in result.picks[claim_key])


class TestSingleITInCluster:
    """allocator_test.go Describe("Single IT, in-cluster devices") :284-416."""

    def test_allocates_a_single_device(self):
        # :294 "should allocate a single device"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 1

    def test_allocates_multiple_devices_single_request(self):
        # :306 "should allocate multiple devices for a single request"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu(f"g{i}") for i in range(4)])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=3)
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 3

    def test_fails_when_not_enough_devices(self):
        # :316 "should fail when not enough devices are available"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=3)
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and "cannot allocate" in err

    def test_multiple_requests_in_a_single_claim(self):
        # :325 "should handle multiple requests in a single claim"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("big", count=2, model="a100"), req("small", count=1, model="h100")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2", "h1"]

    def test_fails_when_requests_exceed_total(self):
        # :338 "should fail when multiple requests exceed total devices"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("r1", count=2), req("r2", count=1)])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_multiple_claims_one_call(self):
        # :350 "should handle multiple claims"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu(f"g{i}") for i in range(3)])
        alloc = Allocator(store, clock)
        rc1, rc2 = gpu_claim("c1", count=2), gpu_claim("c2", count=1)
        store.create(rc1)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc1, rc2])
        assert err is None
        assert len(result.picks[rc1.key()]) == 2 and len(result.picks[rc2.key()]) == 1

    def test_same_claim_name_distinct_namespaces(self):
        # :363 "should distinguish claims with the same name in different namespaces"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        alloc = Allocator(store, clock)
        rc1 = gpu_claim("shared")
        rc2 = gpu_claim("shared", ns="other")
        store.create(rc1)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc1, rc2])
        assert err is None
        assert set(result.picks) == {"default/shared", "other/shared"}

    def test_skips_already_allocated_devices(self):
        # :389 + :403 — a device held by an in-cluster allocation is skipped,
        # the remaining device is allocated
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        held = gpu_claim("held")
        held.status.allocation = {
            "nodeName": "n1",
            "devices": [{"driver": DRIVER, "pool": "pool-1", "device": "g1"}],
        }
        store.create(held)
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["g2"]


class TestNodePinnedDevices:
    """allocator_test.go Describe("Node-name-pinned in-cluster devices") :418-459."""

    def test_pinned_device_allocates_on_its_node(self):
        # :429
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 1

    def test_pinned_device_not_offered_to_other_node(self):
        # :441
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n2", [rc])
        assert result is None and err is not None

    def test_pinned_device_not_offered_to_inflight_nodeclaim(self):
        # :451 — NodeClaims see template devices only, never node slices
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate("nc-1", [], [rc], alloc.loop_tracker)
        assert result is None and err is not None


class TestSelectorFiltering:
    """allocator_test.go Describe("CEL selector filtering") :461-524 +
    Describe("Combined class and request selectors") :3415-3466, mapped onto
    the structured-dict selector language."""

    def test_only_matching_devices_allocate(self):
        # :494
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("h1", model="h100"), dev("a2")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2, model="a100")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2"]

    def test_fails_when_not_enough_match_selector(self):
        # :504
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2, model="a100")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_request_level_selectors_filter(self):
        # :513 — request selector layered on the class selector
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1", attrs={f"{DRIVER}/mem": "80"}), dev("a2", attrs={f"{DRIVER}/mem": "40"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(selectors=[{"attribute": "mem", "operator": "Gte", "values": ["80"]}])])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1"]

    def test_class_and_request_selectors_must_both_match(self):
        # :3416 "should require both class and request selectors to match" —
        # the class demands the model attribute EXISTS; a device missing it
        # fails even though the request selector matches
        store, clock, _ = build_store()
        bare = Device(name="bare", attributes={f"{DRIVER}/vendor": "x"}, capacity={})
        slice_on(store, "n1", [bare, dev("a1", attrs={f"{DRIVER}/vendor": "x"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(selectors=[{"attribute": "vendor", "operator": "In", "values": ["x"]}])])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1"]

    def test_does_not_exist_excludes_attributed_devices(self):
        # selector-language edge: DoesNotExist inverts Exists
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1", attrs={f"{DRIVER}/shared": "true"}), dev("a2")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(selectors=[{"attribute": "shared", "operator": "DoesNotExist"}])])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a2"]

    def test_non_numeric_attribute_fails_numeric_operator(self):
        # :4168 analogue — an unparseable bound renders the device ineligible
        # instead of raising
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1", attrs={f"{DRIVER}/mem": "lots"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(selectors=[{"attribute": "mem", "operator": "Gt", "values": ["8"]}])])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_unqualified_attribute_name_matches_suffix(self):
        # request.go qualified-name handling: "model" finds "driver/model"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1")])
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", model="a100")  # unqualified "model" selector
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 1


class TestConstraintSatisfaction:
    """allocator_test.go Describe("Constraint satisfaction") :526-605 +
    "Constraint + template integration" :3134-3245 + "Constraint scoped to
    request subset" :4673-4712."""

    def test_backtracks_to_satisfy_constraint(self):
        # :566 "should backtrack to satisfy constraints" — the first pick
        # (a100) strands the constraint; the DFS revises it
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("h1", model="h100"), dev("h2", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(count=2)], constraints=[{"matchAttribute": f"{DRIVER}/model"}])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["h1", "h2"]

    def test_backtracks_across_requests(self):
        # :581 "should satisfy constraints with backtracking across requests"
        # — request r1's pick must be revised when r2 cannot match it
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("h1", model="h100"), dev("h2", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim(
            "c1",
            [req("r1", count=1), req("r2", count=1)],
            constraints=[{"matchAttribute": f"{DRIVER}/model"}],
        )
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["h1", "h2"]

    def test_multiple_constraints_same_claim(self):
        # :3176 "should satisfy multiple constraints on the same claim"
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [
                dev("x1", attrs={f"{DRIVER}/link": "nv4"}),
                dev("x2", model="h100", attrs={f"{DRIVER}/link": "nv4"}),
                dev("x3", attrs={f"{DRIVER}/link": "nv4"}),
            ],
        )
        alloc = Allocator(store, clock)
        rc = claim(
            "c1",
            [req(count=2)],
            constraints=[{"matchAttribute": f"{DRIVER}/model"}, {"matchAttribute": f"{DRIVER}/link"}],
        )
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["x1", "x3"]

    def test_fails_when_constraints_unsatisfiable_together(self):
        # :3211 "should fail when multiple constraints cannot be
        # simultaneously satisfied"
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [
                dev("x1", attrs={f"{DRIVER}/link": "nv4"}),
                dev("x2", attrs={f"{DRIVER}/link": "nv8"}),
            ],
        )
        alloc = Allocator(store, clock)
        rc = claim(
            "c1",
            [req(count=2)],
            constraints=[{"matchAttribute": f"{DRIVER}/model"}, {"matchAttribute": f"{DRIVER}/link"}],
        )
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_constraint_scoped_to_request_subset(self):
        # :4674 "should allow non-scoped requests to cross constraint
        # boundaries" — the constraint binds r1 only; r2 picks a different
        # model freely
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim(
            "c1",
            [req("r1", count=2, model="a100"), req("r2", count=1, model="h100")],
            constraints=[{"matchAttribute": f"{DRIVER}/model", "requests": ["r1"]}],
        )
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2", "h1"]

    def test_constraint_missing_attribute_fails_device(self):
        # constraint.go:41-146 — a device without the matched attribute can
        # never join the constrained set
        store, clock, _ = build_store()
        noattr = Device(name="plain", attributes={f"{DRIVER}/model": "a100"}, capacity={})
        slice_on(store, "n1", [noattr, dev("a1", attrs={f"{DRIVER}/numa": "0"}), dev("a2", attrs={f"{DRIVER}/numa": "0"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(count=2)], constraints=[{"matchAttribute": f"{DRIVER}/numa"}])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2"]


class TestAllMode:
    """allocator_test.go Describe("All-mode allocation") :2574-2728 +
    "Multiple pools in All-mode" :4714-4733 + "All-mode + ExactCount under
    shared constraint" :4386-4500."""

    def test_allocates_all_matching_devices(self):
        # :2575
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(mode="All", count=0, model="a100")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2"]

    def test_all_mode_zero_matches_fails(self):
        # :2620 "should fail when an All-mode request matches zero devices"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(mode="All", count=0, model="a100")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_all_and_exact_mixed_in_one_claim(self):
        # :2653 "should work with All-mode and ExactCount mixed"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100"), dev("h2", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("every-a", mode="All", model="a100"), req("one-h", count=1, model="h100")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2", "h1"]

    def test_match_attribute_in_all_mode(self):
        # :2701 "should satisfy MatchAttribute constraints in All mode" — a
        # mismatched member fails the whole set
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(mode="All")], constraints=[{"matchAttribute": f"{DRIVER}/model"}])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_all_mode_aggregates_multiple_pools(self):
        # :4715 "should aggregate devices from multiple pools in All-mode"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1")], pool="pool-1")
        slice_on(store, "n1", [dev("a2")], pool="pool-2")
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(mode="All", model="a100")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["a1", "a2"]

    def test_all_exact_shared_constraint_mismatch_fails(self):
        # :4446 "should fail when mixed-mode requests cannot share
        # constraint value"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("h1", model="h100")])
        alloc = Allocator(store, clock)
        rc = claim(
            "c1",
            [req("every-a", mode="All", model="a100"), req("one-h", count=1, model="h100")],
            constraints=[{"matchAttribute": f"{DRIVER}/model"}],
        )
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None


class TestMultiPool:
    """allocator_test.go Describe("Multi-pool devices") :3381-3413."""

    def test_allocates_across_pools(self):
        # :3382
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")], pool="pool-1")
        slice_on(store, "n1", [gpu("g2")], pool="pool-2")
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 2

    def test_same_device_name_distinct_pools(self):
        # :3398 "should treat same device name in different pools as distinct"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")], pool="pool-1")
        slice_on(store, "n1", [gpu("g1")], pool="pool-2")
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        pools = sorted(ref.pool for _n, ref, _c in result.picks[rc.key()])
        assert pools == ["pool-1", "pool-2"]


class TestMultiClaimCompetition:
    """allocator_test.go Describe("Multi-claim competition") :3300-3379."""

    def test_claims_fit_within_total_devices(self):
        # :3318
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu(f"g{i}") for i in range(4)])
        alloc = Allocator(store, clock)
        rc1, rc2 = gpu_claim("c1", count=2), gpu_claim("c2", count=2)
        store.create(rc1)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc1, rc2])
        assert err is None
        all_picked = picked_names(result, rc1.key()) + picked_names(result, rc2.key())
        assert sorted(all_picked) == ["g0", "g1", "g2", "g3"]

    def test_claims_exceeding_total_fail(self):
        # :3301 — the second claim finds the pool drained
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        alloc = Allocator(store, clock)
        rc1, rc2 = gpu_claim("c1", count=2), gpu_claim("c2", count=1)
        store.create(rc1)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc1, rc2])
        assert result is None and "c2" in err

    def test_independent_constraints_across_claims(self):
        # :3335 "should maintain independent constraints across claims" —
        # each claim pins its own attribute value
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("a1"), dev("a2"), dev("h1", model="h100"), dev("h2", model="h100")])
        alloc = Allocator(store, clock)
        rc1 = claim("c1", [req(count=2, model="a100")], constraints=[{"matchAttribute": f"{DRIVER}/model"}])
        rc2 = claim("c2", [req(count=2, model="h100")], constraints=[{"matchAttribute": f"{DRIVER}/model"}])
        store.create(rc1)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc1, rc2])
        assert err is None
        assert picked_names(result, rc1.key()) == ["a1", "a2"]
        assert picked_names(result, rc2.key()) == ["h1", "h2"]


class TestUncommittedIsolation:
    """allocator_test.go Describe("Uncommitted allocation state isolation")
    :3048-3090."""

    def test_uncommitted_allocation_reserves_nothing(self):
        # :3049 — allocate() is pure; without commit the same devices serve a
        # second probe
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        alloc = Allocator(store, clock)
        rc1, rc2 = gpu_claim("c1"), gpu_claim("c2")
        store.create(rc1)
        store.create(rc2)
        r1, err1 = alloc.allocate_for_node("n1", [rc1])
        r2, err2 = alloc.allocate_for_node("n1", [rc2])
        assert err1 is None and err2 is None
        assert picked_names(r1, rc1.key()) == picked_names(r2, rc2.key()) == ["g1"]

    def test_commit_reserves_for_later_probes(self):
        # :2533 "should mark in-cluster devices as allocated after commit"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        alloc = Allocator(store, clock)
        rc1, rc2 = gpu_claim("c1"), gpu_claim("c2")
        store.create(rc1)
        store.create(rc2)
        r1, err1 = alloc.allocate_for_node("n1", [rc1])
        assert err1 is None
        alloc.commit_for_node("n1", r1)
        r2, err2 = alloc.allocate_for_node("n1", [rc2])
        assert r2 is None and err2 is not None


class TestConsumableCapacity:
    """allocator_test.go Describe("Consumable capacity — DFS capacity-gated
    allocation") :5135-6355."""

    def test_two_requests_share_multi_alloc_device(self):
        # :5210 "should deduct capacity within a single DFS when multiple
        # slots request the same multi-alloc device"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("big", multi=True, capacity={"memory": "10Gi"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("r1", capacity={"memory": "4Gi"}), req("r2", capacity={"memory": "4Gi"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["big", "big"]

    def test_intra_dfs_capacity_exhaustion_fails(self):
        # :5260 "should fail when intra-DFS capacity deduction exceeds device
        # capacity"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("big", multi=True, capacity={"memory": "10Gi"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("r1", capacity={"memory": "6Gi"}), req("r2", capacity={"memory": "6Gi"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_backtrack_restores_capacity_finds_alternative(self):
        # :5295 "should restore capacity on backtrack and find alternative
        # devices" — r1's 6Gi forces r2 onto the sibling
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [dev("d1", multi=True, capacity={"memory": "10Gi"}), dev("d2", multi=True, capacity={"memory": "10Gi"})],
        )
        alloc = Allocator(store, clock)
        rc = claim("c1", [req("r1", capacity={"memory": "6Gi"}), req("r2", capacity={"memory": "6Gi"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["d1", "d2"]

    def test_missing_capacity_dimension_skips_device(self):
        # :6094 "should skip device missing a requested dimension and succeed
        # on a sibling that has it"
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [dev("nomem", multi=True, capacity={"slots": "4"}), dev("mem", multi=True, capacity={"memory": "8Gi"})],
        )
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(capacity={"memory": "4Gi"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["mem"]

    def test_one_dimension_exceeded_rejects_device(self):
        # :6143 "should reject when one capacity dimension is exceeded even
        # if other dimensions have room"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("d1", multi=True, capacity={"memory": "40Gi", "slots": "1"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(capacity={"memory": "4Gi", "slots": "2"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_both_dimensions_sufficient_succeeds(self):
        # :6176
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("d1", multi=True, capacity={"memory": "40Gi", "slots": "4"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(capacity={"memory": "4Gi", "slots": "2"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 1

    def test_zero_capacity_dimension_rejects(self):
        # :6271 "should reject allocation when device has zero capacity for a
        # requested dimension"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("d1", multi=True, capacity={"memory": "0"})])
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(capacity={"memory": "1Gi"})])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_capacity_accumulates_across_commits(self):
        # :5370 "should accumulate capacity across sequential Allocate+Commit
        # calls for the same multi-alloc device"
        store, clock, _ = build_store()
        slice_on(store, "n1", [dev("big", multi=True, capacity={"memory": "10Gi"})])
        alloc = Allocator(store, clock)
        rc1 = claim("c1", [req(capacity={"memory": "6Gi"})])
        rc2 = claim("c2", [req(capacity={"memory": "6Gi"})])
        store.create(rc1)
        store.create(rc2)
        r1, err1 = alloc.allocate_for_node("n1", [rc1])
        assert err1 is None
        alloc.commit_for_node("n1", r1)
        r2, err2 = alloc.allocate_for_node("n1", [rc2])
        assert r2 is None and err2 is not None


class TestPartitionableDepth:
    """allocator_test.go Describe("SharedCounters") :644-2352."""

    def test_zero_counter_capacity_rejects(self):
        # :921 "should reject allocation when counter has zero capacity"
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [dev("p1", consumes=[{"counterSet": "gpu-0", "counters": {"mig": "1"}}])],
            counters=[{"name": "gpu-0", "counters": {"mig": "0"}}],
        )
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_zero_consumption_zero_capacity_succeeds(self):
        # :945 "should succeed when both counter capacity and device
        # consumption are zero"
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [dev("p1", consumes=[{"counterSet": "gpu-0", "counters": {"mig": "0"}}])],
            counters=[{"name": "gpu-0", "counters": {"mig": "0"}}],
        )
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None and len(result.picks[rc.key()]) == 1

    def test_multiple_counter_sets_single_pool(self):
        # :1050 "should handle multiple counter sets in a single pool" — a
        # device draws from both sets; the second copy finds set-a drained
        store, clock, _ = build_store()
        consume = [
            {"counterSet": "set-a", "counters": {"slots": "1"}},
            {"counterSet": "set-b", "counters": {"mem": "1"}},
        ]
        slice_on(
            store,
            "n1",
            [dev("p1", consumes=consume), dev("p2", consumes=consume)],
            counters=[{"name": "set-a", "counters": {"slots": "1"}}, {"name": "set-b", "counters": {"mem": "4"}}],
        )
        alloc = Allocator(store, clock)
        rc1 = gpu_claim("c1")
        store.create(rc1)
        result, err = alloc.allocate_for_node("n1", [rc1])
        assert err is None
        rc2 = gpu_claim("c2", count=2)
        store.create(rc2)
        result, err = alloc.allocate_for_node("n1", [rc2])
        assert result is None and err is not None

    def test_backtrack_restores_counter_deductions(self):
        # :1110 "should backtrack counter deductions when DFS path fails
        # constraints" — p1 drains the budget then fails the constraint; the
        # deduction must unwind for p2+p3 to fit
        store, clock, _ = build_store()
        slice_on(
            store,
            "n1",
            [
                dev("p1", consumes=[{"counterSet": "gpu-0", "counters": {"slots": "2"}}]),
                dev("p2", model="h100", consumes=[{"counterSet": "gpu-0", "counters": {"slots": "1"}}]),
                dev("p3", model="h100", consumes=[{"counterSet": "gpu-0", "counters": {"slots": "1"}}]),
            ],
            counters=[{"name": "gpu-0", "counters": {"slots": "2"}}],
        )
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(count=2)], constraints=[{"matchAttribute": f"{DRIVER}/model"}])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["p2", "p3"]

    def test_all_mode_respects_counter_budget(self):
        # :1152 "should enforce all-mode counter budget"
        store, clock, _ = build_store()
        consume = [{"counterSet": "gpu-0", "counters": {"slots": "1"}}]
        slice_on(
            store,
            "n1",
            [dev("p1", consumes=consume), dev("p2", consumes=consume), dev("p3", consumes=consume)],
            counters=[{"name": "gpu-0", "counters": {"slots": "2"}}],
        )
        alloc = Allocator(store, clock)
        rc = claim("c1", [req(mode="All")])
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [rc])
        assert result is None and err is not None

    def test_template_counters_independent_per_instance_type(self):
        # :2067 "should evaluate template counters independently per instance
        # type" — each IT's pool has its own budget
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        per_it = {}
        for name in ("it-a", "it-b"):
            it = gpu_it(name, [dev("p1", consumes=[{"counterSet": "gpu-0", "counters": {"slots": "1"}}])])
            it.dynamic_resources_counters = [{"name": "gpu-0", "counters": {"slots": "1"}}]
            tracker = AllocationTracker(budgets=alloc.counter_budgets)
            result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
            assert err is None, f"{name}: {err}"
            per_it[name] = (tracker, result)
        assert len(per_it) == 2

    def test_template_budget_fresh_per_nodeclaim(self):
        # :2151 "should allow template counter allocation on a different NC
        # after exhausting budget on the first" — each candidate's tracker
        # materializes its own remaining copy
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        it = gpu_it("it-a", [dev("p1", consumes=[{"counterSet": "gpu-0", "counters": {"slots": "1"}}])])
        it.dynamic_resources_counters = [{"name": "gpu-0", "counters": {"slots": "1"}}]
        devices = alloc.template_devices(it)
        rc1, rc2 = gpu_claim("c1"), gpu_claim("c2")
        store.create(rc1)
        store.create(rc2)
        t1 = AllocationTracker(budgets=alloc.counter_budgets)
        r1, err1 = alloc.allocate("nc-1", devices, [rc1], t1)
        assert err1 is None
        alloc.commit("nc-1", r1, t1)
        # nc-1's tracker is drained...
        r1b, err1b = alloc.allocate("nc-1", devices, [gpu_claim("c3")], t1)
        assert err1b is not None
        # ...but a second NodeClaim starts from the full budget
        t2 = AllocationTracker(budgets=alloc.counter_budgets)
        r2, err2 = alloc.allocate("nc-2", devices, [rc2], t2)
        assert err2 is None


class TestAllocatedClaimHandling:
    """allocator_test.go Describe("In-cluster allocated claim handling")
    :3577-3711."""

    def test_allocated_claim_passes_through(self):
        # :3578 "should pass through claims with no nodeSelector"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        held = gpu_claim("held")
        held.status.allocation = {"devices": [{"driver": DRIVER, "pool": "pool-1", "device": "g1"}]}
        store.create(held)
        alloc = Allocator(store, clock)
        result, err = alloc.allocate_for_node("n1", [held])
        assert err is None and result.picks == {}

    def test_mix_of_allocated_and_unallocated(self):
        # :3681 "should handle a mix of allocated and unallocated claims"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1"), gpu("g2")])
        held = gpu_claim("held")
        held.status.allocation = {
            "nodeName": "n1",
            "devices": [{"driver": DRIVER, "pool": "pool-1", "device": "g1"}],
        }
        store.create(held)
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        result, err = alloc.allocate_for_node("n1", [held, rc])
        assert err is None
        assert picked_names(result, rc.key()) == ["g2"]
        assert held.key() not in result.picks

    def test_returns_early_when_all_allocated(self):
        # :3698 "should return early when all claims are already allocated"
        store, clock, _ = build_store()
        slice_on(store, "n1", [gpu("g1")])
        h1, h2 = gpu_claim("h1"), gpu_claim("h2")
        for h in (h1, h2):
            h.status.allocation = {"nodeName": "n1", "devices": []}
            store.create(h)
        alloc = Allocator(store, clock)
        result, err = alloc.allocate_for_node("n1", [h1, h2])
        assert err is None and result.picks == {}


class TestRequirementBounds:
    """allocator_test.go Describe("Topology requirement narrowing")
    :2911-3047, exercised through the req_bounds seeding the DFS."""

    def test_bound_rejects_incompatible_devices(self):
        # :3021 "should reject a device whose topology is incompatible with
        # accumulated requirements"
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        it = gpu_it("it-a", [zoned_gpu("gb", ["test-zone-b"]), zoned_gpu("ga", ["test-zone-a"])])
        bound = Requirements()
        bound.add(Requirement(wk.ZONE_LABEL_KEY, "In", ["test-zone-a"]))
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate(
            "nc-1", alloc.template_devices(it), [rc], tracker, req_bounds={rc.key(): bound}
        )
        assert err is None
        assert picked_names(result, rc.key()) == ["ga"]

    def test_bound_with_no_compatible_device_fails(self):
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1")
        store.create(rc)
        it = gpu_it("it-a", [zoned_gpu("gb", ["test-zone-b"])])
        bound = Requirements()
        bound.add(Requirement(wk.ZONE_LABEL_KEY, "In", ["test-zone-a"]))
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate(
            "nc-1", alloc.template_devices(it), [rc], tracker, req_bounds={rc.key(): bound}
        )
        assert result is None and err is not None

    def test_cross_claim_backtracking_revises_earlier_claim(self):
        # review finding: c1 can take g1(zone-a) or g2(zone-b); c2 only
        # matches g3(zone-b). A greedy per-claim pass picks g1 for c1 and
        # strands c2 — the claim-spanning DFS must backtrack into c1's
        # choices and land g2+g3
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        g3 = zoned_gpu("g3", ["test-zone-b"], model="h100")
        it = gpu_it("it-a", [zoned_gpu("g1", ["test-zone-a"]), zoned_gpu("g2", ["test-zone-b"]), g3])
        rc1 = gpu_claim("c1", model="a100")
        rc2 = gpu_claim("c2", model="h100")
        store.create(rc1)
        store.create(rc2)
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc1, rc2], tracker)
        assert err is None, err
        assert picked_names(result, rc1.key()) == ["g2"]
        assert picked_names(result, rc2.key()) == ["g3"]

    def test_collapsed_seed_fails_even_with_unconstrained_device(self):
        # review finding: with req_bounds pinning c1 to zone-a and c2 to
        # zone-b, c1's zone-a pick makes c2's seeded bound collapse — a
        # requirement-FREE candidate for c2 must not slip through the
        # collapsed bound unchecked
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        free = dev("free", model="h100")  # no node requirements
        it = gpu_it("it-a", [zoned_gpu("ga", ["test-zone-a"]), free])
        rc1 = gpu_claim("c1", model="a100")
        rc2 = gpu_claim("c2", model="h100")
        store.create(rc1)
        store.create(rc2)
        b1, b2 = Requirements(), Requirements()
        b1.add(Requirement(wk.ZONE_LABEL_KEY, "In", ["test-zone-a"]))
        b2.add(Requirement(wk.ZONE_LABEL_KEY, "In", ["test-zone-b"]))
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate(
            "nc-1", alloc.template_devices(it), [rc1, rc2], tracker,
            req_bounds={rc1.key(): b1, rc2.key(): b2},
        )
        assert result is None and err is not None

    def test_accumulated_requirements_backtrack(self):
        # :2988 "should backtrack and restore requirements when a zonal
        # device path fails" — the zone-b pair is explored and abandoned; the
        # zone-a pair (which needs the zone-b accumulation fully unwound)
        # succeeds
        store, clock, _ = build_store()
        alloc = Allocator(store, clock)
        rc = gpu_claim("c1", count=2)
        store.create(rc)
        # gb1 first in list: DFS enters zone-b, finds no partner with
        # capacity left (gb2 is exclusive-taken by design below), must unwind
        it = gpu_it(
            "it-a",
            [
                zoned_gpu("gb1", ["test-zone-b"]),
                zoned_gpu("ga1", ["test-zone-a"]),
                zoned_gpu("ga2", ["test-zone-a"]),
            ],
        )
        tracker = AllocationTracker(budgets=alloc.counter_budgets)
        result, err = alloc.allocate("nc-1", alloc.template_devices(it), [rc], tracker)
        assert err is None
        assert picked_names(result, rc.key()) == ["ga1", "ga2"]
