"""Shared object builders (reference: pkg/test fixtures)."""

from __future__ import annotations

import itertools

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.kube import (
    Affinity,
    Container,
    NodeAffinity,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.utils.resources import parse_resource_list

_seq = itertools.count(1)


def make_pod(
    name=None,
    ns="default",
    cpu="1",
    memory=None,
    labels=None,
    node_selector=None,
    node_name="",
    required_affinity=None,  # list of term lists
    preferred_affinity=None,  # list of (weight, term list)
    tolerations=None,
    tsc=None,  # list of TopologySpreadConstraint
    anti_affinity=None,  # list of PodAffinityTerm
    pod_affinity=None,
    priority=None,
    annotations=None,
    owner_refs=None,
    volumes=None,  # list of volume dicts (persistentVolumeClaim / ephemeral / ...)
):
    name = name or f"pod-{next(_seq)}"
    requests = {"cpu": cpu}
    if memory:
        requests["memory"] = memory
    affinity = None
    if required_affinity or preferred_affinity or anti_affinity or pod_affinity:
        affinity = Affinity(
            node_affinity=NodeAffinity(
                required=required_affinity or [],
                preferred=[PreferredSchedulingTerm(weight=w, preference=t) for w, t in (preferred_affinity or [])],
            )
            if (required_affinity or preferred_affinity)
            else None,
            pod_anti_affinity_required=anti_affinity or [],
            pod_affinity_required=pod_affinity or [],
        )
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}, annotations=annotations or {}),
        spec=PodSpec(
            containers=[Container(resources={"requests": parse_resource_list(requests)})],
            node_selector=node_selector or {},
            node_name=node_name,
            affinity=affinity,
            tolerations=tolerations or [],
            topology_spread_constraints=tsc or [],
            priority=priority,
            volumes=volumes or [],
        ),
    )
    if owner_refs:
        pod.metadata.owner_references = owner_refs
    return pod


def make_nodepool(name="default-pool", requirements=None, taints=None, limits=None, weight=0, labels=None, replicas=None):
    np = NodePool(metadata=ObjectMeta(name=name))
    np.spec.weight = weight
    np.spec.replicas = replicas
    np.spec.template.requirements = requirements or [
        {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_SPOT]},
    ]
    np.spec.template.taints = taints or []
    np.spec.template.labels = labels or {}
    if limits:
        np.spec.limits = parse_resource_list(limits)
    return np


def zone_spread(max_skew=1, selector=None, when="DoNotSchedule"):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=wk.ZONE_LABEL_KEY,
        when_unsatisfiable=when,
        label_selector=selector,
    )


def hostname_anti_affinity(selector):
    return PodAffinityTerm(label_selector=selector, topology_key=wk.HOSTNAME_LABEL_KEY)
