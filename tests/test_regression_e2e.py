"""Regression e2e scenarios through the full Environment, modeled on the
reference's test/suites/regression/ breadth (10 files / 3,735 LoC):
expiration (steady + under churn + budget-blocked), termination (drain
order, instance teardown, under churn), chaos (node kills during
consolidation, taint flapping during a drift roll, runaway guards), using
the round-3 Monitor / MetricsPoller / churn-watcher harness."""

import random

from helpers import hostname_anti_affinity, make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.testing import Monitor
from karpenter_tpu.testing.debug import ObjectChurnWatcher

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]
OD_ONLY = LINUX_AMD64 + [
    {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_ON_DEMAND]},
]


def make_env(np_kwargs=None, consolidate_after="30s", expire_after=None, budgets=None, **opts):
    env = Environment(options=Options(**opts))
    np_kwargs = dict(np_kwargs or {})
    np_kwargs.setdefault("requirements", OD_ONLY)
    np = make_nodepool(**np_kwargs)
    np.spec.disruption.consolidate_after = consolidate_after
    if expire_after is not None:
        np.spec.template.expire_after = expire_after
    if budgets is not None:
        np.spec.disruption.budgets = budgets
    env.store.create(np)
    return env, Monitor(env.store, env.cluster)


def run(env, rounds=10, step=15.0):
    for _ in range(rounds):
        env.clock.step(step)
        env.tick(provision_force=True)


class TestExpirationRegression:
    def test_node_expires_and_pods_reschedule(self):
        # expiration_test.go "should expire the node after the expiration is
        # reached" + "replace expired node ... and schedule all pods"
        env, monitor = make_env(expire_after="120s")
        for i in range(8):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle()
        first_nodes = {n.metadata.name for n in env.store.list("Node")}
        assert first_nodes
        env.clock.step(150.0)  # beyond expireAfter
        run(env, rounds=20, step=10.0)
        env.settle(rounds=8)
        after = {n.metadata.name for n in env.store.list("Node")}
        assert not (after & first_nodes), "expired nodes must be replaced"
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 8

    def test_expiration_under_churn(self):
        # churn while the fleet rolls on expiry: pods added and removed each
        # round; everything must converge bound with no stranded pods
        rng = random.Random(7)
        env, monitor = make_env(expire_after="200s")
        live = []
        for i in range(10):
            name = f"base-{i}"
            env.store.create(make_pod(cpu="1", name=name))
            live.append(name)
        env.settle()
        for round_ in range(12):
            env.clock.step(30.0)
            if rng.random() < 0.7:
                name = f"churn-{round_}"
                env.store.create(make_pod(cpu="1", name=name))
                live.append(name)
            elif live:
                victim = live.pop(rng.randrange(len(live)))
                env.store.delete("Pod", victim)
            env.tick(provision_force=True)
        env.settle(rounds=15)
        assert monitor.pending_pod_count() == 0, "churned pods stranded during expiry roll"
        assert monitor.running_pod_count() == len(live)

    def test_expiration_is_absolute_despite_blocking_budget(self):
        # expiration is ABSOLUTE (expiration.go): a fully blocking disruption
        # budget holds emptiness/consolidation but NOT the expiry of claims
        env, monitor = make_env(expire_after="60s", budgets=[Budget(nodes="0")])
        for i in range(4):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle()
        nodes_before = {n.metadata.name for n in env.store.list("Node")}
        env.clock.step(90.0)
        run(env, rounds=12, step=10.0)
        env.settle(rounds=10)
        after = {n.metadata.name for n in env.store.list("Node")}
        assert not (after & nodes_before), "expiration must replace nodes regardless of budgets"
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 4


class TestTerminationRegression:
    def test_terminates_node_and_instance_on_deletion(self):
        # termination_test.go "should terminate the node and the instance on
        # deletion": deleting the NodeClaim tears down node + cloud instance
        env, monitor = make_env()
        env.store.create(make_pod(cpu="1", name="p0"))
        env.settle()
        nc = env.store.list("NodeClaim")[0]
        env.store.delete("NodeClaim", nc.metadata.name)
        env.settle(rounds=12)
        assert env.store.count("NodeClaim") >= 1  # replacement provisioned
        assert all(c.metadata.name != nc.metadata.name for c in env.store.list("NodeClaim"))
        assert monitor.pending_pod_count() == 0

    def test_drains_pods_in_priority_order(self):
        # termination_test.go "should drain pods on a node in order": lower
        # priority groups unbind before higher ones (eviction resets the pod
        # to Pending, as a ReplicaSet would recreate it)
        env, monitor = make_env()
        env.store.create(make_pod(cpu="500m", name="low", priority=0))
        env.store.create(make_pod(cpu="500m", name="high", priority=1000))
        env.settle()
        node = env.store.list("Node")[0]
        env.store.delete("Node", node.metadata.name)
        env.termination.reconcile()
        low, high = env.store.get("Pod", "low"), env.store.get("Pod", "high")
        assert low.spec.node_name == "", "low priority evicts in the first pass"
        assert high.spec.node_name != "", "high priority drains in a later pass"
        env.termination.reconcile()
        assert env.store.get("Pod", "high").spec.node_name == ""
        # the control plane then reschedules both
        env.settle(rounds=12)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 2

    def test_termination_under_churn(self):
        # nodes deleted while new pods keep arriving: the control plane must
        # keep every pod schedulable and tear down cleanly
        rng = random.Random(11)
        env, monitor = make_env()
        for i in range(12):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle()
        total = 12
        for round_ in range(8):
            nodes = env.store.list("Node")
            if nodes and rng.random() < 0.6:
                victim = rng.choice(nodes)
                env.store.delete("Node", victim.metadata.name)
            env.store.create(make_pod(cpu="500m", name=f"new-{round_}"))
            total += 1
            for _ in range(5):
                env.clock.step(6.0)
                env.tick(provision_force=True)
        env.settle(rounds=20)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == total

    def test_do_not_disrupt_pod_blocks_drain_until_released(self):
        # termination_test.go do-not-disrupt family: the annotation blocks
        # eviction during drain (the node lingers, finalizer held); removing
        # the annotation releases the drain and the pod reschedules
        env, monitor = make_env()
        env.store.create(
            make_pod(cpu="1", name="precious", annotations={wk.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"})
        )
        env.settle()
        node = env.store.list("Node")[0]
        env.store.delete("Node", node.metadata.name)
        run(env, rounds=6, step=10.0)
        # drain blocked: pod still bound to the deleting node
        p = env.store.get("Pod", "precious")
        assert p.spec.node_name == node.metadata.name, "do-not-disrupt must hold the drain"

        def release(x):
            x.metadata.annotations.pop(wk.DO_NOT_DISRUPT_ANNOTATION_KEY, None)

        env.store.patch("Pod", "precious", release)
        env.settle(rounds=20)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 1
        assert env.store.try_get("Node", node.metadata.name) is None


class TestChaosRegression:
    def test_node_kills_during_consolidation(self):
        # VERDICT r3 #9: random node kills while consolidation is actively
        # shrinking the fleet; must converge with all pods bound
        rng = random.Random(3)
        env, monitor = make_env(budgets=[Budget(nodes="100%")])
        sel = {"matchLabels": {"app": "x"}}
        for i in range(10):
            env.store.create(
                make_pod(cpu="500m", name=f"s{i}", labels={"app": "x"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        env.settle()
        assert env.store.count("Node") == 10
        # free the anti-affinity so consolidation wants to shrink, then kill
        # nodes mid-consolidation
        for i in range(10):
            env.store.delete("Pod", f"s{i}")
        for i in range(10):
            env.store.create(make_pod(cpu="500m", name=f"f{i}"))
        for round_ in range(10):
            env.clock.step(20.0)
            env.tick(provision_force=True)
            nodes = env.store.list("Node")
            if nodes and round_ % 3 == 1:
                victim = rng.choice(nodes)
                env.store.delete("Node", victim.metadata.name, grace=False)
                env.cluster.delete_node(victim.metadata.name)
        # quiet period past the consolidated-state TTL (cluster.go:599-610,
        # 5 min) so the controller re-evaluates after the churn settles
        run(env, rounds=25, step=15.0)
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 10
        assert env.store.count("Node") < 10, "consolidation must still shrink the fleet"

    def test_taint_flapping_during_drift_roll(self):
        # VERDICT r3 #9: a user taints/untaints nodes while a drift roll
        # replaces the fleet; the roll must complete without runaway
        env, monitor = make_env()
        for i in range(6):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle()
        old_nodes = {n.metadata.name for n in env.store.list("Node")}

        # drift the pool: change the template so the hash moves
        np = env.store.list("NodePool")[0]

        def relabel(p):
            p.spec.template.labels["rollout"] = "v2"

        env.store.patch("NodePool", np.metadata.name, relabel)
        from karpenter_tpu.scheduling.taints import Taint

        max_nodes = 0
        for round_ in range(14):
            env.clock.step(15.0)
            # flap a taint on some surviving node every other round
            nodes = env.store.list("Node")
            if nodes and round_ % 2 == 0:
                name = nodes[round_ % len(nodes)].metadata.name

                def flap(n):
                    has = [t for t in n.spec.taints if t.key == "flap"]
                    if has:
                        n.spec.taints = [t for t in n.spec.taints if t.key != "flap"]
                    else:
                        n.spec.taints.append(Taint(key="flap", value="y", effect="NoSchedule"))

                env.store.patch("Node", name, flap)
            env.tick(provision_force=True)
            max_nodes = max(max_nodes, env.store.count("Node"))
        # clear any leftover flap taints, then converge
        for n in env.store.list("Node"):
            def clear(x):
                x.spec.taints = [t for t in x.spec.taints if t.key != "flap"]

            env.store.patch("Node", n.metadata.name, clear)
        env.settle(rounds=25)
        new_nodes = {n.metadata.name for n in env.store.list("Node")}
        assert not (new_nodes & old_nodes), "drift roll must replace the old fleet"
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == 6
        assert max_nodes <= len(old_nodes) * 3 + 3, "taint flapping caused runaway"

    def test_no_runaway_scaleup_with_consolidation_enabled(self):
        # chaos_test.go "should not produce a runaway scale-up when
        # consolidation is enabled": watch object churn during steady state
        env, monitor = make_env()
        for i in range(20):
            env.store.create(make_pod(cpu="1", name=f"p{i}"))
        env.settle()
        baseline = env.store.count("Node")
        watcher = ObjectChurnWatcher(env.store, kinds=("NodeClaim",), clock=env.clock)
        run(env, rounds=20, step=10.0)
        watcher.close()
        assert env.store.count("Node") <= baseline + 1
        churn = [e for e in watcher.events if e.kind == "NodeClaim" and e.event == "ADDED"]
        assert len(churn) <= 2, f"steady state churned {len(churn)} nodeclaims"
        assert monitor.running_pod_count() == 20

    def test_no_runaway_scaleup_with_emptiness(self):
        # chaos_test.go emptiness flavor: deleting pods empties nodes which
        # must terminate once, not oscillate create/delete
        env, monitor = make_env()
        sel = {"matchLabels": {"app": "e"}}
        for i in range(8):
            env.store.create(
                make_pod(cpu="500m", name=f"e{i}", labels={"app": "e"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        env.settle()
        watcher = ObjectChurnWatcher(env.store, kinds=("NodeClaim",), clock=env.clock)
        for i in range(8):
            env.store.delete("Pod", f"e{i}")
        run(env, rounds=20, step=10.0)
        watcher.close()
        assert env.store.count("Node") == 0
        creates = [e for e in watcher.events if e.kind == "NodeClaim" and e.event == "ADDED"]
        assert len(creates) == 0, "emptiness teardown must not re-create nodes"
