"""Offering capacity/overhead overrides: offerings can carry their own
capacity or overhead deltas, grouping an instance type's offerings into
allocatable sets (reference types.go:195-257 AllocatableOfferings +
nodeclaim.go:624-640 fits; suite_test.go:5521-5601 "Offering Overrides").
Covers the grouping math, the host scheduler path, and the tensor path."""

from helpers import make_nodepool, make_pod
from test_scheduler import LINUX_AMD64, build_env, make_scheduler
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.cloudprovider.types import InstanceType, InstanceTypeOverhead, Offering
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils.quantity import Quantity
from karpenter_tpu.utils.resources import parse_resource_list

EXT = "test.com/extended-slots"


def ext_pod(name=None, ext="1", cpu="100m"):
    """Pod requesting the override-only extended resource."""
    p = make_pod(name=name, cpu=cpu)
    p.spec.containers[0].resources["requests"][EXT] = Quantity.parse(ext)
    return p


def _requests_of(p):
    return p.spec.containers[0].resources.get("requests", {})


def _offering(zone="test-zone-a", ct=wk.CAPACITY_TYPE_ON_DEMAND, price=1.0,
              available=True, capacity_override=None, overhead_override=None):
    return Offering(
        requirements=Requirements.from_labels({
            wk.CAPACITY_TYPE_LABEL_KEY: ct,
            wk.ZONE_LABEL_KEY: zone,
        }),
        price=price,
        available=available,
        capacity_override=capacity_override,
        overhead_override=overhead_override,
    )


def _it(name, offerings, cpu="4", memory="8Gi", capacity_extra=None):
    cap = {"cpu": cpu, "memory": memory, "pods": "110"}
    cap.update(capacity_extra or {})
    return InstanceType(
        name=name,
        requirements=Requirements.from_labels({
            wk.INSTANCE_TYPE_LABEL_KEY: name,
            wk.ARCH_LABEL_KEY: "amd64",
            wk.OS_LABEL_KEY: "linux",
        }),
        offerings=offerings,
        capacity=parse_resource_list(cap),
    )


def override_capable(name="override-capable", available=True):
    """Instance type with base offerings plus override clones declaring the
    extended resource and an extra 1Gi memory system-reserve
    (suite_test.go:5525-5543)."""
    base = [_offering(zone=z) for z in ("test-zone-a", "test-zone-b")]
    overrides = [
        _offering(
            zone=z,
            available=available,
            capacity_override=parse_resource_list({EXT: "4"}),
            overhead_override=InstanceTypeOverhead(
                system_reserved=parse_resource_list({"memory": "1Gi"})
            ),
        )
        for z in ("test-zone-a", "test-zone-b")
    ]
    return _it(name, base + overrides)


class TestAllocatableGrouping:
    def test_base_group_first_and_override_grouped(self):
        it = override_capable()
        groups = it.allocatable_offerings_list()
        # base group + one override group (identical override content merges)
        assert len(groups) == 2
        base_alloc, base_offs = groups[0]
        ov_alloc, ov_offs = groups[1]
        assert len(base_offs) == 2 and len(ov_offs) == 2
        assert EXT not in base_alloc
        assert ov_alloc[EXT] == Quantity.parse("4")
        # overhead override subtracts 1Gi memory from the override group only
        assert ov_alloc["memory"].milli == base_alloc["memory"].milli - Quantity.parse("1Gi").milli

    def test_unavailable_offerings_excluded_from_groups(self):
        it = override_capable(available=False)
        groups = it.allocatable_offerings_list()
        assert len(groups) == 1  # only the base group remains
        assert len(groups[0][1]) == 2

    def test_no_override_fast_path(self):
        it = _it("plain", [_offering()])
        groups = it.allocatable_offerings_list()
        assert len(groups) == 1
        assert groups[0][0] == it.allocatable()

    def test_distinct_override_contents_form_distinct_groups(self):
        offs = [
            _offering(),
            _offering(capacity_override=parse_resource_list({EXT: "4"})),
            _offering(capacity_override=parse_resource_list({EXT: "8"})),
            _offering(capacity_override=parse_resource_list({EXT: "4"})),
        ]
        it = _it("multi", offs)
        groups = it.allocatable_offerings_list()
        assert len(groups) == 3
        assert len(groups[1][1]) == 2  # the two EXT=4 offerings merged

    def test_capacity_overlay_invalidates_group_cache(self):
        it = override_capable()
        before = it.allocatable_offerings_list()[0][0]["cpu"]
        it.apply_capacity_overlay(parse_resource_list({"cpu": "16"}))
        after = it.allocatable_offerings_list()[0][0]["cpu"]
        assert after.milli > before.milli


class TestGroupCacheLiveAvailability:
    def test_in_place_availability_flip_rebuilds_groups(self):
        # tests/overlays flip o.available in place; the cached groups must
        # follow the live availability like every other call site does
        it = override_capable()
        assert len(it.allocatable_offerings_list()) == 2
        for o in it.offerings:
            if o.capacity_override:
                o.available = False
        assert len(it.allocatable_offerings_list()) == 1


class TestDownstreamConsumers:
    def test_price_overlay_copy_preserves_overrides(self):
        # nodeoverlay copy-on-write must not drop an offering's overrides —
        # that would silently move the copy into the base allocatable group
        from karpenter_tpu.apis.nodeoverlay import NodeOverlay, NodeOverlaySpec
        from karpenter_tpu.controllers.nodeoverlay.store import InternalInstanceTypeStore
        from karpenter_tpu.kube import ObjectMeta

        it = override_capable()
        store = InternalInstanceTypeStore()
        store.evaluated_node_pools.add("default-pool")
        ov = NodeOverlay(metadata=ObjectMeta(name="p"), spec=NodeOverlaySpec(price_adjustment="+10%"))
        store.update_instance_type_offering("default-pool", it.name, ov, it.offerings)
        out = store.apply("default-pool", it)
        assert out is not it
        groups = out.allocatable_offerings_list()
        assert len(groups) == 2
        assert all(o.capacity_override for o in groups[1][1])
        assert all(o.price_overlaid for o in out.offerings)

    def test_kwok_launch_stamps_override_allocatable(self):
        # a node launched via an override offering must carry the override
        # group's capacity/allocatable or pods packed against it cannot bind
        from karpenter_tpu.cloudprovider.kwok import KWOKCloudProvider
        from karpenter_tpu.kube import Store

        only_override = _it(
            "ov-only",
            [_offering(
                capacity_override=parse_resource_list({EXT: "4"}),
                overhead_override=InstanceTypeOverhead(
                    system_reserved=parse_resource_list({"memory": "1Gi"})
                ),
            )],
        )
        store = Store()
        cp = KWOKCloudProvider(store, instance_types=[only_override])
        from karpenter_tpu.apis.nodeclaim import NodeClaim

        nc = NodeClaim()
        nc.metadata.name = "nc-ov"
        nc.spec.requirements = [
            {"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "In", "values": ["ov-only"]},
        ]
        node = cp._to_node(nc)  # noqa: SLF001 — launch conversion under test
        assert node.status.capacity[EXT] == Quantity.parse("4")
        assert node.status.allocatable[EXT] == Quantity.parse("4")
        expected_mem = only_override.allocatable()["memory"].milli - Quantity.parse("1Gi").milli
        assert node.status.allocatable["memory"].milli == expected_mem


class TestFullControlPlane:
    def test_override_pod_provisions_launches_and_binds(self):
        # the whole slice: provisioner packs the pod against the override
        # group, the KWOK launch seeds the node's vectors with the claim's
        # requests and the chosen offering's overrides
        # (kwok/cloudprovider.go:231-232 lo.Assign semantics), the pod binds
        # on the FIRST claim — no runaway relaunches
        from karpenter_tpu.operator import Environment
        from karpenter_tpu.operator.options import Options

        env = Environment(options=Options(), instance_types=[override_capable()])
        env.store.create(make_nodepool())
        env.store.create(ext_pod(name="want-ext"))
        env.settle(rounds=6)
        cur = env.store.get("Pod", "want-ext", namespace="default")
        assert cur.spec.node_name, f"{env.store.count('NodeClaim')} claims, pod unbound"
        node = env.store.get("Node", cur.spec.node_name)
        assert node.status.allocatable.get(EXT) is not None
        assert env.store.count("NodeClaim") == 1


class TestComputeAllocatable:
    def test_hugepages_reduce_memory(self):
        # types.go:283-294 — hugepage reservations come out of memory
        it = _it("huge", [_offering()], memory="8Gi", capacity_extra={"hugepages-2Mi": "2Gi"})
        alloc = it.allocatable()
        assert alloc["memory"].milli == Quantity.parse("6Gi").milli

    def test_overhead_override_merges_not_replaces(self):
        it = _it("ovh", [_offering()])
        out = it.compute_allocatable(
            overhead_override=InstanceTypeOverhead(system_reserved=parse_resource_list({"memory": "1Gi"}))
        )
        # cpu untouched, memory down 1Gi
        assert out["cpu"] == it.allocatable()["cpu"]
        assert out["memory"].milli == it.allocatable()["memory"].milli - Quantity.parse("1Gi").milli


class TestHostSchedulerPath:
    def test_only_override_capable_selected_for_override_resource(self):
        # suite_test.go:5522 — pod requesting the extended resource must land
        # on the override-capable type and exclude the normal one
        types = [override_capable(), _it("normal", [_offering(), _offering(zone="test-zone-b")])]
        env = build_env(node_pools=[make_nodepool(requirements=LINUX_AMD64)], types=types)
        s = make_scheduler(*env)
        results = s.solve([ext_pod()])
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1
        names = {it.name for it in results.new_node_claims[0].instance_type_options}
        assert "override-capable" in names
        assert "normal" not in names

    def test_unavailable_override_offerings_reject_instance_type(self):
        # suite_test.go:5566 — the override allocatable fits but all its
        # offerings are unavailable: no NodeClaim may launch
        types = [override_capable(available=False)]
        env = build_env(node_pools=[make_nodepool(requirements=LINUX_AMD64)], types=types)
        s = make_scheduler(*env)
        results = s.solve([ext_pod()])
        assert not results.all_pods_scheduled()
        assert len(results.new_node_claims) == 0

    def test_base_workload_unaffected_by_override_groups(self):
        types = [override_capable()]
        env = build_env(node_pools=[make_nodepool(requirements=LINUX_AMD64)], types=types)
        s = make_scheduler(*env)
        results = s.solve([make_pod(cpu="1")])
        assert results.all_pods_scheduled()

    def test_shrinking_override_rejected_when_only_override_compatible(self):
        # an IT whose ONLY spot offerings are override ones with a smaller
        # allocatable must NOT pass on the base group's headroom
        small_override = [
            _offering(ct=wk.CAPACITY_TYPE_ON_DEMAND),  # base: on-demand only
            _offering(
                ct=wk.CAPACITY_TYPE_SPOT,
                overhead_override=InstanceTypeOverhead(
                    system_reserved=parse_resource_list({"memory": "7Gi"})
                ),
            ),
        ]
        types = [_it("shrinks-on-spot", small_override, memory="8Gi")]
        np_spot = make_nodepool(requirements=LINUX_AMD64 + [
            {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [wk.CAPACITY_TYPE_SPOT]},
        ])
        env = build_env(node_pools=[np_spot], types=types)
        s = make_scheduler(*env)
        # 4Gi fits base (8Gi) but not the spot override group (1Gi)
        results = s.solve([make_pod(cpu="100m", memory="4Gi")])
        assert not results.all_pods_scheduled()


class TestTensorPath:
    def _solve_tpu(self, types, pods, node_pools=None):
        from karpenter_tpu.solver.snapshot import SolverSnapshot
        from karpenter_tpu.solver.tpu import TPUSolver

        env = build_env(node_pools=node_pools or [make_nodepool(requirements=LINUX_AMD64)], types=types)
        store, clock, cluster, pools, _ = env
        snap = SolverSnapshot(
            store=store,
            cluster=cluster,
            node_pools=pools,
            instance_types={np.metadata.name: types for np in pools},
            state_nodes=cluster.nodes(),
            daemonset_pods=[],
            pods=pods,
            clock=clock,
        )
        solver = TPUSolver(force=True)
        return solver.solve(snap)

    def test_tensor_rows_use_override_allocatable(self):
        types = [override_capable(), _it("normal", [_offering(), _offering(zone="test-zone-b")])]
        results = self._solve_tpu(types, [ext_pod()])
        assert results.all_pods_scheduled()
        assert len(results.new_node_claims) == 1
        names = {it.name for it in results.new_node_claims[0].instance_type_options}
        assert "override-capable" in names
        assert "normal" not in names

    def test_tensor_unavailable_override_no_launch(self):
        types = [override_capable(available=False)]
        results = self._solve_tpu(types, [ext_pod()])
        assert not results.all_pods_scheduled()

    def test_tensor_parity_with_host_for_mixed_workload(self):
        types = [override_capable(), _it("normal", [_offering(), _offering(zone="test-zone-b")])]
        pods = [ext_pod(name=f"p{i}") for i in range(3)]
        pods += [make_pod(name=f"q{i}", cpu="500m") for i in range(3)]
        tpu_results = self._solve_tpu(types, pods)
        env = build_env(node_pools=[make_nodepool(requirements=LINUX_AMD64)], types=types)
        host_results = make_scheduler(*env).solve(pods)
        assert tpu_results.all_pods_scheduled() == host_results.all_pods_scheduled() is True
        # every claim holding an EXT pod launches only override-capable types
        for res in (tpu_results, host_results):
            for nc in res.new_node_claims:
                if any(EXT in _requests_of(p) for p in nc.pods):
                    assert all(it.name == "override-capable" for it in nc.instance_type_options)
