"""shardfleet (karpenter_tpu/serving/shard.py): horizontal multi-process
fleet sharding with warm-cache scale-out.

Pins the subsystem's contracts:
- ring stability: adding a shard moves ONLY tenants onto the new shard (and
  no more than ~T/N of them); removing a shard moves EXACTLY its orphans;
  the assignment is a pure bit-stable function of the shard-id set,
  identical across processes regardless of PYTHONHASHSEED (blake2b, never
  the builtin hash());
- bounded shard labels: shard_label mirrors tenant_label's cap/overflow/
  collision-disambiguation contract;
- tenant-filtered replay: ChurnSpec.from_event_log(tenant=...) replays a
  NAMED subset of a tenant-stamped log (untagged ops always replay), and a
  fleet-attached harness stamps every recorded op with its tenant — the
  shard re-homing substrate;
- race-safe compile-cache claim: two processes configuring the same fresh
  KARPENTER_SOLVER_COMPILE_CACHE dir both succeed (first-writer wins, the
  loser adopts), and an unwritable dir degrades to uncached, never broken;
- the router end-to-end: N worker processes replay the same recorded log
  deterministically (bit-identical placement digests ACROSS processes),
  aggregation surfaces merge shard-stamped, a killed shard quarantines via
  its breaker, and its tenants re-home with bit-identical placements —
  both onto a surviving shard and onto a respawned one.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import pytest

from test_churn_loop import small_spec
from karpenter_tpu.serving import ChurnHarness, ChurnSpec
from karpenter_tpu.serving.shard import (
    SHARD_LABEL_CAP,
    ShardRing,
    ShardRouter,
    reset_shard_labels,
    shard_label,
)


@pytest.fixture(autouse=True)
def _fresh_shard_labels():
    reset_shard_labels()
    yield
    reset_shard_labels()


def ring_assignments(n_shards: int, tenants) -> dict:
    return ShardRing([f"shard-{i}" for i in range(n_shards)]).assignments(tenants)


class TestShardRing:
    T = 200

    def test_add_moves_only_onto_the_new_shard(self):
        """Scale-out movement bound: growing N -> N+1 re-homes only tenants
        whose successor became the NEW shard — every moved tenant lands on
        it, and the moved count stays near T/(N+1)."""
        tenants = [f"tenant-{i}" for i in range(self.T)]
        before = ring_assignments(4, tenants)
        after = ring_assignments(5, tenants)
        moved = [t for t in tenants if before[t] != after[t]]
        assert moved, "a new shard must take some tenants"
        assert all(after[t] == "shard-4" for t in moved)
        # ceil(T/N) + slack: vnode placement is uneven (64 replicas), but
        # nowhere near a full reshuffle
        bound = math.ceil(self.T / 5) + math.ceil(self.T / 10)
        assert len(moved) <= bound, f"moved {len(moved)} > {bound}"

    def test_remove_moves_exactly_the_orphans(self):
        """Shard death re-homes EXACTLY the dead shard's tenants; every
        surviving tenant keeps its assignment bit-for-bit."""
        tenants = [f"tenant-{i}" for i in range(self.T)]
        before = ring_assignments(5, tenants)
        ring = ShardRing([f"shard-{i}" for i in range(5)])
        ring.remove("shard-2")
        after = ring.assignments(tenants)
        moved = {t for t in tenants if before[t] != after[t]}
        orphans = {t for t in tenants if before[t] == "shard-2"}
        assert moved == orphans
        assert all(after[t] != "shard-2" for t in orphans)

    def test_bit_stable_across_rebuilds_and_insertion_order(self):
        """The assignment is a pure function of the shard-id SET: a rebuilt
        ring (router restart) and a permuted insertion order agree on every
        tenant."""
        tenants = [f"tenant-{i}" for i in range(self.T)]
        a = ShardRing(["shard-0", "shard-1", "shard-2"]).assignments(tenants)
        b = ShardRing(["shard-2", "shard-0", "shard-1"]).assignments(tenants)
        assert a == b
        # add/remove round-trip restores the original assignment exactly
        ring = ShardRing(["shard-0", "shard-1", "shard-2"])
        ring.add("shard-3")
        ring.remove("shard-3")
        assert ring.assignments(tenants) == a

    def test_seed_independent_across_processes(self):
        """A subprocess with a DIFFERENT PYTHONHASHSEED computes the same
        assignments — the ring must never lean on the builtin hash()."""
        tenants = [f"tenant-{i}" for i in range(50)]
        local = ring_assignments(3, tenants)
        code = (
            "import json, sys\n"
            "from karpenter_tpu.serving.shard import ShardRing\n"
            "ring = ShardRing(['shard-0', 'shard-1', 'shard-2'])\n"
            "tenants = [f'tenant-{i}' for i in range(50)]\n"
            "print(json.dumps(ring.assignments(tenants)))\n"
        )
        env = dict(os.environ, PYTHONHASHSEED="12345", JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True, timeout=60
        )
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout.strip()) == local

    def test_empty_ring_raises(self):
        with pytest.raises(ValueError):
            ShardRing().assign("tenant-0")


class TestShardLabel:
    def test_cap_and_overflow(self):
        for i in range(SHARD_LABEL_CAP):
            assert shard_label(f"shard-{i}") == f"shard-{i}"
        assert shard_label("one-more") == "overflow"
        # established assignments stay sticky past the cap
        assert shard_label("shard-0") == "shard-0"

    def test_sanitize_collisions_never_merge_shards(self):
        a = shard_label("zone/a")
        b = shard_label("zone:a")
        assert a != b
        assert shard_label("zone/a") == a and shard_label("zone:a") == b

    def test_empty_id_gets_default(self):
        assert shard_label("") == "default"


class TestTenantFilteredReplay:
    def _write_log(self, path: str) -> None:
        ops = [
            {"op": "header", "n_base_pods": 3, "n_types": 2, "arrivals": 1, "cancels": 0,
             "departures": 1, "bind_every": 1, "seed": 7, "batch_idle_seconds": 0.01},
            {"op": "base", "t": 0.0},  # untagged: the shared pacing skeleton
            {"op": "arrive", "t": 0.1, "tenant": "alpha"},
            {"op": "arrive", "t": 0.2, "tenant": "beta"},
            {"op": "depart", "t": 0.3, "tenant": "alpha"},
            {"op": "mark", "t": 0.4},
        ]
        with open(path, "w") as f:
            for op in ops:
                f.write(json.dumps(op) + "\n")

    def test_named_subset_filter(self, tmp_path):
        """tenant= replays only that tenant's tagged ops plus every
        untagged op — the shard re-homing contract."""
        log = str(tmp_path / "fleet.jsonl")
        self._write_log(log)
        spec = ChurnSpec.from_event_log(log, tenant="alpha")
        kinds = [(op["op"], op.get("tenant")) for op in spec.replay_events]
        assert kinds == [("base", None), ("arrive", "alpha"), ("depart", "alpha"), ("mark", None)]
        # header scale fields round-trip through the filter
        assert spec.n_base_pods == 3 and spec.seed == 7

    def test_collection_and_unfiltered(self, tmp_path):
        log = str(tmp_path / "fleet.jsonl")
        self._write_log(log)
        both = ChurnSpec.from_event_log(log, tenant={"alpha", "beta"})
        assert len(both.replay_events) == 5
        unfiltered = ChurnSpec.from_event_log(log)
        assert len(unfiltered.replay_events) == 5
        nobody = ChurnSpec.from_event_log(log, tenant="gamma")
        assert [op["op"] for op in nobody.replay_events] == ["base", "mark"]

    def test_attached_harness_stamps_tenant(self, tmp_path):
        """A fleet-attached recording tags every op with the owning tenant
        id, so a merged log can later replay a named subset."""
        h = ChurnHarness(small_spec(record_path=str(tmp_path / "rec.jsonl")))
        assert h._event_log == []
        h._log(op="solve")
        assert "tenant" not in h._event_log[-1]  # standalone: untagged
        h._tenant_id = "tenant-7"  # what attach() sets
        h._log(op="solve")
        assert h._event_log[-1]["tenant"] == "tenant-7"


class TestCompileCacheRace:
    def test_two_process_first_writer_wins(self, tmp_path):
        """Two processes racing configure_compile_cache on the same FRESH
        dir both succeed: one wins the O_EXCL stamp claim, the loser adopts
        the dir — the shard scale-out boot path."""
        cache = str(tmp_path / "shared-cache")
        code = (
            "import os\n"
            "from karpenter_tpu.solver.tpu import configure_compile_cache\n"
            "path = configure_compile_cache()\n"
            "assert path == os.environ['KARPENTER_SOLVER_COMPILE_CACHE'], path\n"
            "print('CLAIMED', path)\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", KARPENTER_SOLVER_COMPILE_CACHE=cache)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", code],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for _ in range(2)
        ]
        outs = [p.communicate(timeout=120) for p in procs]
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, err
            assert "CLAIMED" in out
        assert os.path.exists(os.path.join(cache, ".karpenter-cache-stamp"))

    def test_unwritable_dir_degrades_to_uncached(self, tmp_path, monkeypatch):
        from karpenter_tpu.solver.tpu import configure_compile_cache

        blocker = tmp_path / "not-a-dir"
        blocker.write_text("")
        monkeypatch.setenv("KARPENTER_SOLVER_COMPILE_CACHE", str(blocker / "cache"))
        assert configure_compile_cache() is None


class TestShardRouterEndToEnd:
    """The full multi-process path: spawn, replay, aggregate, kill,
    re-home. Worker processes run the ffd backend (jax-free), so the test
    exercises every router mechanism without paying XLA compiles; the
    tpu-backend warm-cache gates live in bench fleet_sharded."""

    def test_router_replay_aggregation_and_rehoming(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_DOUBLEBUF", "0")
        log = str(tmp_path / "churn.jsonl")
        rec = ChurnHarness(
            small_spec(
                n_base_pods=60, n_types=6, arrivals=12, cancels=8, departures=12,
                iterations=2, warmup_cycles=1, record_path=log,
            )
        )
        rec.run()
        rec.close()

        router = ShardRouter(
            n_shards=2, solver="ffd", breaker_failures=1, breaker_backoff_seconds=0.05
        )
        try:
            assert router.spawn() == ["shard-0", "shard-1"]
            tenants = ["t0", "t1", "t2"]
            for tid in tenants:
                sid = router.add_tenant(tid, log_path=log)
                assert sid == router.assign(tid)
            assert router.ready()

            results = router.run_all()
            assert all(r.get("ok") for r in results.values()), results
            digests = {t: router._tenants[t]["digest"] for t in tenants}
            assert all(digests.values())
            # the same log replays to BIT-IDENTICAL placements in every
            # worker process — the cross-process determinism pin
            assert len(set(digests.values())) == 1, digests

            # aggregation: shard-stamped tenant rows, the merged exposition,
            # and the ?tenant=-proxied solve dump
            rows = router.debug_tenants()
            owners = router.tenants()
            for tid in tenants:
                assert rows[tid]["shard"] == owners[tid]
            merged = router.merged_metrics()
            assert 'shard="' in merged
            assert "karpenter_solver_fleet_shards 2" in merged
            assert merged.count("# TYPE karpenter_solver_fleet_shards") == 1
            solves = json.loads(router.debug_solves(tenants[0]))
            assert isinstance(solves, dict)
            shards_dump = router.debug_shards()
            assert set(shards_dump) == {"shard-0", "shard-1"}
            assert all(row["alive"] for row in shards_dump.values())

            # shard death: the breaker quarantines, readiness drops, and the
            # orphans re-home onto the survivor with matching digests
            victim = owners[tenants[0]]
            survivor = "shard-1" if victim == "shard-0" else "shard-0"
            router._handle(victim).kill()
            states = router.check_shards()
            assert states[victim] == "quarantined"
            assert not router.ready()
            rehomed = router.rehome_tenants(victim)
            orphans = [t for t, s in owners.items() if s == victim]
            assert sorted(rehomed) == sorted(orphans)
            for tid, row in rehomed.items():
                assert row["shard"] == survivor
                assert row["matches"], (tid, row, digests[tid])
            assert router.tenants()[orphans[0]] == survivor

            # second death, this time RESPAWNED in place: a fresh process
            # under the same shard id replays the orphans back to the same
            # placements, and the restart is counted
            router._handle(survivor).kill()
            router.check_shards()
            rehomed2 = router.rehome_tenants(survivor, respawn=True)
            assert sorted(rehomed2) == sorted(tenants)
            assert all(row["matches"] for row in rehomed2.values())
            assert all(row["shard"] == survivor for row in rehomed2.values())
            from karpenter_tpu import metrics as m

            restarts = sum(v for _labels, v in router.registry.counter(m.SOLVER_SHARD_RESTARTS_TOTAL).collect())
            assert restarts >= 1
            # a probe pass after the respawn re-admits the shard
            assert router.check_shards()[survivor] in ("probing", "healthy")
        finally:
            router.close()
