"""Daemonset overhead depth specs ported from the reference's provisioning
suite_test.go (:934-1495) — which daemons count against a candidate node, how
their overhead shapes instance selection, and taint/affinity interplay."""

import pytest

from helpers import make_nodepool, make_pod
from test_scheduler import LINUX_AMD64, build_env, make_scheduler
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.scheduling.taints import Taint
from karpenter_tpu.utils import resources as res


def solve(pods, daemons=(), node_pools=None, types=None, **kw):
    env = build_env(node_pools=node_pools, types=types)
    s = make_scheduler(*env, daemons=daemons, **kw)
    return s.solve(pods)


def daemon(cpu="500m", memory=None, node_selector=None, tolerations=None, required_affinity=None, preferred_affinity=None):
    return make_pod(
        cpu=cpu,
        memory=memory,
        node_selector=node_selector,
        tolerations=tolerations,
        required_affinity=required_affinity,
        preferred_affinity=preferred_affinity,
    )


def claim_fits_with(nc, extra):
    total = res.merge(res.requests_for_pods(nc.pods), extra)
    return [it for it in nc.instance_type_options if res.fits(total, it.allocatable())]


class TestDaemonOverheadDepth:
    def test_accounts_for_daemonsets(self):
        # :934 — every surviving instance type fits pods + daemon overhead
        d = daemon(cpu="1", memory="1Gi")
        results = solve([make_pod(cpu="1", memory="1Gi")], daemons=[d])
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert claim_fits_with(nc, res.pod_requests(d)) == nc.instance_type_options

    def test_too_large_daemonset_overhead_blocks(self):
        # :1003 — a daemon bigger than every instance type
        types = [catalog.make_instance_type("c", 4)]
        results = solve([make_pod(cpu="1")], daemons=[daemon(cpu="16")], types=types)
        assert len(results.pod_errors) == 1

    def test_ignores_daemonsets_without_matching_tolerations(self):
        # :1142 — tainted pool: an intolerant daemon won't run there, so its
        # overhead must NOT shrink the candidate's capacity
        np = make_nodepool(
            requirements=LINUX_AMD64,
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        )
        tol = [{"key": "dedicated", "operator": "Equal", "value": "x", "effect": "NoSchedule"}]
        types = [catalog.make_instance_type("c", 4)]  # ~3.9 allocatable
        # pod of 3 cpu + daemon of 2 would NOT fit; without the daemon it does
        results = solve(
            [make_pod(cpu="3", tolerations=tol)],
            daemons=[daemon(cpu="2")],  # no toleration: ignored
            node_pools=[np],
            types=types,
        )
        assert results.all_pods_scheduled()

    def test_tolerating_daemonset_counts_on_tainted_pool(self):
        np = make_nodepool(
            requirements=LINUX_AMD64,
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        )
        tol = [{"key": "dedicated", "operator": "Equal", "value": "x", "effect": "NoSchedule"}]
        types = [catalog.make_instance_type("c", 4)]
        results = solve(
            [make_pod(cpu="3", tolerations=tol)],
            daemons=[daemon(cpu="2", tolerations=tol)],
            node_pools=[np],
            types=types,
        )
        # 3 + 2 > 3.9 allocatable: unschedulable on the only type
        assert len(results.pod_errors) == 1

    def test_daemon_filtered_by_instance_type_requirements(self):
        # :1245 — a daemon pinned to arm64 doesn't burden amd64 candidates
        np = make_nodepool(requirements=[{"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]}])
        types = [
            catalog.make_instance_type("c", 4, arch="amd64"),
            catalog.make_instance_type("c", 4, arch="arm64"),
        ]
        results = solve(
            [make_pod(cpu="3", node_selector={wk.ARCH_LABEL_KEY: "amd64"})],
            daemons=[daemon(cpu="2", node_selector={wk.ARCH_LABEL_KEY: "arm64"})],
            node_pools=[np],
            types=types,
        )
        assert results.all_pods_scheduled()
        nc = results.new_node_claims[0]
        assert all(it.requirements.get(wk.ARCH_LABEL_KEY).has("amd64") for it in nc.instance_type_options)

    def test_daemon_nodeselector_matching_nodepool_counts(self):
        # :1218 — daemon selects a custom label the pool's template carries
        np = make_nodepool(requirements=LINUX_AMD64, labels={"team": "infra"})
        types = [catalog.make_instance_type("c", 4)]
        results = solve(
            [make_pod(cpu="3")],
            daemons=[daemon(cpu="2", node_selector={"team": "infra"})],
            node_pools=[np],
            types=types,
        )
        assert len(results.pod_errors) == 1  # daemon counts: 3+2 > 3.9

    def test_daemon_notin_unspecified_key_counts(self):
        # :1275 — NotIn on a key the pool doesn't define matches (absent ok)
        types = [catalog.make_instance_type("c", 4)]
        results = solve(
            [make_pod(cpu="3")],
            daemons=[daemon(cpu="2", required_affinity=[[{"key": "special", "operator": "NotIn", "values": ["never"]}]])],
            types=types,
        )
        assert len(results.pod_errors) == 1  # daemon counts

    def test_daemon_with_multiple_or_terms_schedulable(self):
        # :1370 — ANY satisfied OR-term makes the daemon count
        types = [catalog.make_instance_type("c", 4)]
        results = solve(
            [make_pod(cpu="3")],
            daemons=[
                daemon(
                    cpu="2",
                    required_affinity=[
                        [{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["s390x"]}],
                        [{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]}],
                    ],
                )
            ],
            types=types,
        )
        assert len(results.pod_errors) == 1  # second OR-term matches: counts

    def test_daemon_with_incompatible_preference_still_counts(self):
        # :1430 — preferences never exclude a daemon
        types = [catalog.make_instance_type("c", 4)]
        results = solve(
            [make_pod(cpu="3")],
            daemons=[daemon(cpu="2", preferred_affinity=[(10, [{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["mars"]}])])],
            types=types,
        )
        assert len(results.pod_errors) == 1

    def test_no_double_count_across_pods_on_one_claim(self):
        # :1958 — overhead applies once per node, not per pod
        types = [catalog.make_instance_type("c", 8)]  # ~7.9 allocatable
        d = daemon(cpu="1")
        results = solve([make_pod(cpu="3"), make_pod(cpu="3")], daemons=[d], types=types)
        assert results.all_pods_scheduled()
        # 3+3+1 = 7 <= 7.9: both pods share one claim
        assert len([nc for nc in results.new_node_claims if nc.pods]) == 1

    def test_api_claim_requests_include_daemon_overhead(self):
        # :1938 — the created NodeClaim's resource requests carry the overhead
        d = daemon(cpu="1", memory="1Gi")
        results = solve([make_pod(cpu="1", memory="1Gi")], daemons=[d])
        nc = results.new_node_claims[0]
        api = nc.to_api_node_claim()
        assert api.spec.resources.get("cpu").milli >= 2000


class TestDaemonHostPorts:
    def test_daemon_hostport_blocks_conflicting_pod(self):
        # suite_test.go:955 "should account for daemonset hostports" — a pod
        # sharing a host port with a compatible daemonset can NEVER schedule:
        # the daemon holds the port on every fresh node
        d = daemon(cpu="500m")
        d.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080}]
        pod = make_pod(cpu="1")
        pod.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080}]
        results = solve([pod], daemons=[d])
        assert not results.new_node_claims
        assert pod.key() in results.pod_errors

    def test_daemon_hostport_allows_disjoint_ports(self):
        d = daemon(cpu="500m")
        d.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080}]
        pod = make_pod(cpu="1")
        pod.spec.containers[0].ports = [{"containerPort": 9090, "hostPort": 9090}]
        results = solve([pod], daemons=[d])
        assert results.all_pods_scheduled()
