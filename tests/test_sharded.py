"""Sharded grouped pack scan: exact equivalence vs the single-device kernel.

The slot axis shards across an 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8); all cross-slot reductions in the
kernel are integer prefix-sums/sums, so the sharded result must be
BIT-IDENTICAL to the single-device result — not merely simulation-equivalent.
"""

import jax
import numpy as np
import pytest

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from test_solver import LINUX_AMD64, make_snapshot
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.models.scheduler_model import make_tensors
from karpenter_tpu.models.scheduler_model_grouped import build_items, make_item_tensors
from karpenter_tpu.parallel.sharded import (
    assert_sharded_equivalent,
    dryrun_step,
    make_mesh,
)

pytestmark = pytest.mark.heavy
from karpenter_tpu.solver.encode import encode
from karpenter_tpu.solver.tpu import TPUSolver
from karpenter_tpu.solver.validate import validate_results

OUT_NAMES = ("takes", "leftovers", "slot_basis", "slot_zoneset", "slot_rank", "open_count")


def assert_pack_equivalent(snap, mesh):
    enc = encode(snap)
    assert not enc.fallback_reasons, enc.fallback_reasons
    item_arrays, item_pods = build_items(enc)
    items = make_item_tensors(item_arrays)
    t = make_tensors(enc, with_pods=False)
    # raises unless every output is bit-identical to the single-device kernel
    sharded = assert_sharded_equivalent(t, items, mesh)
    return enc, sharded


def existing_node_snapshot(pods, types):
    """Snapshot with one existing zone-b node (so existing-slot prefill spans
    the sharded axis) built the same way test_solver's redistribution specs
    do."""
    from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_REGISTERED, NodeClaim
    from karpenter_tpu.kube import Node, ObjectMeta, Store
    from karpenter_tpu.kube.objects import NodeSpec, NodeStatus
    from karpenter_tpu.solver import SolverSnapshot
    from karpenter_tpu.state import Cluster
    from karpenter_tpu.state.informer import start_informers
    from karpenter_tpu.utils.clock import FakeClock
    from karpenter_tpu.utils.resources import parse_resource_list

    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    np_ = make_nodepool(requirements=LINUX_AMD64)
    store.create(np_)
    nc = NodeClaim(metadata=ObjectMeta(name="c1", labels={wk.NODEPOOL_LABEL_KEY: np_.metadata.name}))
    nc.status.provider_id = "kwok://n1"
    nc.status.conditions.set_true(COND_REGISTERED)
    nc.status.conditions.set_true(COND_INITIALIZED)
    store.create(nc)
    store.create(
        Node(
            metadata=ObjectMeta(
                name="n1",
                labels={
                    wk.NODEPOOL_LABEL_KEY: np_.metadata.name,
                    wk.HOSTNAME_LABEL_KEY: "n1",
                    wk.ZONE_LABEL_KEY: "test-zone-b",
                },
            ),
            spec=NodeSpec(provider_id="kwok://n1"),
            status=NodeStatus(
                capacity=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
                allocatable=parse_resource_list({"cpu": "8", "memory": "16Gi", "pods": "110"}),
            ),
        )
    )
    return SolverSnapshot(
        store=store,
        cluster=cluster,
        node_pools=[np_],
        instance_types={np_.metadata.name: types},
        state_nodes=cluster.nodes(),
        daemonset_pods=[],
        pods=pods,
        clock=clock,
    )


class TestShardedPackEquivalence:
    def test_zone_spread_and_anti_affinity(self):
        # the VERDICT r2 #1 'done' workload: zone spread + hostname
        # anti-affinity + plain pods, 8-device mesh
        sel = {"matchLabels": {"app": "db"}}
        web = {"matchLabels": {"app": "web"}}
        pods = (
            [make_pod(cpu="500m", labels={"app": "db"}, tsc=[zone_spread(1, sel)], anti_affinity=[hostname_anti_affinity(sel)]) for _ in range(6)]
            + [make_pod(cpu="1", labels={"app": "web"}, tsc=[zone_spread(2, web)]) for _ in range(17)]
            + [make_pod(cpu="2", memory="4Gi") for _ in range(9)]
        )
        enc, sharded = assert_pack_equivalent(make_snapshot(pods), make_mesh())
        # the workload actually schedules (this is not a vacuous comparison)
        assert int(np.asarray(sharded[1]).sum()) == 0, "no leftovers expected"

    def test_existing_nodes_span_shards(self):
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a"])]
        sel = {"matchLabels": {"app": "db"}}
        pods = [
            make_pod(cpu="500m", labels={"app": "db"}, tsc=[zone_spread(50, sel)], anti_affinity=[hostname_anti_affinity(sel)])
            for _ in range(8)
        ]
        assert_pack_equivalent(existing_node_snapshot(pods, types), make_mesh())

    @pytest.mark.parametrize("n_dev", [2, 3, 5, 8])
    def test_mesh_sizes_and_padding(self, n_dev):
        # non-power-of-two meshes exercise the slot-axis padding path
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(13)]
        mesh = make_mesh(jax.devices()[:n_dev])
        assert_pack_equivalent(make_snapshot(pods), mesh)

    def test_random_fuzz_equivalence(self):
        import random

        rng = random.Random(7)
        zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
        for trial in range(4):
            pods = []
            sel = {"matchLabels": {"app": f"a{trial}"}}
            for i in range(rng.randint(5, 40)):
                kind = rng.random()
                if kind < 0.3:
                    pods.append(make_pod(cpu=f"{rng.randint(1, 4)}", labels={"app": f"a{trial}"}, tsc=[zone_spread(rng.randint(1, 3), sel)]))
                elif kind < 0.5:
                    pods.append(make_pod(cpu="500m", node_selector={wk.ZONE_LABEL_KEY: rng.choice(zones)}))
                else:
                    pods.append(make_pod(cpu=f"{rng.randint(1, 7)}", memory=f"{rng.randint(1, 8)}Gi"))
            assert_pack_equivalent(make_snapshot(pods), make_mesh())


class TestShardedSolverEndToEnd:
    def test_tpu_solver_with_mesh_matches_unmeshed(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(12)] + [
            make_pod(cpu="3", memory="6Gi") for _ in range(7)
        ]
        meshed = TPUSolver(force=True, mesh=make_mesh())
        r_mesh = meshed.solve(make_snapshot(pods))
        assert meshed.last_backend == "tpu"
        plain = TPUSolver(force=True)
        r_plain = plain.solve(make_snapshot(pods))

        assert not validate_results(make_snapshot(pods), r_mesh)
        assert set(r_mesh.pod_errors) == set(r_plain.pod_errors) == set()
        assert len(r_mesh.new_node_claims) == len(r_plain.new_node_claims)
        assert sorted(len(nc.pods) for nc in r_mesh.new_node_claims) == sorted(len(nc.pods) for nc in r_plain.new_node_claims)

    def test_dryrun_step_runs_production_kernel(self):
        sel = {"matchLabels": {"app": "w"}}
        pods = [make_pod(cpu="1", labels={"app": "w"}, tsc=[zone_spread(1, sel)]) for _ in range(16)]
        snap = make_snapshot(pods)
        assignment = dryrun_step(encode(snap), make_mesh())
        assert assignment.shape[0] == 16
        assert (assignment >= 0).all()


class TestShardedPorts:
    def test_host_ports_equivalent_sharded(self):
        # port bitmask state is slot-sharded; results must stay bit-identical
        def ported(name):
            p = make_pod(cpu="100m", name=name)
            p.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080}]
            return p

        pods = [ported(f"hp{i}") for i in range(5)] + [make_pod(cpu="100m") for _ in range(7)]
        enc, sharded = assert_pack_equivalent(make_snapshot(pods), make_mesh())
        assert int(np.asarray(sharded[1]).sum()) == 0


class TestShardedAnneal:
    def test_sharded_chains_match_single_device(self):
        # chains are independent: the meshed run on the same keys must be
        # bit-identical per chain to the single-device vmap
        import jax

        from test_consolidation_tpu import build_fleet
        from karpenter_tpu.models.consolidation_model import anneal_chains
        from karpenter_tpu.parallel.sharded import anneal_sharded, make_mesh
        from karpenter_tpu.solver.consolidation import encode_candidates

        env = build_fleet(12)
        env.clock.step(40)
        env.nodeclaim_disruption.reconcile()
        cands = env.disruption.get_candidates()
        assert len(cands) >= 10
        its = env.cloud_provider.get_instance_types()
        t = encode_candidates(cands, its)
        mesh = make_mesh(jax.devices()[:8])
        key = jax.random.PRNGKey(7)
        xs_s, ss_s = anneal_sharded(t, key, mesh, n_chains=32)
        keys = jax.random.split(key, 32)
        xs_1, ss_1 = anneal_chains(t, keys)
        assert np.array_equal(np.asarray(xs_s), np.asarray(xs_1))
        assert np.array_equal(np.asarray(ss_s), np.asarray(ss_1))

    def test_sharded_proposals_profitable(self):
        import jax

        from test_consolidation_tpu import build_fleet
        from karpenter_tpu.parallel.sharded import anneal_sharded, make_mesh
        from karpenter_tpu.solver.consolidation import encode_candidates

        env = build_fleet(10)
        env.clock.step(40)
        env.nodeclaim_disruption.reconcile()
        cands = env.disruption.get_candidates()
        its = env.cloud_provider.get_instance_types()
        t = encode_candidates(cands, its)
        mesh = make_mesh(jax.devices()[:4])
        _, scores = anneal_sharded(t, jax.random.PRNGKey(0), mesh, n_chains=16)
        assert (np.asarray(scores) > 0).any(), "idle fleet must yield profitable subsets"


class TestShardedAtScale:
    def test_ten_thousand_pod_sharded_pack(self):
        # VERDICT r3 #10: sharded evidence at a scale that would motivate the
        # growth path — 10k pods on the 8-device CPU mesh, bit-identical to
        # the single-device kernel
        import jax

        from bench import build_snapshot
        from karpenter_tpu.solver.encode import encode
        from karpenter_tpu.parallel.sharded import dryrun_step, make_mesh

        snap = build_snapshot(10_000, 60)
        enc = encode(snap)
        assert not enc.fallback_reasons
        mesh = make_mesh(jax.devices()[:8])
        assignment = dryrun_step(enc, mesh)  # raises unless sharded == single
        assert (np.asarray(assignment) >= 0).all()
