"""Performance suite: scale-out / consolidation / spread scenarios with
regression thresholds.

Reference: test/suites/performance/*.go — each scenario drives the full
control plane (provision -> launch -> register -> bind -> disrupt) against the
KWOK provider and asserts wall-clock + shape thresholds. Thresholds are
overridable via the KARPENTER_PERF_THRESHOLDS env var (JSON mapping scenario
-> {max_wall_seconds, ...}), mirroring thresholds.go:27-80.

Wall-clock numbers here bound the in-process control plane's real compute
(solver + controllers) — there is no apiserver latency, so they are far
tighter than the reference's kind-cluster budgets.
"""

from __future__ import annotations

import json
import os
import time

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.operator import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.testing import Monitor

import pytest

pytestmark = pytest.mark.heavy

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]

# Reference scales AND reference wall budgets (the reference's are kind+KWOK
# budgets with a real apiserver; this in-process substrate has no network, so
# staying inside them is the *minimum* bar — the per-scenario numbers printed
# on pass are the real regression signal).
THRESHOLDS = {
    "basic_scale_out": {"max_wall_seconds": 120.0, "pods": 1000},  # basic_test.go:36-59 (<2 min)
    "basic_consolidation": {"max_wall_seconds": 1200.0, "pods": 1000, "scale_to": 700},  # basic_test.go:67-81 (<20 min)
    "wide_deployments": {"max_wall_seconds": 300.0, "deployments": 30, "pods_each": 30},  # wide_deployments_test.go:177-185 (<5 min)
    "hostname_spreading": {"max_wall_seconds": 300.0, "pods": 1000},  # host_name_spreading_test.go:59-67 (<5 min)
    "hostname_spreading_xl": {"max_wall_seconds": 2100.0, "pods": 2000},  # host_name_spreading_xl_test.go:40-67 (<35 min)
    "interference": {"max_wall_seconds": 300.0, "pods": 1000},  # interference_test.go:58-66 (<5 min)
    "drift_replacement": {"max_wall_seconds": 3000.0, "pods": 600},  # drift_performance_test.go:61-96 (<50 min)
}
_overrides = os.environ.get("KARPENTER_PERF_THRESHOLDS")
if _overrides:
    for k, v in json.loads(_overrides).items():
        THRESHOLDS.setdefault(k, {}).update(v)


def make_env(**kw):
    env = Environment(options=Options(**kw))
    env.store.create(make_nodepool(requirements=LINUX_AMD64))
    return env, Monitor(env.store, env.cluster)


def settle_until(env, pred, max_rounds=60, step=5.0):
    for _ in range(max_rounds):
        env.clock.step(step)
        env.tick(provision_force=True)
        if pred():
            return True
    return pred()


class TestBasicScaleOut:
    def test_1000_pods(self):
        """performance/basic_test.go:36-59 — two deployments, 1000 pods."""
        t = THRESHOLDS["basic_scale_out"]
        env, monitor = make_env()
        n = t["pods"]
        for i in range(n // 2):
            env.store.create(make_pod(cpu="500m", memory="512Mi", name=f"a-{i}", labels={"app": "a"}))
        for i in range(n // 2):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"b-{i}", labels={"app": "b"}))
        start = time.perf_counter()
        ok = settle_until(env, lambda: monitor.pending_pod_count() == 0)
        wall = time.perf_counter() - start
        assert ok, f"{monitor.pending_pod_count()} pods still pending"
        assert monitor.running_pod_count() == n
        assert wall < t["max_wall_seconds"], f"scale-out took {wall:.1f}s"
        print(f"\nbasic_scale_out({n}): {wall:.1f}s")
        # capacity should be reasonably packed, not one node per pod
        assert monitor.avg_utilization("cpu") > 0.5, monitor.avg_utilization("cpu")

    def test_basic_consolidation(self):
        """basic_test.go:67-81 — scale down 30%, nodes shrink. Instance sizes
        are capped so the fleet is wide enough for consolidation to matter."""
        t = THRESHOLDS["basic_consolidation"]
        n, keep = t["pods"], t["scale_to"]
        env = Environment(options=Options())
        env.store.create(
            make_nodepool(
                requirements=LINUX_AMD64
                + [{"key": "karpenter.kwok.sh/instance-size", "operator": "In", "values": ["4x", "8x"]}]
            )
        )
        monitor = Monitor(env.store, env.cluster)
        for i in range(n):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"p-{i}", labels={"app": "a"}))
        assert settle_until(env, lambda: monitor.pending_pod_count() == 0)
        nodes_before = monitor.node_count()
        # scale down 30% (basic_test.go:67-81)
        for i in range(keep, n):
            env.store.delete("Pod", f"p-{i}")
        start = time.perf_counter()
        settle_until(env, lambda: monitor.node_count() < nodes_before, max_rounds=40, step=20.0)
        wall = time.perf_counter() - start
        assert monitor.node_count() < nodes_before, "consolidation never shrank the cluster"
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == keep
        assert wall < t["max_wall_seconds"], f"consolidation took {wall:.1f}s"
        print(f"\nbasic_consolidation: {wall:.1f}s ({nodes_before}->{monitor.node_count()} nodes)")


class TestWideDeployments:
    def test_many_deployments(self):
        """wide_deployments_test.go — N deployments with distinct constraints."""
        t = THRESHOLDS["wide_deployments"]
        env, monitor = make_env()
        zones = ["test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d"]
        total = 0
        for d in range(t["deployments"]):
            sel = {"matchLabels": {"app": f"d{d}"}}
            for i in range(t["pods_each"]):
                env.store.create(
                    make_pod(
                        cpu="500m",
                        memory="512Mi",
                        name=f"d{d}-{i}",
                        labels={"app": f"d{d}"},
                        node_selector={wk.ZONE_LABEL_KEY: zones[d % 4]} if d % 2 == 0 else None,
                        tsc=[zone_spread(selector=sel)] if d % 2 == 1 else None,
                    )
                )
                total += 1
        start = time.perf_counter()
        ok = settle_until(env, lambda: monitor.pending_pod_count() == 0)
        wall = time.perf_counter() - start
        assert ok and monitor.running_pod_count() == total
        assert wall < t["max_wall_seconds"], f"took {wall:.1f}s"
        print(f"\nwide_deployments({total}): {wall:.1f}s")


class TestHostnameSpreading:
    def test_one_pod_per_node(self):
        """host_name_spreading_test.go — anti-affinity forces 1 pod/node."""
        t = THRESHOLDS["hostname_spreading"]
        env, monitor = make_env()
        sel = {"matchLabels": {"app": "spread"}}
        for i in range(t["pods"]):
            env.store.create(
                make_pod(cpu="100m", name=f"s-{i}", labels={"app": "spread"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        start = time.perf_counter()
        ok = settle_until(env, lambda: monitor.pending_pod_count() == 0, max_rounds=80)
        wall = time.perf_counter() - start
        assert ok
        assert monitor.node_count() >= t["pods"]  # one node per pod
        assert wall < t["max_wall_seconds"], f"took {wall:.1f}s"
        print(f"\nhostname_spreading({t['pods']}): {wall:.1f}s")

    def test_one_pod_per_node_xl(self):
        """host_name_spreading_xl_test.go:40-67 — 2,000 anti-affinity pods
        through the FULL control plane (provision -> launch -> register ->
        bind), one node per pod, inside the reference's 35-minute budget."""
        t = THRESHOLDS["hostname_spreading_xl"]
        env, monitor = make_env()
        sel = {"matchLabels": {"app": "spread-xl"}}
        for i in range(t["pods"]):
            env.store.create(
                make_pod(cpu="100m", name=f"x-{i}", labels={"app": "spread-xl"}, anti_affinity=[hostname_anti_affinity(sel)])
            )
        start = time.perf_counter()
        ok = settle_until(env, lambda: monitor.pending_pod_count() == 0, max_rounds=120)
        wall = time.perf_counter() - start
        assert ok, f"{monitor.pending_pod_count()} pods still pending after {wall:.1f}s"
        assert monitor.node_count() >= t["pods"]
        assert wall < t["max_wall_seconds"], f"took {wall:.1f}s"
        print(f"\nhostname_spreading_xl({t['pods']}): {wall:.1f}s")


class TestInterference:
    def test_anti_affinity_interference(self):
        """interference_test.go — a spread workload interleaved with bulk pods."""
        t = THRESHOLDS["interference"]
        env, monitor = make_env()
        sel = {"matchLabels": {"app": "aa"}}
        for i in range(10):
            env.store.create(make_pod(cpu="100m", name=f"aa-{i}", labels={"app": "aa"}, anti_affinity=[hostname_anti_affinity(sel)]))
        for i in range(t["pods"]):
            env.store.create(make_pod(cpu="500m", memory="512Mi", name=f"bulk-{i}"))
        start = time.perf_counter()
        ok = settle_until(env, lambda: monitor.pending_pod_count() == 0)
        wall = time.perf_counter() - start
        assert ok and monitor.running_pod_count() == t["pods"] + 10
        assert wall < t["max_wall_seconds"], f"took {wall:.1f}s"
        print(f"\ninterference({t['pods']}): {wall:.1f}s")


class TestDriftReplacement:
    def test_drift_rolls_fleet(self):
        """drift_performance_test.go — template change replaces all capacity
        while keeping pods running."""
        t = THRESHOLDS["drift_replacement"]
        env, monitor = make_env()
        for i in range(t["pods"]):
            env.store.create(make_pod(cpu="1", memory="1Gi", name=f"p-{i}", labels={"app": "drift"}))
        assert settle_until(env, lambda: monitor.pending_pod_count() == 0)
        before = {n.metadata.name for n in env.store.list("Node")}
        np = env.store.list("NodePool")[0]
        np.spec.template.labels = {"roll": "v2"}
        env.store.update(np)
        start = time.perf_counter()
        settle_until(
            env,
            lambda: not ({n.metadata.name for n in env.store.list("Node")} & before)
            and monitor.pending_pod_count() == 0,
            max_rounds=250,
            step=15.0,
        )
        wall = time.perf_counter() - start
        after = {n.metadata.name for n in env.store.list("Node")}
        assert not (after & before), "old nodes still present after drift roll"
        assert monitor.pending_pod_count() == 0
        assert monitor.running_pod_count() == t["pods"]
        assert wall < t["max_wall_seconds"], f"drift roll took {wall:.1f}s"
        print(f"\ndrift_replacement({t['pods']}): {wall:.1f}s")


class TestFFDThroughputFloor:
    def test_ffd_1k_pods_meets_reference_floor(self):
        """The host FFD path (the tensor solver's fallback) must clear the
        reference's asserted scheduler floor of 100 pods/sec
        (scheduling_benchmark_test.go:58) on the heterogeneous benchmark
        workload."""
        from bench import bench_ffd

        pods_per_sec = bench_ffd(1000)
        assert pods_per_sec >= 100, f"FFD at {pods_per_sec:.0f} pods/s is below the 100 pods/s floor"
