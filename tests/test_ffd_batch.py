"""Signature-batched host FFD (KARPENTER_FFD_BATCH, scheduler.py fit memo +
placement cursors + PodData template cache + incremental claim ordering).

The contract under test: placements are BIT-IDENTICAL between the batched
(=1, default) and exact-reference (=0) paths across every scenario family —
the memo may only skip work whose outcome is provably monotone within the
solve. Plus targeted memo-soundness cases (capacity rejections stay
permanent, topology skew changes are still re-evaluated) and the queue
cycle-detection regression for twice-relaxed pods.
"""

import copy
import random

from helpers import hostname_anti_affinity, make_nodepool, make_pod, zone_spread
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.controllers.provisioning.scheduling import Scheduler
from karpenter_tpu.controllers.provisioning.scheduling.nodeclaim import _reqs_content_key
from karpenter_tpu.controllers.provisioning.scheduling.queue import Queue
from karpenter_tpu.kube import Store
from karpenter_tpu.kube.objects import Node, NodeSpec, NodeStatus, ObjectMeta, TopologySpreadConstraint
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.resources import parse_resource_list

LINUX_AMD64 = [
    {"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
    {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]},
]


def build_env(node_pools=None, types=None, nodes=()):
    store = Store()
    clock = FakeClock()
    cluster = Cluster(store, clock)
    start_informers(store, cluster)
    node_pools = node_pools if node_pools is not None else [make_nodepool(requirements=LINUX_AMD64)]
    for np in node_pools:
        store.create(np)
    for n in nodes:
        store.create(n)
    types = types if types is not None else catalog.construct_instance_types()
    return store, clock, cluster, node_pools, types


def make_scheduler(store, clock, cluster, node_pools, types, ffd_batch, daemons=(), **kw):
    return Scheduler(
        store,
        cluster,
        node_pools,
        {np.metadata.name: types for np in node_pools},
        cluster.nodes(),
        list(daemons),
        clock,
        ffd_batch=ffd_batch,
        **kw,
    )


def unowned_node(name, zone="test-zone-a", cpu="16", memory="32Gi"):
    return Node(
        metadata=ObjectMeta(name=name, labels={wk.HOSTNAME_LABEL_KEY: name, wk.ZONE_LABEL_KEY: zone}),
        spec=NodeSpec(provider_id=f"byo://{name}"),
        status=NodeStatus(
            capacity=parse_resource_list({"cpu": cpu, "memory": memory, "pods": "110"}),
            allocatable=parse_resource_list({"cpu": cpu, "memory": memory, "pods": "110"}),
        ),
    )


def placements_key(results):
    """Everything scheduling-relevant in a Results, hostile to incidental
    ordering but exact on placements: pod->existing-node assignment, and per
    claim the pod set, pool, option set, and requirement CONTENT (hostname
    placeholders and claim names are run-unique by construction)."""
    existing = {en.name(): tuple(sorted(p.metadata.name for p in en.pods)) for en in results.existing_nodes if en.pods}
    claims = sorted(
        (
            tuple(sorted(p.metadata.name for p in nc.pods)),
            nc.nodepool_name,
            tuple(sorted(it.name for it in nc.instance_type_options)),
            _reqs_content_key(nc.requirements),
        )
        for nc in results.new_node_claims
    )
    return existing, claims


def run_pair(pods, node_pools=None, types=None, nodes=(), **kw):
    """Solve the same scenario with KARPENTER_FFD_BATCH off and on; assert
    bit-identical Results; return (off, on, batched_scheduler)."""
    env = build_env(node_pools, types, nodes)
    s_off = make_scheduler(*env, ffd_batch=False, **kw)
    r_off = s_off.solve(pods)
    s_on = make_scheduler(*env, ffd_batch=True, **kw)
    r_on = s_on.solve(pods)
    assert placements_key(r_off) == placements_key(r_on)
    assert r_off.pod_errors == r_on.pod_errors
    assert r_off.pending_pods_by_effective_zone == r_on.pending_pods_by_effective_zone
    assert r_off.timed_out == r_on.timed_out
    return r_off, r_on, s_on


ZONE_B_TERM = [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-b"]}]
ZONE_C_TERM = [{"key": wk.ZONE_LABEL_KEY, "operator": "In", "values": ["test-zone-c"]}]


class TestParityFamilies:
    def test_mixed_replicas(self):
        pods = []
        for shape in (("1", "1Gi"), ("2", "4Gi"), ("500m", "512Mi")):
            pods += [make_pod(cpu=shape[0], memory=shape[1]) for _ in range(12)]
        _, r_on, s = run_pair(pods)
        assert r_on.all_pods_scheduled()
        assert s.memo_stats["miss"] > 0  # replicas rode the batched path

    def test_replicas_fill_claims_and_hit_memo(self):
        # a single 16-cpu type caps each claim at two 7-cpu pods: full claims
        # become permanent capacity rejections that later replicas skip
        types = [catalog.make_instance_type("c", 16)]
        pods = [make_pod(cpu="7") for _ in range(10)]
        _, r_on, s = run_pair(pods, types=types)
        assert r_on.all_pods_scheduled()
        assert len(r_on.new_node_claims) == 5
        assert s.memo_stats["hit"] > 0

    def test_zone_spread_replicas(self):
        sel = {"matchLabels": {"app": "web"}}
        pods = [make_pod(cpu="1", memory="1Gi", labels={"app": "web"}, tsc=[zone_spread(selector=sel)]) for _ in range(18)]
        pods += [make_pod(cpu="2", memory="2Gi") for _ in range(6)]
        _, r_on, _ = run_pair(pods)
        assert r_on.all_pods_scheduled()

    def test_hostname_topology(self):
        sel = {"matchLabels": {"app": "db"}}
        host_tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL_KEY, when_unsatisfiable="DoNotSchedule", label_selector=sel
        )
        pods = [make_pod(cpu="1", labels={"app": "db"}, tsc=[host_tsc]) for _ in range(6)]
        pods += [
            make_pod(cpu="500m", labels={"app": "anti"}, anti_affinity=[hostname_anti_affinity({"matchLabels": {"app": "anti"}})])
            for _ in range(4)
        ]
        _, r_on, _ = run_pair(pods)
        assert r_on.all_pods_scheduled()

    def test_host_ports_bypass(self):
        pods = []
        for i in range(6):
            p = make_pod(cpu="1")
            p.spec.containers[0].ports = [{"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}]
            pods.append(p)
        pods += [make_pod(cpu="1") for _ in range(6)]
        _, r_on, s = run_pair(pods)
        assert r_on.all_pods_scheduled()
        # port pods bypass the memo entirely
        assert all(s._sig_by_uid[p.metadata.uid] is None for p in pods[:6])

    def test_min_values_best_effort_and_strict(self):
        reqs = LINUX_AMD64 + [{"key": wk.INSTANCE_TYPE_LABEL_KEY, "operator": "Exists", "minValues": 2}]
        for policy in ("BestEffort", "Strict"):
            pods = [make_pod(cpu="1", memory="1Gi") for _ in range(10)]
            _, r_on, _ = run_pair(
                pods, node_pools=[make_nodepool(requirements=reqs)], min_values_policy=policy
            )
            assert r_on.all_pods_scheduled()

    def test_reserved_offerings(self):
        types = catalog.construct_instance_types(include_reserved=True)
        for mode in ("fallback", "strict"):
            pods = [make_pod(cpu="1") for _ in range(8)]
            _, r_on, _ = run_pair(pods, types=types, reserved_offering_mode=mode)
            assert r_on.all_pods_scheduled()

    def test_existing_nodes_and_cursor(self):
        nodes = [unowned_node(f"byo-{i}", zone="test-zone-a", cpu="4") for i in range(4)]
        pods = [make_pod(cpu="3") for _ in range(8)]  # one per node, rest overflow
        _, r_on, s = run_pair(pods, nodes=nodes)
        assert r_on.all_pods_scheduled()
        landed = sum(1 for en in r_on.existing_nodes for _ in en.pods)
        assert landed == 4
        # the per-signature cursor advanced past the exhausted node prefix
        assert any(c > 0 for c in s._existing_cursor.values())

    def test_unschedulable_pods_error_parity(self):
        pods = [make_pod(cpu="500") for _ in range(3)] + [make_pod(cpu="1") for _ in range(3)]
        r_off, r_on, _ = run_pair(pods)
        assert len(r_on.pod_errors) == 3
        assert r_off.pod_errors == r_on.pod_errors  # exact strings, not just keys

    def test_relaxation_rekeys_memo(self):
        # preferred zone-c affinity is unsatisfiable (no zone-c offering in the
        # catalog subset) — the pod relaxes, and the relaxed signature must be
        # tracked separately from the strict one
        types = [catalog.make_instance_type("c", 8, zones=["test-zone-a", "test-zone-b"])]
        pods = [make_pod(cpu="1", preferred_affinity=[(1, ZONE_C_TERM)]) for _ in range(5)]
        _, r_on, _ = run_pair(pods, types=types)
        assert r_on.all_pods_scheduled()


class TestRandomizedParity:
    def _random_pods(self, rng, n):
        spread_sel = {"matchLabels": {"app": "web"}}
        anti_sel = {"matchLabels": {"app": "db"}}
        pods = []
        for _ in range(n):
            k = rng.random()
            if k < 0.30:  # replica shapes
                cpu, mem = rng.choice([("1", "1Gi"), ("2", "2Gi"), ("500m", "512Mi")])
                pods.append(make_pod(cpu=cpu, memory=mem))
            elif k < 0.45:  # zone spread
                pods.append(make_pod(cpu="1", memory="1Gi", labels={"app": "web"}, tsc=[zone_spread(selector=spread_sel)]))
            elif k < 0.55:  # zone node selector
                pods.append(make_pod(cpu="1", node_selector={wk.ZONE_LABEL_KEY: rng.choice(["test-zone-a", "test-zone-b"])}))
            elif k < 0.65:  # hostname anti-affinity
                pods.append(make_pod(cpu="500m", labels={"app": "db"}, anti_affinity=[hostname_anti_affinity(anti_sel)]))
            elif k < 0.75:  # preferred zone affinity (relaxation candidates)
                pods.append(make_pod(cpu="1", preferred_affinity=[(2, ZONE_B_TERM)]))
            elif k < 0.85:  # host ports (memo bypass)
                p = make_pod(cpu="500m")
                p.spec.containers[0].ports = [{"containerPort": 80, "hostPort": 8000 + rng.randrange(4), "protocol": "TCP"}]
                pods.append(p)
            elif k < 0.93:  # heterogeneous one-offs
                pods.append(make_pod(cpu=f"{rng.randrange(1, 7)}", memory=f"{rng.randrange(1, 8)}Gi"))
            else:  # unschedulable
                pods.append(make_pod(cpu="500"))
        return pods

    def test_randomized_mixes(self):
        for seed in range(5):
            rng = random.Random(seed)
            pods = self._random_pods(rng, 60)
            nodes = [unowned_node(f"byo-{seed}-{i}", zone=rng.choice(["test-zone-a", "test-zone-b"]), cpu="8") for i in range(3)]
            reserved = seed % 2 == 1
            types = catalog.construct_instance_types(include_reserved=reserved)
            run_pair(
                pods,
                types=types,
                nodes=nodes,
                min_values_policy=rng.choice(["Strict", "BestEffort"]),
                reserved_offering_mode="strict" if reserved else "fallback",
            )


class TestMemoSoundness:
    def test_capacity_rejection_is_permanent_but_exact(self):
        # 3 identical 3-cpu pods against one 4-cpu node: the first lands, the
        # second's "exceeds node resources" is memoized, the third must skip
        # the node via the memo — and still open claims exactly like the
        # reference path
        nodes = [unowned_node("small", cpu="4")]
        pods = [make_pod(cpu="3") for _ in range(3)]
        _, r_on, s = run_pair(pods, nodes=nodes)
        assert r_on.all_pods_scheduled()
        landed = [p.metadata.name for en in r_on.existing_nodes for p in en.pods]
        assert len(landed) == 1
        assert s.memo_stats["hit"] >= 1

    def test_topology_skew_still_reevaluated(self):
        # zone spread maxSkew=1 over two existing nodes: a node that rejects a
        # pod for skew must ACCEPT a later identical pod once counts rebalance
        # — a memoized topology rejection would starve node-a
        nodes = [unowned_node("node-a", zone="test-zone-a", cpu="64"), unowned_node("node-b", zone="test-zone-b", cpu="64")]
        sel = {"matchLabels": {"app": "web"}}
        # restrict the offering universe to the two node zones so the spread's
        # domain min tracks the nodes (a third empty zone would pin min at 0)
        types = [catalog.make_instance_type("c", 16, zones=["test-zone-a", "test-zone-b"])]
        pods = [make_pod(cpu="1", labels={"app": "web"}, tsc=[zone_spread(selector=sel)]) for _ in range(6)]
        _, r_on, _ = run_pair(pods, nodes=nodes, types=types)
        assert r_on.all_pods_scheduled()
        counts = {en.name(): len(en.pods) for en in r_on.existing_nodes}
        assert counts.get("node-a", 0) == 3 and counts.get("node-b", 0) == 3

    def test_claim_version_invalidates_pass_entries(self):
        # alternating signatures landing on the same claim force pass-entry
        # invalidation (the claim's version moves under the memo)
        pods = []
        for _ in range(8):
            pods.append(make_pod(cpu="1", memory="1Gi"))
            pods.append(make_pod(cpu="1", memory="2Gi"))
        _, r_on, s = run_pair(pods)
        assert r_on.all_pods_scheduled()
        assert s.memo_stats["invalidate"] >= 1

    def test_memo_cap_clearing_preserves_parity(self, monkeypatch):
        # a tiny cap forces mid-solve memo clears: verdicts must re-derive
        # identically (clearing forgets, never corrupts — cursors included)
        from karpenter_tpu.controllers.provisioning.scheduling import scheduler as sched_mod

        monkeypatch.setattr(sched_mod, "_FIT_MEMO_MAX", 4)
        types = [catalog.make_instance_type("c", 16)]
        pods = [make_pod(cpu="7") for _ in range(10)] + [make_pod(cpu="3") for _ in range(6)]
        nodes = [unowned_node("cap-node", cpu="4")]
        _, r_on, s = run_pair(pods, types=types, nodes=nodes)
        assert r_on.all_pods_scheduled()
        assert len(s._fit_memo) <= 4

    def test_pod_data_template_cache_shares_entries(self):
        pods = [make_pod(cpu="1") for _ in range(10)]
        _, _, s = run_pair(pods)
        datas = {id(s.cached_pod_data[p.metadata.uid]) for p in pods}
        assert len(datas) == 1  # one PodData template for ten replicas


class TestObservability:
    def test_memo_counter_and_phase_histogram(self):
        from karpenter_tpu import metrics as m

        registry = m.make_registry()
        env = build_env()
        pods = [make_pod(cpu="7") for _ in range(6)]
        s = make_scheduler(*env, ffd_batch=True, registry=registry)
        s.solve(pods)
        memo = registry.counter(m.SOLVER_FFD_MEMO_TOTAL)
        assert memo.value(kind="miss") == s.memo_stats["miss"] > 0
        assert memo.value(kind="hit") == s.memo_stats["hit"]
        assert memo.value(kind="invalidate") == s.memo_stats["invalidate"]
        phases = registry.histogram(m.SOLVER_FFD_PHASE_SECONDS)
        for phase in ("existing", "inflight", "new_claim"):
            assert phases._totals[(("phase", phase),)] == 1  # one solve observed


class TestGate:
    def test_env_gate(self, monkeypatch):
        env = build_env()
        monkeypatch.setenv("KARPENTER_FFD_BATCH", "0")
        assert make_scheduler(*env, ffd_batch=None).batch_enabled is False
        monkeypatch.setenv("KARPENTER_FFD_BATCH", "1")
        assert make_scheduler(*env, ffd_batch=None).batch_enabled is True
        monkeypatch.delenv("KARPENTER_FFD_BATCH")
        assert make_scheduler(*env, ffd_batch=None).batch_enabled is True  # default-on


class TestQueueCycleRegression:
    def test_uid_survives_deepcopy(self):
        pod = make_pod(cpu="1")
        assert copy.deepcopy(pod).metadata.uid == pod.metadata.uid

    def test_twice_relaxed_pod_terminates(self):
        # impossible node selector + two preferred affinity terms: every
        # _try_schedule relaxes twice on a deepcopy, the ORIGINAL pod is
        # re-queued, and the uid-keyed cycle detection must stop the queue
        # instead of spinning (ISSUE 5 satellite)
        pod = make_pod(
            cpu="1",
            node_selector={wk.ZONE_LABEL_KEY: "no-such-zone"},
            preferred_affinity=[(2, ZONE_B_TERM), (1, ZONE_C_TERM)],
        )
        for batch in (False, True):
            env = build_env()
            s = make_scheduler(*env, ffd_batch=batch)
            results = s.solve([pod])
            assert pod.key() in results.pod_errors
            assert not results.timed_out

    def test_queue_stops_without_progress(self):
        pods = [make_pod(cpu="1"), make_pod(cpu="1")]
        data = {p.metadata.uid: type("D", (), {"requests": {}})() for p in pods}
        q = Queue(pods, data)
        a = q.pop()
        q.push(a)
        b = q.pop()
        q.push(b)
        assert q.pop() is None  # full cycle, no progress
