"""Native requirements kernel: exact-parity fuzz vs the Python algebra."""

import random

import pytest

from karpenter_tpu import native
from karpenter_tpu.scheduling.requirements import Requirement, Requirements

pytestmark = pytest.mark.skipif(not native.available(), reason=f"native kernel unavailable: {native.load_error()}")

KEYS = ["zone", "arch", "size", "cpu", "custom/a", "custom/b"]
VALUES = ["a", "b", "c", "1", "2", "16", "999", "x"]
OPS = ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt", "Gte", "Lte"]


def random_requirements(rng, max_reqs=4) -> Requirements:
    reqs = Requirements()
    for key in rng.sample(KEYS, rng.randrange(1, max_reqs + 1)):
        op = rng.choice(OPS)
        if op in ("Gt", "Lt", "Gte", "Lte"):
            vals = [str(rng.randrange(0, 50))]
        elif op in ("Exists", "DoesNotExist"):
            vals = []
        else:
            vals = rng.sample(VALUES, rng.randrange(1, 4))
        reqs.add(Requirement(key, op, vals))
    return reqs


class TestParity:
    def test_fuzz_matches_python_intersects(self):
        rng = random.Random(1234)
        rows = [random_requirements(rng) for _ in range(200)]
        table = native.ReqTable(rows)
        for _ in range(100):
            query = random_requirements(rng)
            mask = table.filter(query)
            for i, row in enumerate(rows):
                expected = row.intersects(query) is None
                assert bool(mask[i]) == expected, (
                    f"row {i}: native={bool(mask[i])} python={expected}\nrow={row}\nquery={query}"
                )

    def test_catalog_vs_pod_requirements(self):
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.cloudprovider import catalog

        its = catalog.construct_instance_types()
        table = native.ReqTable([it.requirements for it in its])
        query = Requirements()
        query.add(Requirement(wk.ARCH_LABEL_KEY, "In", ["amd64"]))
        query.add(Requirement(wk.OS_LABEL_KEY, "In", ["linux"]))
        query.add(Requirement(catalog.INSTANCE_CPU_LABEL_KEY, "Gt", ["8"]))
        mask = table.filter(query)
        for i, it in enumerate(its):
            assert bool(mask[i]) == (it.requirements.intersects(query) is None), it.name

    def test_unseen_query_values(self):
        rows = [Requirements()]
        rows[0].add(Requirement("zone", "In", ["a", "b"]))
        table = native.ReqTable(rows)
        q = Requirements()
        q.add(Requirement("zone", "In", ["never-interned"]))
        assert table.filter(q) == b"\x00"
        q2 = Requirements()
        q2.add(Requirement("zone", "NotIn", ["never-interned"]))
        assert table.filter(q2) == b"\x01"

    def test_two_negatives_never_conflict(self):
        rows = [Requirements()]
        rows[0].add(Requirement("k", "NotIn", ["x"]))
        # Gt MaxInt canonicalizes to an empty In (matches nothing) but is
        # still non-negative; a DoesNotExist query against NotIn passes
        table = native.ReqTable(rows)
        q = Requirements()
        q.add(Requirement("k", "DoesNotExist"))
        assert table.filter(q) == b"\x01"


class TestSchedulerUsesNative:
    def test_ffd_solve_matches_with_and_without_native(self):
        import os
        import subprocess
        import sys

        script = r"""
import sys; sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/root/repo/tests")
import random
from helpers import make_nodepool, make_pod
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider import catalog
from karpenter_tpu.kube import Store
from karpenter_tpu.solver import FFDSolver, SolverSnapshot
from karpenter_tpu.state import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import FakeClock

LINUX = [{"key": wk.ARCH_LABEL_KEY, "operator": "In", "values": ["amd64"]},
         {"key": wk.OS_LABEL_KEY, "operator": "In", "values": ["linux"]}]
store, clock = Store(), FakeClock()
cluster = Cluster(store, clock); start_informers(store, cluster)
np_ = make_nodepool(requirements=LINUX); store.create(np_)
rng = random.Random(5)
pods = [make_pod(cpu=rng.choice(["500m","1","2"]), memory="1Gi",
                 node_selector={wk.ZONE_LABEL_KEY: rng.choice(catalog.ZONES)} if rng.random() < 0.3 else None)
        for _ in range(120)]
for i, p in enumerate(pods):
    p.metadata.uid = f"uid-{i:04d}"  # deterministic FFD tie-breaks across processes
from karpenter_tpu.cloudprovider.fake import instance_types_assorted
types = instance_types_assorted(400)  # above NATIVE_MIN_TABLE_ROWS so the kernel engages
snap = SolverSnapshot(store=store, cluster=cluster, node_pools=[np_],
    instance_types={np_.metadata.name: types},
    state_nodes=[], daemonset_pods=[], pods=pods, clock=clock)
r = FFDSolver().solve(snap)
assert r.all_pods_scheduled()
print(len(r.new_node_claims), sorted(len(nc.pods) for nc in r.new_node_claims))
"""
        outs = []
        for disable in ("", "1"):
            env = dict(os.environ, JAX_PLATFORMS="cpu", KARPENTER_DISABLE_NATIVE=disable)
            p = subprocess.run([sys.executable, "-c", script], env=env, capture_output=True, text=True, timeout=300)
            assert p.returncode == 0, p.stdout + p.stderr
            outs.append(p.stdout.strip().splitlines()[-1])
        assert outs[0] == outs[1], f"native={outs[0]} python={outs[1]}"
